"""Multi-controller pod scale-out (ISSUE 9): process-topology mesh
helpers, the multi-process-safe reshard count exchange + capacity cache,
process-scoped journals, whole-host loss, the multi-host ingest wiring —
and the REAL 2-process jax.distributed CPU dryrun proving 1-process vs
2-process bit-identity for all four sharded drivers."""

import os
import tempfile

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import ingest, input_validators
from pipelinedp_tpu.parallel import make_mesh
from pipelinedp_tpu.parallel import mesh as mesh_lib
from pipelinedp_tpu.parallel import reshard
from pipelinedp_tpu.runtime import faults as rt_faults
from pipelinedp_tpu.runtime import journal as rt_journal
from pipelinedp_tpu.runtime import multihost
from pipelinedp_tpu.runtime import retry as rt_retry
from pipelinedp_tpu.runtime import telemetry as rt_telemetry

pytestmark = pytest.mark.multihost


class FakeDevice:
    """Stand-in for a remote jax device: id + owning process."""

    def __init__(self, id_, process_index):
        self.id = id_
        self.process_index = process_index

    def __repr__(self):
        return f"FakeDevice(id={self.id}, p={self.process_index})"


# ---------------------------------------------------------------------------
# Mesh process-topology helpers
# ---------------------------------------------------------------------------


class TestMeshHelpers:

    def test_single_process_topology(self):
        mesh = make_mesh(n_devices=4)
        assert mesh_lib.process_index() == 0
        assert mesh_lib.process_count() == 1
        assert mesh_lib.is_fully_addressable(mesh)
        assert mesh_lib.local_devices(mesh) == list(mesh.devices.flat)
        assert mesh_lib.mesh_processes(mesh) == [0]
        assert mesh_lib.cross_process_fraction(mesh) == 0.0

    def test_cross_process_fraction_counts_dcn_pairs(self):
        # 2 processes x 2 devices: of the 12 ordered pairs, 8 cross.
        devs = [FakeDevice(i, i // 2) for i in range(4)]

        class M:
            pass

        mesh = M()
        import numpy as np_
        mesh.devices = np_.asarray(devs, dtype=object)
        assert mesh_lib.cross_process_fraction(mesh) == pytest.approx(
            8 / 12)
        assert mesh_lib.mesh_processes(mesh) == [0, 1]

    def test_device_process_defaults_to_zero(self):
        assert mesh_lib.device_process(object()) == 0


# ---------------------------------------------------------------------------
# Liveness probe: remote devices, heartbeat, whole-host faults
# ---------------------------------------------------------------------------


class TestRemoteLiveness:

    def test_schedule_is_the_remote_oracle(self):
        remote = [FakeDevice(100, 1), FakeDevice(101, 1),
                  FakeDevice(102, 2)]
        schedule = rt_faults.FaultSchedule(
            [rt_faults.Fault("device_loss", process=1)])
        schedule.note_device_loss(schedule._remaining[0][0])
        with rt_faults.inject(schedule):
            live = mesh_lib.probe_live_devices(remote)
        # Process 1's devices are lost wholesale; process 2's survive.
        assert [d.id for d in live] == [102]

    def test_heartbeat_decides_without_schedule(self):
        remote = [FakeDevice(100, 1), FakeDevice(101, 1)]
        live = mesh_lib.probe_live_devices(
            remote, heartbeat=lambda devs: set(devs))
        assert live == remote
        # A failing heartbeat conservatively loses every remote device.
        def broken(devs):
            raise RuntimeError("DCN unreachable")
        assert mesh_lib.probe_live_devices(remote, heartbeat=broken) == []

    def test_heartbeat_partial_answer(self):
        remote = [FakeDevice(100, 1), FakeDevice(101, 2)]
        live = mesh_lib.probe_live_devices(
            remote, heartbeat=lambda devs: {devs[0]})
        assert [d.id for d in live] == [100]

    def test_local_devices_still_round_trip(self):
        import jax
        devices = jax.devices()[:2]
        live = mesh_lib.probe_live_devices(devices)
        assert live == list(devices)

    def test_whole_host_fault_validation(self):
        with pytest.raises(ValueError, match="device_loss"):
            rt_faults.Fault("oom", process=1)
        with pytest.raises(ValueError, match="mutually exclusive"):
            rt_faults.Fault("device_loss", device=3, process=1)

    def test_assign_lost_covers_whole_process(self):
        devs = [FakeDevice(i, i // 2) for i in range(6)]
        schedule = rt_faults.FaultSchedule(
            [rt_faults.Fault("device_loss", process=2)])
        schedule.note_device_loss(schedule._remaining[0][0])
        assert schedule.assign_lost(devs) == {4, 5}

    def test_host_evacuated_is_mesh_degradation(self):
        assert issubclass(rt_retry.HostEvacuatedError,
                          rt_retry.MeshDegradationError)


# ---------------------------------------------------------------------------
# Reshard: capacity cache + multi-process-safe count exchange
# ---------------------------------------------------------------------------


def _reshard_data(n=10_000, n_ids=700, seed=0):
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n_ids, n).astype(np.int32)
    pk = rng.integers(0, 50, n).astype(np.int32)
    values = rng.uniform(0, 5, n).astype(np.float32)
    valid = rng.random(n) >= 0.1
    return (tuple(jnp.asarray(c) for c in (pid, pk, values, valid)),
            (pid, pk, values, valid))


class TestCapacityCache:

    def setup_method(self):
        reshard.reset_capacity_cache()

    def _multiset(self, cols):
        rp, rk, rv, rva = map(np.asarray, cols)
        return sorted(zip(rp[rva].tolist(), rk[rva].tolist(),
                          rv[rva].tolist()))

    def test_repeat_geometry_reuses_capacity(self):
        mesh = make_mesh(n_devices=8)
        dev, (pid, pk, values, valid) = _reshard_data()
        before = rt_telemetry.snapshot().get("reshard_capacity_reuse", 0)
        # First exchange: cold cache. Second: same geometry, must reuse —
        # and the transfer guard proves the whole path (including the
        # NEW on-device-reduced count exchange) moves no rows to host.
        with reshard.forbid_row_fetches():
            out1 = reshard.device_reshard_rows_by_pid(mesh, *dev)
        with reshard.forbid_row_fetches():
            out2 = reshard.device_reshard_rows_by_pid(mesh, *dev)
        after = rt_telemetry.snapshot().get("reshard_capacity_reuse", 0)
        assert after == before + 1
        expected = sorted(zip(pid[valid].tolist(), pk[valid].tolist(),
                              values[valid].tolist()))
        assert self._multiset(out1) == expected
        assert self._multiset(out2) == expected

    def test_overflow_redispatches_exactly(self):
        # Same padded geometry, then a pathological distribution (every
        # row on one privacy id -> one bucket holds everything): the
        # cached capacity no longer fits, the exchange re-dispatches at
        # the exact capacity, no reuse is counted, no row is lost.
        mesh = make_mesh(n_devices=8)
        dev, (pid, pk, values, valid) = _reshard_data()
        reshard.device_reshard_rows_by_pid(mesh, *dev)
        import jax.numpy as jnp
        hot = (jnp.zeros(len(pid), jnp.int32), dev[1], dev[2], dev[3])
        before = rt_telemetry.snapshot().get("reshard_capacity_reuse", 0)
        out = reshard.device_reshard_rows_by_pid(mesh, *hot)
        after = rt_telemetry.snapshot().get("reshard_capacity_reuse", 0)
        assert after == before
        rva = np.asarray(out[3])
        assert rva.sum() == valid.sum()

    def test_distinct_geometry_is_a_miss(self):
        mesh = make_mesh(n_devices=8)
        dev, _ = _reshard_data()
        reshard.device_reshard_rows_by_pid(mesh, *dev)
        smaller, _ = _reshard_data(n=4_000, seed=1)
        before = rt_telemetry.snapshot().get("reshard_capacity_reuse", 0)
        reshard.device_reshard_rows_by_pid(mesh, *smaller)
        assert rt_telemetry.snapshot().get("reshard_capacity_reuse",
                                           0) == before

    def test_count_stats_replicated_and_correct(self):
        import jax
        mesh = make_mesh(n_devices=8)
        dev, (pid, pk, values, valid) = _reshard_data()
        from pipelinedp_tpu.parallel.mesh import rows_per_shard
        per_in = rows_per_shard(len(pid), 8)
        cols = reshard._pad_and_shard(mesh, per_in, *dev)
        stats = reshard._count_stats_kernel(cols[0], cols[3], 8, 0, mesh)
        assert isinstance(stats, jax.Array)
        assert stats.sharding.is_fully_replicated
        max_send, max_recv, total = (int(x) for x in np.asarray(stats))
        assert total == int(valid.sum())
        assert 0 < max_send <= max_recv <= total


# ---------------------------------------------------------------------------
# Journal: (job_id, process_index) scoping
# ---------------------------------------------------------------------------


def _record(v):
    return rt_journal.BlockRecord(ids=np.asarray([v], np.int64),
                                  outputs={"count": np.asarray([v * 2.0])})


class TestProcessScopedJournal:

    def test_two_processes_share_a_directory_without_collision(self):
        with tempfile.TemporaryDirectory() as tmp:
            j0 = rt_journal.BlockJournal(tmp).scoped_to_process(0)
            j1 = rt_journal.BlockJournal(tmp).scoped_to_process(1)
            j0.put("job", "0:128", _record(10))
            j1.put("job", "0:128", _record(20))
            assert int(j0.get("job", "0:128").ids[0]) == 10
            assert int(j1.get("job", "0:128").ids[0]) == 20
            # Distinct files on disk, each scope listing only its own.
            names = sorted(os.listdir(tmp))
            assert [n for n in names if "__p0__" in n]
            assert [n for n in names if "__p1__" in n]
            assert list(j0.keys("job")) == ["0:128"]
            assert list(j1.keys("job")) == ["0:128"]

    def test_cross_process_replay_is_impossible(self):
        with tempfile.TemporaryDirectory() as tmp:
            j0 = rt_journal.BlockJournal(tmp, process_index=0)
            j0.put("job", "0:128", _record(10))
            # A FRESH process-1 journal over the same directory must not
            # see (or replay) process 0's record.
            j1 = rt_journal.BlockJournal(tmp, process_index=1)
            assert j1.get("job", "0:128") is None
            assert list(j1.keys("job")) == []

    def test_quarantine_stays_within_its_process(self):
        with tempfile.TemporaryDirectory() as tmp:
            j0 = rt_journal.BlockJournal(tmp, process_index=0)
            j1 = rt_journal.BlockJournal(tmp, process_index=1)
            j0.put("job", "0:128", _record(10))
            j1.put("job", "0:128", _record(20))
            # Corrupt process 0's record ON DISK; drop its memory cache.
            path = j0._path("job", "0:128")
            with open(path, "r+b") as f:
                f.seek(-8, os.SEEK_END)
                f.write(b"\x00" * 8)
            fresh0 = rt_journal.BlockJournal(tmp, process_index=0)
            fresh1 = rt_journal.BlockJournal(tmp, process_index=1)
            assert fresh0.get("job", "0:128") is None  # quarantined
            got = fresh1.get("job", "0:128")
            assert got is not None and int(got.ids[0]) == 20

    def test_unscoped_journal_ignores_scoped_records(self):
        with tempfile.TemporaryDirectory() as tmp:
            j1 = rt_journal.BlockJournal(tmp, process_index=1)
            j1.put("job", "0:128", _record(20))
            plain = rt_journal.BlockJournal(tmp)
            assert plain.get("job", "0:128") is None
            assert list(plain.keys("job")) == []

    def test_rescoping_rules(self):
        j = rt_journal.BlockJournal(process_index=2)
        assert j.scoped_to_process(2) is j
        with pytest.raises(ValueError, match="alias"):
            j.scoped_to_process(3)

    def test_entry_scopes_journal_on_multicontroller_mesh(self,
                                                          monkeypatch):
        # Force the entry wrapper to see a "multi-controller" mesh and
        # check the journal it hands the driver is process-scoped.
        from pipelinedp_tpu.runtime import entry as rt_entry
        monkeypatch.setattr(mesh_lib, "is_fully_addressable",
                            lambda mesh: False)
        monkeypatch.setattr(mesh_lib, "process_index", lambda: 1)
        seen = {}

        @rt_entry.runtime_entry("probe",
                                fallback=lambda args, kwargs, job: None)
        def fake_driver(mesh, *args, journal=None, job_id=None, **kw):
            seen["journal"] = journal
            return np.zeros(4, bool)

        journal = rt_journal.BlockJournal()
        fake_driver(make_mesh(n_devices=2), journal=journal)
        assert seen["journal"].process_index == 1
        # The single-controller path leaves the journal untouched.
        monkeypatch.setattr(mesh_lib, "is_fully_addressable",
                            lambda mesh: True)
        fake_driver(make_mesh(n_devices=2), journal=journal)
        assert seen["journal"] is journal


# ---------------------------------------------------------------------------
# Multi-host ingest: shard-encoded codes == serial codes
# ---------------------------------------------------------------------------


class TestMultihostIngest:

    def _stream(self, n=2500, seed=3):
        rng = np.random.default_rng(seed)
        pids = np.char.add("u", rng.integers(0, 300, n).astype(str))
        pks = np.char.add("p", rng.integers(0, 25, n).astype(str))
        vals = rng.integers(0, 10, n).astype(np.float64)
        return pids, pks, vals

    def _chunks(self, pids, pks, vals, lo, hi, chunk=400):
        return [(pids[i:min(i + chunk, hi)], pks[i:min(i + chunk, hi)],
                 vals[i:min(i + chunk, hi)])
                for i in range(lo, hi, chunk)]

    def test_shard_encoded_codes_equal_serial_stream_encode(self):
        pids, pks, vals = self._stream()
        n = len(pids)
        half = n // 2
        shard0 = ingest.encode_shard(
            iter(self._chunks(pids, pks, vals, 0, half)))
        shard1 = ingest.encode_shard(
            iter(self._chunks(pids, pks, vals, half, n)))
        metas = [
            ingest._ShardMeta(len(s.pid), np.asarray(s.pid_vocab),
                              np.asarray(s.pk_vocab))
            for s in (shard0, shard1)
        ]
        pid_remaps, pk_remaps, pid_vocab, pk_vocab = \
            ingest.merge_shard_metas(metas, public=False)
        merged_pid = np.concatenate([
            pid_remaps[0][shard0.pid], pid_remaps[1][shard1.pid]])
        merged_pk = np.concatenate([
            pk_remaps[0][shard0.pk], pk_remaps[1][shard1.pk]])
        serial = ingest.stream_encode_columns(
            iter(self._chunks(pids, pks, vals, 0, n)))
        assert np.array_equal(merged_pid, np.asarray(serial.pid)), (
            "shard-encoded pid codes != serial stream_encode_columns")
        assert np.array_equal(merged_pk, np.asarray(serial.pk)), (
            "shard-encoded pk codes != serial stream_encode_columns")
        assert list(pk_vocab) == list(serial.partition_vocab)
        assert len(pid_vocab) == serial.n_privacy_ids

    def test_encode_local_shard_to_mesh_single_process(self):
        pids, pks, vals = self._stream(n=1200)
        mesh = make_mesh(n_devices=4)
        encoded = ingest.encode_local_shard_to_mesh(
            iter(self._chunks(pids, pks, vals, 0, len(pids))), mesh)
        serial = ingest.stream_encode_columns(
            iter(self._chunks(pids, pks, vals, 0, len(pids))))
        valid = np.asarray(encoded.pk) >= 0
        assert valid.sum() == len(pids)
        assert np.array_equal(np.asarray(encoded.pid)[valid],
                              np.asarray(serial.pid))
        assert np.array_equal(np.asarray(encoded.pk)[valid],
                              np.asarray(serial.pk))
        assert list(encoded.partition_vocab) == \
            list(serial.partition_vocab)

    def test_simulated_pod_exchange(self, monkeypatch):
        # Two simulated processes share one exchange: each side encodes
        # only its shard, and the injected exchange returns both
        # payloads in process order.
        import pickle
        pids, pks, vals = self._stream(n=1600)
        n = len(pids)
        half = n // 2
        payloads = {}
        for p, (lo, hi) in enumerate([(0, half), (half, n)]):
            shard = ingest.encode_shard(
                iter(self._chunks(pids, pks, vals, lo, hi)))
            payloads[p] = pickle.dumps(
                ingest._ShardMeta(len(shard.pid),
                                  np.asarray(shard.pid_vocab),
                                  np.asarray(shard.pk_vocab)))
        mesh = make_mesh(n_devices=4)
        exchange = lambda payload: [payloads[0], payloads[1]]  # noqa: E731
        encoded0 = ingest.encode_local_shard_to_mesh(
            iter(self._chunks(pids, pks, vals, 0, half)), mesh,
            exchange=exchange)
        serial = ingest.stream_encode_columns(
            iter(self._chunks(pids, pks, vals, 0, n)))
        valid = np.asarray(encoded0.pk) >= 0
        # Process 0 (the only real process here) uploaded its own half;
        # its codes must be the serial stream's first-half codes.
        assert np.array_equal(np.asarray(encoded0.pid)[valid],
                              np.asarray(serial.pid)[:half])
        assert np.array_equal(np.asarray(encoded0.pk)[valid],
                              np.asarray(serial.pk)[:half])
        # And the vocabularies are the GLOBAL merge, not the local half.
        assert list(encoded0.partition_vocab) == \
            list(serial.partition_vocab)
        assert encoded0.n_privacy_ids == serial.n_privacy_ids


# ---------------------------------------------------------------------------
# Validators + backend knobs
# ---------------------------------------------------------------------------


class TestMultihostKnobs:

    def test_validate_num_processes(self):
        input_validators.validate_num_processes(1, "t")
        input_validators.validate_num_processes(16, "t")
        for bad in (0, -1, 1.5, True, "2", None):
            with pytest.raises(ValueError, match="num_processes"):
                input_validators.validate_num_processes(bad, "t")

    def test_validate_coordinator_address(self):
        input_validators.validate_coordinator_address("10.0.0.1:1234", "t")
        input_validators.validate_coordinator_address("host:65535", "t")
        for bad in ("", None, 7, "hostonly", ":123", "host:0",
                    "host:notaport", "host:70000"):
            with pytest.raises(ValueError, match="coordinator_address"):
                input_validators.validate_coordinator_address(bad, "t")

    def test_backend_validates_multihost_knobs(self):
        with pytest.raises(ValueError, match="num_processes"):
            pdp.TPUBackend(coordinator_address="h:1", num_processes=0)
        with pytest.raises(ValueError, match="coordinator_address"):
            pdp.TPUBackend(coordinator_address="bogus", num_processes=2)
        with pytest.raises(ValueError, match="together"):
            pdp.TPUBackend(num_processes=2)
        with pytest.raises(ValueError, match="together"):
            pdp.TPUBackend(coordinator_address="h:1")
        # num_processes=1: validated, accepted, and no distributed
        # bring-up is attempted (the backend stays single-process).
        backend = pdp.TPUBackend(coordinator_address="127.0.0.1:1",
                                 num_processes=1)
        assert backend.num_processes == 1
        assert mesh_lib.process_count() == 1

    def test_health_snapshot_carries_process_index(self):
        from pipelinedp_tpu.runtime import health as rt_health
        snap = rt_health.for_job("mh-probe").snapshot()
        assert snap["process_index"] == 0


class TestMultihostReceipt:

    def test_receipt_keys(self):
        receipt = multihost.multihost_receipt(make_mesh(n_devices=4))
        assert receipt["multihost_processes"] == 1
        assert receipt["multihost_local_devices"] == 4
        assert receipt["multihost_mesh_devices"] == 4
        assert receipt["multihost_per_process_ingest_overlap"] == 1
        assert receipt["multihost_cross_host_fraction"] == 0.0
        assert receipt["multihost_cross_host_exchange_bytes"] == 0


# ---------------------------------------------------------------------------
# The 2-process jax.distributed dryrun gate
# ---------------------------------------------------------------------------


class TestTwoProcessPod:

    # `slow`: ~52s of pod spawn + 4-driver sweep. The identity pod gate
    # still runs on every dryrun (__graft_entry__._dryrun_multihost_pod)
    # and tier-1 keeps a real 2-process spawn via
    # test_two_process_whole_host_loss (~16s).
    @pytest.mark.slow
    @pytest.mark.hard_timeout(360)
    def test_two_process_bit_identity_all_four_drivers(self, tmp_path):
        """2 controllers x 2 CPU devices == 1 controller x 4 devices,
        bitwise, for aggregate/select x dense/blocked + the engine over
        the multi-host ingest path, with equal budget-ledger counts and
        process-scoped journals sharing one directory."""
        results = multihost.spawn_local_pod("identity", str(tmp_path),
                                            timeout_s=300)
        reference = multihost.reference_identity_outputs()
        msg = multihost.check_identity_results(results, reference)
        assert "bit-identical" in msg
        # The merged observability rollup over the same pod: both
        # controllers' spans on distinct pid tracks, parseable mid-run
        # scrapes, incident instants exactly once per recorder.
        obs_msg = multihost.check_pod_observability(
            str(tmp_path), results, "identity")
        assert "pod rollup merged 2 controllers" in obs_msg
        names = sorted(n for n in os.listdir(tmp_path / "journal")
                       if n.endswith(".npz"))
        p0 = [n for n in names if "__p0__" in n]
        p1 = [n for n in names if "__p1__" in n]
        assert p0 and len(p0) == len(p1), names
        assert len(p0) + len(p1) == len(names), (
            f"unscoped journal records in a pod directory: {names}")
        assert not any(n.endswith(".corrupt") for n in names)

    @pytest.mark.hard_timeout(360)
    def test_two_process_whole_host_loss(self, tmp_path):
        """Whole-host loss mid-run: the surviving controller rebuilds
        the mesh over its own devices and finishes bit-identically to a
        fault-free run (DEGRADED health, mesh_degradations+host_losses
        incremented, journaled blocks replayed); the lost controller
        evacuates via HostEvacuatedError."""
        results = multihost.spawn_local_pod("host_loss", str(tmp_path),
                                            timeout_s=300)
        reference = multihost.reference_host_loss_outputs()
        msg = multihost.check_host_loss_results(results, reference)
        assert "bit-identically" in msg
        # Injected host-loss incidents appear EXACTLY ONCE per
        # recording controller in the merged trace (no double-count
        # from per-process buffers).
        obs_msg = multihost.check_pod_observability(
            str(tmp_path), results, "host_loss")
        assert "host_losses" in obs_msg


# ---------------------------------------------------------------------------
# Fleet operations on the REAL 2-process pod (slow: each scenario is a
# full jax.distributed spawn; the fast in-process siblings live in
# tests/test_fleet.py and run in tier-1)
# ---------------------------------------------------------------------------


class TestFleetPodScenarios:

    @pytest.mark.slow
    @pytest.mark.hard_timeout(360)
    def test_two_process_elastic_grow(self, tmp_path):
        """Scale-UP on the real pod: both controllers start on HALF
        their devices, announce the rest as join candidates at block 2,
        grow to the full mesh mid-run and finish bit-identically to the
        full-geometry reference (journaled blocks replayed, zero
        degradations)."""
        results = multihost.spawn_local_pod("grow", str(tmp_path),
                                            timeout_s=300)
        reference = multihost.reference_host_loss_outputs()
        msg = multihost.check_grow_results(results, reference)
        assert "bit-identically" in msg

    @pytest.mark.slow
    @pytest.mark.hard_timeout(360)
    def test_two_process_drain_and_migrate(self, tmp_path):
        """Drain-and-migrate across pods: the 2-process pod's journaled
        job is interrupted mid-run (both controllers persist their
        odometer trails), then THIS process — a different pod at a
        different geometry (8 devices) — adopts the records and resumes
        bit-identically to an uninterrupted run."""
        results = multihost.spawn_local_pod("migrate_source",
                                            str(tmp_path), timeout_s=300)
        journal_dir = str(tmp_path / "journal")
        adopted, adopted_odo, resumed = multihost.run_migration_target(
            journal_dir, n_devices=8)
        reference = multihost.reference_host_loss_outputs()
        msg = multihost.check_migration_results(
            results, adopted, adopted_odo, resumed, reference)
        assert "bit-identically" in msg

    @pytest.mark.slow
    @pytest.mark.hard_timeout(600)
    def test_two_process_rolling_restart_drill(self, tmp_path):
        """The pod rolling-restart drill: two full controller
        generations over one shared ledger directory (a jax.distributed
        world is fixed at init, so a bounced controller IS a respawned
        process), generation 1 taking the scripted mid-persist kill on
        p1. Gates: bit-identical traffic every generation, every
        planned job charged exactly once on BOTH controller trails,
        total spend bit-equal."""
        state = tmp_path / "state"
        out = tmp_path / "out"
        state.mkdir()
        out.mkdir()
        all_results = multihost.run_pod_drill(str(state), str(out),
                                              generations=2,
                                              timeout_s=280)
        reference = multihost.reference_drill_outputs()
        msg = multihost.check_pod_drill_results(all_results, str(state),
                                                reference)
        assert "charged exactly once" in msg
