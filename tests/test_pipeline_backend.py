"""Per-op backend semantics matrix (reference: tests/pipeline_backend_test.py).

One behavioral contract, asserted across every backend that can execute in
this environment (LocalBackend, MultiProcLocalBackend, TPUBackend's generic
op path). Beam/Spark adapters are exercised by tests/test_private_apis.py
via fake runners.
"""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, pipeline_backend, pipeline_functions


def _local():
    return pdp.LocalBackend(seed=0)


def _multiproc():
    return pdp.MultiProcLocalBackend(n_jobs=2)


def _tpu_generic():
    # TPUBackend inherits the generic op vocabulary; the fused path only
    # takes over inside DPEngine.aggregate.
    return pdp.TPUBackend(noise_seed=0)


BACKENDS = [_local, _multiproc, _tpu_generic]
BACKEND_IDS = ["local", "multiproc", "tpu-generic"]


@pytest.fixture(params=BACKENDS, ids=BACKEND_IDS)
def backend(request):
    return request.param()


class TestElementwiseOps:

    def test_map_empty(self, backend):
        assert list(backend.map([], lambda x: x / 0, "map")) == []

    def test_map(self, backend):
        assert list(backend.map([1, 2, 3], str, "map")) == ["1", "2", "3"]
        assert list(backend.map(range(5), lambda x: x**2,
                                "map")) == [0, 1, 4, 9, 16]

    def test_map_with_side_inputs(self, backend):
        if isinstance(backend, pdp.MultiProcLocalBackend):
            pytest.skip("side inputs not supported on multiproc")
        got = backend.map_with_side_inputs([1, 2],
                                           lambda x, l1, l2: [x] + l1 + l2,
                                           [[3, 4, 5], [6]], "side")
        assert list(got) == [[1, 3, 4, 5, 6], [2, 3, 4, 5, 6]]

    def test_flat_map(self, backend):
        assert list(backend.flat_map([[1, 2], [3]], lambda x: x,
                                     "fm")) == [1, 2, 3]
        pairs = [("a", [1, 2]), ("b", [3])]
        assert list(
            backend.flat_map(pairs, lambda kv: [(kv[0], v) for v in kv[1]],
                             "fm")) == [("a", 1), ("a", 2), ("b", 3)]

    def test_flat_map_empty_inner(self, backend):
        assert list(backend.flat_map([[], [], [7]], lambda x: x, "fm")) == [7]

    def test_map_tuple(self, backend):
        data = [(1, 2), (2, 3), (3, 4)]
        assert list(backend.map_tuple(data, lambda k, v: k + v,
                                      "mt")) == [3, 5, 7]
        assert list(backend.map_tuple(data, lambda k, v: (str(k), str(v)),
                                      "mt")) == [("1", "2"), ("2", "3"),
                                                 ("3", "4")]

    def test_map_values(self, backend):
        assert list(backend.map_values([], lambda x: x / 0, "mv")) == []
        data = [(1, 2), (2, 3), (3, 4)]
        assert list(backend.map_values(data, lambda x: x**2,
                                       "mv")) == [(1, 4), (2, 9), (3, 16)]

    def test_filter(self, backend):
        assert list(backend.filter([], lambda x: True, "f")) == []
        data = [1, 2, 2, 3, 3, 4, 2]
        assert list(backend.filter(data, lambda x: x % 2, "f")) == [1, 3, 3]
        assert list(backend.filter(data, lambda x: x < 3,
                                   "f")) == [1, 2, 2, 2]

    def test_keys_values(self, backend):
        data = [(1, 2), (2, 3), (3, 4), (4, 8)]
        assert list(backend.keys([], "k")) == []
        assert list(backend.keys(data, "k")) == [1, 2, 3, 4]
        assert list(backend.values([], "v")) == []
        assert list(backend.values(data, "v")) == [2, 3, 4, 8]


class TestKeyedOps:

    def test_group_by_key(self, backend):
        data = [("cheese", "brie"), ("bread", "sourdough"),
                ("cheese", "swiss")]
        got = {k: sorted(v) for k, v in backend.group_by_key(data, "g")}
        assert got == {
            "cheese": ["brie", "swiss"],
            "bread": ["sourdough"],
        }

    def test_group_by_key_unhashable_values_ok(self, backend):
        data = [(1, [1, 2]), (1, [3])]
        got = dict(backend.group_by_key(data, "g"))
        assert sorted(got[1]) == [[1, 2], [3]]

    def test_filter_by_key_empty_keys(self, backend):
        col = [(7, 1), (2, 1), (3, 9)]
        assert list(backend.filter_by_key(col, [], "fbk")) == []

    def test_filter_by_key(self, backend):
        col = [(7, 1), (2, 1), (3, 9), (4, 1), (9, 10)]
        got = sorted(backend.filter_by_key(col, [7, 9], "fbk"))
        assert got == [(7, 1), (9, 10)]

    def test_filter_by_key_none_raises_or_keeps_nothing(self, backend):
        # keys_to_keep must be a collection; None is a misuse.
        col = [(1, 1)]
        with pytest.raises(TypeError):
            list(backend.filter_by_key(col, None, "fbk"))

    def test_count_per_element(self, backend):
        data = [1, 2, 3, 4, 5, 6, 1, 4, 0, 1]
        assert dict(backend.count_per_element(data, "c")) == {
            1: 3, 2: 1, 3: 1, 4: 2, 5: 1, 6: 1, 0: 1}

    def test_sum_per_key(self, backend):
        data = [(1, 2), (2, 1), (1, 4), (3, 8), (2, -3), (10, 5)]
        got = sorted(backend.sum_per_key(data, "s"))
        assert got == [(1, 6), (2, -2), (3, 8), (10, 5)]

    def test_reduce_per_key(self, backend):
        data = [(1, 2), (2, 1), (1, 4), (3, 8), (2, 3)]
        got = sorted(backend.reduce_per_key(data, lambda x, y: x + y, "r"))
        assert got == [(1, 6), (2, 4), (3, 8)]

    def test_combine_accumulators_per_key(self, backend):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=10)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1e6,
                                               total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, accountant)
        accountant.compute_budgets()
        data = [(1, [1, 1]), (1, [1]), (2, [1])]
        col = backend.map_values(data, compound.create_accumulator, "acc")
        col = backend.combine_accumulators_per_key(col, compound, "comb")
        # row_count counts merged (pid, pk) accumulators — the
        # privacy-unit count partition selection consumes.
        got = {k: acc[0] for k, acc in col}
        assert got == {1: 2, 2: 1}


class TestCollectionOps:

    def test_flatten(self, backend):
        got = list(backend.flatten(([1, 2], [3], [4, 5]), "fl"))
        assert sorted(got) == [1, 2, 3, 4, 5]

    def test_flatten_with_empty(self, backend):
        assert sorted(backend.flatten(([], [1], []), "fl")) == [1]

    def test_distinct(self, backend):
        data = [3, 2, 1, 3, 5, 4, 1, 1, 2]
        assert set(backend.distinct(data, "d")) == {1, 2, 3, 4, 5}

    def test_to_list(self, backend):
        got = list(backend.to_list([1, 2, 3], "tl"))
        assert len(got) == 1
        assert sorted(got[0]) == [1, 2, 3]

    def test_to_multi_transformable_collection(self, backend):
        col = backend.to_multi_transformable_collection(iter([1, 2, 3]))
        assert list(backend.map(col, lambda x: x, "m1")) == [1, 2, 3]
        assert list(backend.map(col, lambda x: x, "m2")) == [1, 2, 3]


class TestSampling:

    def test_sample_fixed_per_key_no_discard_below_cap(self, backend):
        data = [("pid1", ("pk1", 1)), ("pid1", ("pk2", 1)),
                ("pid1", ("pk3", 1)), ("pid2", ("pk4", 1))]
        got = {k: sorted(v) for k, v in
               backend.sample_fixed_per_key(data, 3, "s")}
        assert got == {
            "pid1": [("pk1", 1), ("pk2", 1), ("pk3", 1)],
            "pid2": [("pk4", 1)],
        }

    def test_sample_fixed_per_key_caps(self, backend):
        data = [(("pid1", "pk1"), 1)] * 5 + [(("pid1", "pk2"), 1)] * 2
        got = dict(backend.sample_fixed_per_key(data, 3, "s"))
        assert len(got[("pid1", "pk1")]) == 3
        assert len(got[("pid1", "pk2")]) == 2
        # Sampled values are a subset of the input values.
        assert set(got[("pid1", "pk1")]) == {1}

    def test_sample_fixed_per_key_is_uniform_ish(self):
        # Statistical: sampling 1 of [0..3] many times covers all values.
        backend = pdp.LocalBackend(seed=None)
        seen = set()
        for _ in range(200):
            data = [("k", v) for v in range(4)]
            got = dict(backend.sample_fixed_per_key(data, 1, "s"))
            seen.add(got["k"][0])
        assert seen == {0, 1, 2, 3}


class TestLaziness:
    """Local ops must not consume their input at graph-build time."""

    @staticmethod
    def _poison():
        yield 1 / 0

    @pytest.mark.parametrize("op", [
        lambda b, c: b.map(c, str, "m"),
        lambda b, c: b.map_values(c, str, "mv"),
        lambda b, c: b.filter(c, bool, "f"),
        lambda b, c: b.values(c, "v"),
        lambda b, c: b.keys(c, "k"),
        lambda b, c: b.count_per_element(c, "c"),
        lambda b, c: b.sum_per_key(c, "s"),
        lambda b, c: b.flat_map(c, str, "fm"),
        lambda b, c: b.sample_fixed_per_key(c, 2, "sf"),
        lambda b, c: b.filter_by_key(c, [1], "fbk"),
        lambda b, c: b.distinct(c, "d"),
        lambda b, c: b.group_by_key(c, "g"),
        lambda b, c: b.reduce_per_key(c, lambda x, y: x, "r"),
    ])
    def test_op_is_lazy(self, op):
        backend = pdp.LocalBackend()
        op(backend, self._poison())  # must not raise at build time
        with pytest.raises(ZeroDivisionError):
            list(op(backend, self._poison()))


class TestPipelineFunctions:

    def test_key_by(self):
        backend = pdp.LocalBackend()
        got = list(
            pipeline_functions.key_by(backend, [1, 2, 3], lambda x: x % 2,
                                      "kb"))
        assert sorted(got) == [(0, 2), (1, 1), (1, 3)]

    def test_size(self):
        backend = pdp.LocalBackend()
        assert list(pipeline_functions.size(backend, [5, 6, 7], "sz")) == [3]

    def test_min_max_elements(self):
        backend = pdp.LocalBackend()
        got = list(
            pipeline_functions.min_max_elements(backend, [3, 1, 4, 1, 5],
                                                "mm"))
        assert got == [(1, 5)]

    def test_collect_to_container(self):
        import dataclasses

        @dataclasses.dataclass
        class Box:
            total: int
            items: list

        backend = pdp.LocalBackend()
        got = list(
            pipeline_functions.collect_to_container(
                backend, {
                    "total": backend.to_list([3], "t"),
                    "items": backend.to_list([1, 2], "i"),
                }, Box, "collect"))
        assert len(got) == 1
        assert got[0].total == [3]


class TestAnnotator:

    def test_annotate_hook_receives_kwargs(self):
        calls = []

        class Recorder(pipeline_backend.Annotator):

            def annotate(self, col, backend, stage_name, **kwargs):
                calls.append((stage_name, kwargs))
                return col

        pipeline_backend.register_annotator(Recorder())
        try:
            backend = pdp.LocalBackend()
            out = backend.annotate([1, 2], "stage-x", foo=42)
            assert list(out) == [1, 2]
            assert calls and calls[0][0] == "stage-x"
            assert calls[0][1]["foo"] == 42
        finally:
            pipeline_backend._annotators.clear()


class TestUniqueLabels:

    def test_unique_labels_suffix_and_dedup(self):
        gen = pipeline_backend.UniqueLabelsGenerator("sfx")
        a = gen.unique("stage")
        b = gen.unique("stage")
        assert a != b
        assert "sfx" in a and "sfx" in b

    def test_unique_labels_empty_name(self):
        gen = pipeline_backend.UniqueLabelsGenerator("")
        assert gen.unique("") != gen.unique("")
