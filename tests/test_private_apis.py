"""Tests for the L5 private-collection APIs (PrivateCollection + adapters).

Mirrors the reference test approach for private_beam/private_spark
(tests/private_beam_test.py, tests/private_spark_test.py): huge-epsilon
determinism + public partitions for value checks, plus guarded-container
semantics (map/flat_map keep privacy ids).
"""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import private_collection

HUGE_EPS = 1e7


def make_backend(name):
    if name == "local":
        return pdp.LocalBackend(seed=7)
    return pdp.TPUBackend(noise_seed=7)


BACKENDS = ["local", "tpu"]

# rows: (uid, city, spend)
ROWS = [
    ("u1", "NY", 1.0),
    ("u1", "NY", 2.0),
    ("u1", "SF", 3.0),
    ("u2", "NY", 4.0),
    ("u2", "SF", 1.0),
    ("u3", "NY", 2.0),
]


def _private(backend, accountant):
    return pdp.make_private(ROWS, backend, accountant,
                            privacy_id_extractor=lambda r: r[0])


@pytest.mark.parametrize("backend_name", BACKENDS)
class TestPrivateCollectionMetrics:

    def _run(self, backend_name, method, params_cls, needs_values=True,
             **extra):
        backend = make_backend(backend_name)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        pcol = _private(backend, accountant)
        kwargs = dict(
            max_partitions_contributed=4,
            partition_extractor=lambda r: r[1],
            **extra,
        )
        if needs_values:
            kwargs.update(min_value=0.0, max_value=10.0,
                          value_extractor=lambda r: r[2])
        params = params_cls(**kwargs)
        result = getattr(pcol, method)(params,
                                       public_partitions=["NY", "SF"])
        accountant.compute_budgets()
        return dict(result)

    def test_count(self, backend_name):
        got = self._run(backend_name, "count", pdp.CountParams,
                        needs_values=False,
                        noise_kind=pdp.NoiseKind.LAPLACE,
                        max_contributions_per_partition=4)
        assert got["NY"] == pytest.approx(4, abs=0.1)
        assert got["SF"] == pytest.approx(2, abs=0.1)

    def test_sum(self, backend_name):
        got = self._run(backend_name, "sum", pdp.SumParams,
                        max_contributions_per_partition=4)
        assert got["NY"] == pytest.approx(9.0, abs=0.1)
        assert got["SF"] == pytest.approx(4.0, abs=0.1)

    def test_mean(self, backend_name):
        got = self._run(backend_name, "mean", pdp.MeanParams,
                        max_contributions_per_partition=4)
        assert got["NY"] == pytest.approx(9.0 / 4, abs=0.1)
        assert got["SF"] == pytest.approx(2.0, abs=0.1)

    def test_variance(self, backend_name):
        got = self._run(backend_name, "variance", pdp.VarianceParams,
                        max_contributions_per_partition=4)
        # NY values 1,2,4,2 → var 1.1875
        assert got["NY"] == pytest.approx(1.1875, abs=0.3)

    def test_privacy_id_count(self, backend_name):
        got = self._run(backend_name, "privacy_id_count",
                        pdp.PrivacyIdCountParams, needs_values=False,
                        noise_kind=pdp.NoiseKind.LAPLACE)
        assert got["NY"] == pytest.approx(3, abs=0.1)
        assert got["SF"] == pytest.approx(2, abs=0.1)


@pytest.mark.parametrize("backend_name", BACKENDS)
class TestPrivateCollectionTransforms:

    def test_map_keeps_privacy_ids(self, backend_name):
        backend = make_backend(backend_name)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        pcol = _private(backend, accountant).map(lambda r:
                                                 (r[0], r[1], r[2] * 2))
        result = pcol.sum(
            pdp.SumParams(max_partitions_contributed=4,
                          max_contributions_per_partition=4,
                          min_value=0.0,
                          max_value=20.0,
                          partition_extractor=lambda r: r[1],
                          value_extractor=lambda r: r[2]),
            public_partitions=["NY"])
        accountant.compute_budgets()
        got = dict(result)
        assert got["NY"] == pytest.approx(18.0, abs=0.1)

    def test_flat_map_keeps_privacy_ids(self, backend_name):
        backend = make_backend(backend_name)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        pcol = _private(backend, accountant).flat_map(lambda r: [r, r])
        result = pcol.count(
            pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                            max_partitions_contributed=4,
                            max_contributions_per_partition=10,
                            partition_extractor=lambda r: r[1]),
            public_partitions=["NY"])
        accountant.compute_budgets()
        got = dict(result)
        assert got["NY"] == pytest.approx(8, abs=0.1)

    def test_select_partitions(self, backend_name):
        backend = make_backend(backend_name)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        pcol = _private(backend, accountant)
        got = pcol.select_partitions(
            pdp.SelectPartitionsParams(max_partitions_contributed=2),
            partition_extractor=lambda r: r[1])
        accountant.compute_budgets()
        assert sorted(got) == ["NY", "SF"]


class _SumCombineFn(private_collection.PrivateCombineFn):
    """Toy custom combine fn: clipped sum + Laplace noise via the budget."""

    def create_accumulator(self):
        return 0.0

    def add_input_for_private_output(self, accumulator, value):
        return accumulator + min(max(value, 0.0), 5.0)

    def merge_accumulators(self, accumulators):
        return sum(accumulators)

    def extract_private_output(self, accumulator, budget, aggregate_params):
        # huge-eps test: return the (near-noiseless) clipped sum
        assert budget.eps > 0
        return accumulator

    def request_budget(self, budget_accountant):
        return budget_accountant.request_budget(pdp.MechanismType.LAPLACE)


@pytest.mark.parametrize("backend_name", BACKENDS)
def test_combine_per_key_custom_fn(backend_name):
    backend = make_backend(backend_name)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-5)
    # elements: (uid, (key, value))
    pairs = [(r[0], (r[1], r[2])) for r in ROWS]
    pcol = pdp.make_private(pairs, backend, accountant)
    got = pcol.combine_per_key(
        _SumCombineFn(),
        pdp.CombinePerKeyParams(max_partitions_contributed=4,
                                max_contributions_per_partition=4,
                                public_partitions=["NY", "SF"]))
    accountant.compute_budgets()
    got = dict(got)
    assert got["NY"] == pytest.approx(9.0, abs=0.01)
    assert got["SF"] == pytest.approx(4.0, abs=0.01)


@pytest.mark.parametrize("backend_name", BACKENDS)
def test_multiple_aggregations_on_same_collection(backend_name):
    # Regression: the (privacy_id, element) collection must be re-iterable —
    # the second aggregation used to see an exhausted generator.
    backend = make_backend(backend_name)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-5)
    pcol = _private(backend, accountant)
    sum_res = pcol.sum(
        pdp.SumParams(max_partitions_contributed=4,
                      max_contributions_per_partition=4,
                      min_value=0.0, max_value=10.0,
                      partition_extractor=lambda r: r[1],
                      value_extractor=lambda r: r[2]),
        public_partitions=["NY"])
    count_res = pcol.count(
        pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                        max_partitions_contributed=4,
                        max_contributions_per_partition=4,
                        partition_extractor=lambda r: r[1]),
        public_partitions=["NY"])
    accountant.compute_budgets()
    assert dict(sum_res)["NY"] == pytest.approx(9.0, abs=0.1)
    assert dict(count_res)["NY"] == pytest.approx(4, abs=0.1)


# The Beam/Spark adapters execute end-to-end (real BeamBackend /
# SparkRDDBackend / private_beam / private_spark code) over in-memory fake
# runners in tests/test_fake_runners.py — apache_beam/pyspark themselves are
# not installable in this environment. These two checks only assert the
# import gating works when the real libraries are present.


def test_beam_adapter_requires_beam():
    pytest.importorskip("apache_beam")
    from pipelinedp_tpu import private_beam  # noqa: F401


def test_spark_adapter_requires_spark():
    pytest.importorskip("pyspark")
    from pipelinedp_tpu import private_spark  # noqa: F401
