"""Elastic mesh degradation: device-loss tolerance for the meshed paths.

Covers the device-fatal failure class (runtime/retry.is_device_fatal),
the mesh re-plan loop (run_with_mesh_degradation) driven through all
four meshed drivers, the degradation floor (D=1 unsharded fallback, the
min_devices error), and the privacy invariant the whole design rests
on: block noise/selection keys are fold_in(final_key, b) — pure
functions of the run key and block index, independent of mesh size D —
so a run degraded onto fewer devices releases bit-identical noise.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, executor
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.ops import selection_ops
from pipelinedp_tpu.parallel import large_p, make_mesh, sharded
from pipelinedp_tpu.parallel import mesh as mesh_lib
from pipelinedp_tpu.runtime import BlockJournal
from pipelinedp_tpu.runtime import faults
from pipelinedp_tpu.runtime import health as health_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import telemetry

pytestmark = pytest.mark.faults

P = 1 << 12
BLOCK = 1 << 10  # 4 blocks
L0 = 2
FAST = retry_lib.RetryPolicy(max_retries=2, base_delay=0.0, max_delay=0.0)


def _spec(noise_free=False):
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                          pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=L0,
                                 max_contributions_per_partition=3,
                                 min_value=0.0,
                                 max_value=5.0)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    budget = accountant.request_budget(MechanismType.GENERIC)
    accountant.compute_budgets()
    selection = selection_ops.selection_params_from_host(
        params.partition_selection_strategy, budget.eps, budget.delta, L0,
        None)
    cfg = executor.make_kernel_config(params, compound, P,
                                      private_selection=True,
                                      selection_params=selection)
    stds = np.asarray(executor.compute_noise_stds(compound, params))
    if noise_free:
        stds = np.zeros_like(stds)
    return cfg, stds, executor.kernel_scalars(params), selection


def _data():
    """Placement-independent rows: every privacy id holds exactly ONE row
    in ONE partition (L0/Linf bounding can never drop anything, so which
    shard an id lands on — a function of mesh size D — cannot change the
    aggregate), and INTEGER values, so per-shard partial sums are exact
    in floating point and reduce ordering across different D cannot
    perturb a bit. 12 dense partitions with 120 ids each (keep
    probability ~1) + 5 single-id partitions (~0)."""
    dense_parts = (np.arange(12, dtype=np.int64) * 239 + 57) % P
    n_per = 120
    pid = (np.repeat(np.arange(n_per), 12) * 1_000_003 +
           np.tile(np.arange(12), n_per)).astype(np.int32)
    pk = np.tile(dense_parts, n_per).astype(np.int32)
    rng = np.random.default_rng(7)
    values = rng.integers(0, 6, len(pk)).astype(np.float64)
    pid = np.concatenate([pid,
                          2_000_000_000 + np.arange(5, dtype=np.int32)])
    sparse_parts = (np.arange(5, dtype=np.int64) * 911 + 13) % P
    pk = np.concatenate([pk, sparse_parts.astype(np.int32)])
    values = np.concatenate([values, np.ones(5)])
    return pid, pk, values, np.ones(len(pid), bool), np.sort(dense_parts)


class TestDeviceFatalClassification:

    def test_injected_and_markers(self):
        assert retry_lib.is_device_fatal(
            faults.InjectedDeviceLossError("x"))
        assert retry_lib.is_device_fatal(
            RuntimeError("INTERNAL: DEVICE_LOST: core dumped"))
        assert retry_lib.is_device_fatal(
            RuntimeError("UNAVAILABLE: device is lost"))
        assert not retry_lib.is_device_fatal(
            RuntimeError("UNAVAILABLE: socket closed"))
        assert not retry_lib.is_device_fatal(faults.InjectedOOMError("x"))

    def test_device_fatal_is_neither_transient_nor_oom(self):
        # Device-loss status text often carries UNAVAILABLE — the
        # device-fatal class must win, or the runtime would retry the
        # same program onto a dead chip.
        lost = RuntimeError("UNAVAILABLE: device is lost (chip 3)")
        assert not retry_lib.is_transient(lost)
        assert not retry_lib.is_oom(lost)
        assert not retry_lib.is_transient(
            faults.InjectedDeviceLossError("x"))

    def test_device_loss_fault_point_validation(self):
        faults.Fault("device_loss", point="dispatch")
        faults.Fault("device_loss", point="collective")
        with pytest.raises(ValueError):
            faults.Fault("device_loss", point="drain")

    def test_schedule_assigns_losses_sticky(self):
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", times=2)])
        sched.note_device_loss(faults.Fault("device_loss"))
        assert sched.assign_lost([0, 1, 2, 3]) == {3}
        # A later probe of the shrunken set agrees and extends.
        sched.note_device_loss(faults.Fault("device_loss"))
        assert sched.assign_lost([0, 1, 2]) == {2}
        assert sched.assign_lost([0, 1, 2, 3]) == {2, 3}


class TestBlockKeyGeometryInvariance:
    """The privacy invariant elastic degradation relies on, pinned:
    fold_in(final_key, b) block keys — and therefore the released noise
    and selection decisions — are independent of the mesh size D. With
    placement-independent inputs (one row per id per partition, integer
    values: see _data) the FULL driver outputs, noise included, must be
    bit-identical on D=1/2/4 CPU meshes and on the unsharded driver."""

    def test_blocked_aggregate_bit_identical_across_mesh_sizes(self):
        cfg, stds, (min_v, max_v, min_s, max_s, mid), _ = _spec()
        pid, pk, values, valid, expected_kept = _data()
        key = jax.random.PRNGKey(5)
        ref_kept, ref_out = large_p.aggregate_blocked(
            pid, pk, values, valid, min_v, max_v, min_s, max_s, mid, stds,
            key, cfg, block_partitions=BLOCK)
        assert np.array_equal(ref_kept, expected_kept)
        for d in (1, 2, 4):
            kept, out = large_p.aggregate_blocked_sharded(
                make_mesh(n_devices=d), pid, pk, values, valid, min_v,
                max_v, min_s, max_s, mid, stds, key, cfg,
                block_partitions=BLOCK)
            assert np.array_equal(ref_kept, kept), f"D={d}"
            for name in ("count", "sum"):
                assert np.array_equal(np.asarray(ref_out[name]),
                                      np.asarray(out[name])), \
                    f"{name} not bit-identical at D={d}"

    def test_blocked_select_bit_identical_across_mesh_sizes(self):
        _, _, _, selection = _spec()
        pid, pk, values, valid, _ = _data()
        key = jax.random.PRNGKey(9)
        ref = large_p.select_partitions_blocked(
            pid, pk, valid, key, L0, P, selection, block_partitions=BLOCK)
        for d in (1, 2, 4):
            kept = large_p.select_partitions_blocked_sharded(
                make_mesh(n_devices=d), pid, pk, valid, key, L0, P,
                selection, block_partitions=BLOCK)
            assert np.array_equal(ref, kept), f"D={d}"

    def test_dense_aggregate_noise_identical_across_mesh_sizes(self):
        cfg, stds, (min_v, max_v, min_s, max_s, mid), _ = _spec()
        pid, pk, values, valid, _ = _data()
        key = jax.random.PRNGKey(11)
        ref = None
        for d in (1, 2, 4):
            out, keep, _ = sharded.sharded_aggregate_arrays(
                make_mesh(n_devices=d), pid, pk, values, valid, min_v,
                max_v, min_s, max_s, mid, stds, key, cfg)
            got = (np.asarray(keep), np.asarray(out["count"]),
                   np.asarray(out["sum"]))
            if ref is None:
                ref = got
                continue
            assert np.array_equal(ref[0], got[0]), f"keep differs at D={d}"
            assert np.array_equal(ref[1], got[1]), f"count differs at D={d}"
            assert np.array_equal(ref[2], got[2]), f"sum differs at D={d}"


def _blocked_agg_runner(mesh, key, journal=None, **kwargs):
    cfg, stds, (min_v, max_v, min_s, max_s, mid), _ = _spec()
    pid, pk, values, valid, _ = _data()
    kept, out = large_p.aggregate_blocked_sharded(
        mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
        stds, key, cfg, block_partitions=BLOCK, journal=journal, **kwargs)
    return kept, np.asarray(out["sum"])


def _blocked_select_runner(mesh, key, journal=None, **kwargs):
    _, _, _, selection = _spec()
    pid, pk, values, valid, _ = _data()
    kept = large_p.select_partitions_blocked_sharded(
        mesh, pid, pk, valid, key, L0, P, selection,
        block_partitions=BLOCK, journal=journal, **kwargs)
    return kept, kept


def _dense_agg_runner(mesh, key, journal=None, **kwargs):
    assert journal is None
    cfg, stds, (min_v, max_v, min_s, max_s, mid), _ = _spec()
    pid, pk, values, valid, _ = _data()
    out, keep, _ = sharded.sharded_aggregate_arrays(
        mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
        stds, key, cfg, **kwargs)
    return np.asarray(keep), np.asarray(out["sum"])


def _dense_select_runner(mesh, key, journal=None, **kwargs):
    assert journal is None
    _, _, _, selection = _spec()
    pid, pk, values, valid, _ = _data()
    keep = sharded.sharded_select_partitions(mesh, pid, pk, valid, key, L0,
                                             P, selection, **kwargs)
    return np.asarray(keep), np.asarray(keep)


# (runner, supports_journal) for each of the four meshed drivers.
DRIVERS = [
    ("blocked_aggregate", _blocked_agg_runner, True),
    ("blocked_select", _blocked_select_runner, True),
    ("dense_aggregate", _dense_agg_runner, False),
    ("dense_select", _dense_select_runner, False),
]


class TestElasticRecovery:

    @pytest.mark.parametrize("name,runner,_j",
                             DRIVERS,
                             ids=[d[0] for d in DRIVERS])
    def test_device_loss_shrinks_mesh_and_preserves_outputs(
            self, name, runner, _j):
        key = jax.random.PRNGKey(21)
        base = runner(make_mesh(n_devices=4), key)
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", point="dispatch")])
        before = telemetry.snapshot()
        job = f"elastic-{name}"
        with faults.inject(sched):
            got = runner(make_mesh(n_devices=4), key, retry=FAST,
                         elastic=True, job_id=job)
        assert sched.pending() == 0
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        delta = telemetry.delta(before)
        assert delta.get("device_losses") == 1, delta
        assert delta.get("mesh_degradations") == 1, delta
        snap = health_lib.for_job(job).snapshot()
        assert snap["state"] == "DEGRADED", snap
        assert snap["planned_devices"] == 4, snap
        assert snap["live_devices"] == 3, snap

    def test_journaled_blocks_replay_on_degraded_mesh(self, tmp_path):
        """A device lost at block 2 must not re-dispatch blocks 0-1: they
        were consumed (and journaled) before the loss, so the degraded
        re-entry replays them from the host record."""
        key = jax.random.PRNGKey(23)
        base = _blocked_agg_runner(make_mesh(n_devices=4), key)
        journal = BlockJournal(str(tmp_path))
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", block=2, point="dispatch")])
        before = telemetry.snapshot()
        with faults.inject(sched):
            got = _blocked_agg_runner(make_mesh(n_devices=4), key,
                                      journal=journal, retry=FAST,
                                      elastic=True, job_id="elastic-replay")
        assert sched.pending() == 0
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        delta = telemetry.delta(before)
        assert delta.get("journal_replays", 0) >= 1, delta

    def test_collective_point_loss_recovers(self):
        """A device lost during the all_to_all reshard is NOT a
        collective failure the host permutation can absorb — the mesh
        must shrink and the permutation rebuild for the new geometry."""
        cfg, stds, (min_v, max_v, min_s, max_s, mid), _ = _spec()
        pid, pk, values, valid, _ = _data()
        key = jax.random.PRNGKey(29)
        mesh = make_mesh(n_devices=4)
        base_kept, base_out = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=BLOCK)
        dev_cols = (jnp.asarray(pid), jnp.asarray(pk), jnp.asarray(values),
                    jnp.asarray(valid))
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", point="collective")])
        before = telemetry.snapshot()
        with faults.inject(sched):
            kept, out = large_p.aggregate_blocked_sharded(
                mesh, *dev_cols, min_v, max_v, min_s, max_s, mid, stds,
                key, cfg, block_partitions=BLOCK, retry=FAST, elastic=True)
        assert sched.pending() == 0
        assert np.array_equal(base_kept, kept)
        assert np.array_equal(np.asarray(base_out["sum"]),
                              np.asarray(out["sum"]))
        delta = telemetry.delta(before)
        assert delta.get("mesh_degradations") == 1, delta
        # The loss propagated to the elastic loop, not the host-fallback
        # path: a dead chip in the mesh cannot be routed around by
        # staging rows through the host.
        assert "reshard_host_fallbacks" not in delta, delta

    def test_repeated_losses_keep_degrading(self):
        key = jax.random.PRNGKey(31)
        base = _blocked_agg_runner(make_mesh(n_devices=4), key)
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", point="dispatch", times=2)])
        before = telemetry.snapshot()
        with faults.inject(sched):
            got = _blocked_agg_runner(make_mesh(n_devices=4), key,
                                      retry=FAST, elastic=True,
                                      job_id="elastic-twice")
        assert sched.pending() == 0
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        delta = telemetry.delta(before)
        assert delta.get("device_losses") == 2, delta
        assert delta.get("mesh_degradations") == 2, delta
        snap = health_lib.for_job("elastic-twice").snapshot()
        assert snap["live_devices"] == 2, snap

    def test_without_elastic_device_loss_is_fatal(self):
        key = jax.random.PRNGKey(33)
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", point="dispatch")])
        with faults.inject(sched):
            with pytest.raises(faults.InjectedDeviceLossError):
                _blocked_agg_runner(make_mesh(n_devices=4), key,
                                    retry=FAST, job_id="elastic-off")
        snap = health_lib.for_job("elastic-off").snapshot()
        assert snap["state"] == "FAILED", snap


class TestDegradationFloor:

    @pytest.mark.parametrize("name,runner,_j",
                             DRIVERS,
                             ids=[d[0] for d in DRIVERS])
    def test_one_device_mesh_takes_unsharded_fallback(
            self, name, runner, _j, caplog):
        key = jax.random.PRNGKey(41)
        base = runner(make_mesh(n_devices=2), key)
        with caplog.at_level(logging.WARNING):
            got = runner(make_mesh(n_devices=1), key, elastic=True)
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        warnings = [r for r in caplog.records
                    if "unsharded driver" in r.getMessage()]
        assert len(warnings) == 1, (
            f"expected exactly one clear fallback warning, got "
            f"{[r.getMessage() for r in warnings]}")

    @pytest.mark.parametrize("name,runner,supports_journal",
                             DRIVERS,
                             ids=[d[0] for d in DRIVERS])
    def test_losses_past_min_devices_raise_actionable_error(
            self, name, runner, supports_journal, tmp_path):
        key = jax.random.PRNGKey(43)
        job = f"floor-{name}"
        journal = BlockJournal(str(tmp_path)) if supports_journal else None
        kwargs = dict(retry=FAST, elastic=True, min_devices=2, job_id=job)
        if supports_journal:
            kwargs["journal"] = journal
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", point="dispatch")])
        with faults.inject(sched):
            with pytest.raises(retry_lib.MeshDegradationError) as err:
                runner(make_mesh(n_devices=2), key, **kwargs)
        msg = str(err.value)
        assert job in msg, msg
        if supports_journal:
            assert str(tmp_path) in msg, msg
        else:
            assert "no journal configured" in msg, msg
        snap = health_lib.for_job(job).snapshot()
        assert snap["state"] == "FAILED", snap

    def test_losing_the_last_device_exhausts_the_floor(self):
        """A device_loss that fires inside the unsharded fallback means
        the final surviving device died: unrecoverable by design."""
        key = jax.random.PRNGKey(47)
        sched = faults.FaultSchedule(
            [faults.Fault("device_loss", point="dispatch", times=2)])
        with faults.inject(sched):
            with pytest.raises(retry_lib.MeshDegradationError):
                _blocked_agg_runner(make_mesh(n_devices=2), key,
                                    retry=FAST, elastic=True,
                                    job_id="floor-last")


class TestHostFetchRetryKnobs:
    """Satellite: host_fetch backoff is jittered (multi-host retries must
    not fire in lockstep) and its budget threads from the backend's
    RetryPolicy instead of the hardcoded default."""

    class _Flaky:
        def __init__(self, failures):
            self.left = failures
            self.calls = 0

        def __array__(self, dtype=None, copy=None):
            self.calls += 1
            if self.left > 0:
                self.left -= 1
                raise RuntimeError("UNAVAILABLE: tunnel hiccup")
            return np.zeros(1)

    def test_fetch_retry_scope_threads_budget(self, monkeypatch):
        monkeypatch.setattr(mesh_lib.time, "sleep", lambda _: None)
        flaky = self._Flaky(failures=4)
        with pytest.raises(RuntimeError):
            mesh_lib.host_fetch(self._Flaky(failures=4))  # default: 2
        with mesh_lib.fetch_retry_scope(6):
            assert mesh_lib.host_fetch(flaky) is not None
        assert flaky.calls == 5

    def test_backoff_is_jittered(self, monkeypatch):
        delays = []
        monkeypatch.setattr(mesh_lib.time, "sleep", delays.append)
        with mesh_lib.fetch_retry_scope(6):
            mesh_lib.host_fetch(self._Flaky(failures=6))
        assert len(delays) == 6
        pure = [min(0.05 * 2**a, 1.0) for a in range(6)]
        # Every delay sits in [0.5, 1.0) x the pure exponential value,
        # and at least one differs from it (the lockstep schedule).
        for d, p in zip(delays, pure):
            assert 0.5 * p <= d < p + 1e-12, (d, p)
        assert any(abs(d - p) > 1e-9 for d, p in zip(delays, pure))


class TestJobScopedTimings:
    """Satellite: timing stats are scoped by job the same way counter
    forwarding is, so a receipt's per-job snapshot cannot mix phases
    from two jobs run in the same process."""

    def test_per_job_snapshots_do_not_mix(self):
        with health_lib.job_scope("timing-job-a"):
            telemetry.record_duration("phase_one", 1.0)
        with health_lib.job_scope("timing-job-b"):
            telemetry.record_duration("phase_one", 3.0)
            telemetry.record_duration("phase_two", 0.5)
        a = telemetry.timing_snapshot("timing-job-a")
        b = telemetry.timing_snapshot("timing-job-b")
        assert a["phase_one"]["count"] == 1 and a["phase_one"]["sum"] == 1.0
        assert "phase_two" not in a
        assert b["phase_one"]["sum"] == 3.0
        assert b["phase_two"]["count"] == 1
        by_job = telemetry.job_timing_snapshot()
        assert by_job["timing-job-a"] == a
        assert by_job["timing-job-b"] == b
        # The process-wide aggregate still merges everything.
        merged = telemetry.timing_snapshot()
        assert merged["phase_one"]["count"] >= 2
