"""The thread-escape race engine + the static determinism rule.

Covers, per the v3 issue: structural root discovery for all seven
spawn patterns, write-write and write-read races with the full
root→access path in the message, common-lock and immutable-after-init
declassification, the guard-candidate fix-it, set-iteration release
flows, the sorted() sanitizer, and convergence on recursive
thread-spawning code — plus the regression test for the real race the
first full-tree run caught (combiners' namedtuple-type cache).
"""

import pytest

from pipelinedp_tpu import staticcheck
from pipelinedp_tpu.staticcheck import rules as sc_rules
from pipelinedp_tpu.staticcheck import threads as sc_threads
from pipelinedp_tpu.staticcheck.model import CallGraph

pytestmark = pytest.mark.staticcheck


def _analyze(sources, rule):
    mods = [staticcheck.parse_source(rel, src)
            for rel, src in sources.items()]
    return staticcheck.analyze(mods, only_rules=[rule]).active


def _roots(sources):
    mods = [staticcheck.parse_source(rel, src)
            for rel, src in sources.items()]
    return sc_threads.discover_roots(CallGraph(mods))


# ---------------------------------------------------------------------------
# Root discovery: the seven structural spawn patterns
# ---------------------------------------------------------------------------


class TestRootDiscovery:

    def test_thread_target_function(self):
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "def work():\n"
            "    pass\n"
            "def start():\n"
            "    threading.Thread(target=work, daemon=True).start()\n")})
        assert [(r.func[1], r.kind) for r in roots] == \
            [("work", "Thread(target=)")]

    def test_thread_target_self_method(self):
        """The watchdog-monitor form: Thread(target=self._m)."""
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "class Monitor:\n"
            "    def _run(self):\n"
            "        pass\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._run).start()\n")})
        assert [r.func[1] for r in roots] == ["Monitor._run"]

    def test_timer(self):
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "def fire():\n"
            "    pass\n"
            "def arm():\n"
            "    threading.Timer(5.0, fire).start()\n")})
        assert [(r.func[1], r.kind) for r in roots] == [("fire", "Timer")]

    def test_executor_submit(self):
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "from concurrent import futures\n"
            "def encode(x):\n"
            "    return x\n"
            "def run(items):\n"
            "    pool = futures.ThreadPoolExecutor(2)\n"
            "    return [pool.submit(encode, i) for i in items]\n")})
        assert [(r.func[1], r.kind) for r in roots] == \
            [("encode", "executor.submit")]

    def test_executor_map(self):
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "from concurrent import futures\n"
            "def encode(x):\n"
            "    return x\n"
            "def run(items):\n"
            "    pool = futures.ThreadPoolExecutor(2)\n"
            "    return list(pool.map(encode, items))\n")})
        assert [(r.func[1], r.kind) for r in roots] == \
            [("encode", "executor.map")]

    def test_backend_map_is_not_an_executor(self):
        """The pipeline-backend `.map(col, fn)` API never matches: the
        receiver is not executor-like and would mis-root the whole
        engine."""
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "def build(backend, col):\n"
            "    return backend.map(col, lambda x: x, 'stage')\n")})
        assert roots == []

    def test_http_handler_class(self):
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "import http.server\n"
            "class Handler(http.server.BaseHTTPRequestHandler):\n"
            "    def do_GET(self):\n"
            "        pass\n")})
        assert [(r.func[1], r.kind) for r in roots] == \
            [("Handler.do_GET", "http-handler")]

    def test_main_guard_subprocess_entry(self):
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "import sys\n"
            "def child_main(arg):\n"
            "    return 0\n"
            "if __name__ == '__main__':\n"
            "    sys.exit(child_main(sys.argv[1]))\n")})
        assert [(r.func[1], r.kind) for r in roots] == \
            [("child_main", "__main__ entry")]

    def test_nested_feeder_and_pool_workers(self):
        """The map_overlapped shape: a nested feeder thread plus pool
        submits of a sibling nested function — both are roots."""
        roots = _roots({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "from concurrent import futures\n"
            "def run(items, fn):\n"
            "    pool = futures.ThreadPoolExecutor(2)\n"
            "    def encode(item):\n"
            "        return fn(item)\n"
            "    def feed():\n"
            "        for item in items:\n"
            "            pool.submit(encode, item)\n"
            "    threading.Thread(target=feed).start()\n")})
        assert {r.func[1] for r in roots} == {"run.encode", "run.feed"}


# ---------------------------------------------------------------------------
# Races, paths, declassification
# ---------------------------------------------------------------------------

_TWO_ROOT_PREAMBLE = (
    "import threading\n"
    "def start():\n"
    "    threading.Thread(target=_worker).start()\n"
    "    threading.Thread(target=_monitor).start()\n")


class TestRaces:

    def test_write_read_race_with_paths(self):
        (f,) = _analyze({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_state = {}\n"
            "def _worker():\n"
            "    _state['k'] = 1\n"
            "def _monitor():\n"
            "    return _state.get('k')\n" + _TWO_ROOT_PREAMBLE[17:])},
            "thread-escape")
        assert "write-read race" in f.message
        assert f.line == 4  # anchored at the racing write
        assert "root _worker" in f.message and \
            "root _monitor" in f.message
        assert "write at pipelinedp_tpu/fix.py:4" in f.message
        assert "read at pipelinedp_tpu/fix.py:6" in f.message

    def test_write_write_race_through_helper_carries_hops(self):
        """Interprocedural: the racing write sits two hops from the
        root and the path names every hop."""
        (f,) = _analyze({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_counts = {}\n"
            "def _bump(name):\n"
            "    _counts[name] = _counts.get(name, 0) + 1\n"
            "def _worker():\n"
            "    _bump('a')\n"
            "def _monitor():\n"
            "    _bump('b')\n" + _TWO_ROOT_PREAMBLE[17:])},
            "thread-escape")
        assert "write-write race" in f.message
        assert "_bump (pipelinedp_tpu/fix.py:6)" in f.message
        assert "_bump (pipelinedp_tpu/fix.py:8)" in f.message

    def test_common_lock_declassifies_and_fixit_names_declaration(self):
        """Consistently-locked-but-undeclared shared state is not a
        race — it is a guard-candidate fix-it naming the _GUARDED_BY
        declaration to add (unification with lock-discipline)."""
        (f,) = _analyze({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_state = {}\n"
            "def _worker():\n"
            "    with _lock:\n"
            "        _state['k'] = 1\n"
            "def _monitor():\n"
            "    with _lock:\n"
            "        return _state.get('k')\n" +
            _TWO_ROOT_PREAMBLE[17:])}, "thread-escape")
        assert "guarded_by('_lock', '_state')" in f.message
        assert "race" not in f.message.split(":")[0]

    def test_declared_guarded_attr_is_lock_disciplines_territory(self):
        """A _GUARDED_BY-declared attribute is skipped entirely —
        lock-discipline owns its enforcement."""
        src = (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "_lock = threading.Lock()\n"
            "_state = {}\n"
            "_GUARDED_BY = guarded_by('_lock', '_state')\n"
            "def _worker():\n"
            "    with _lock:\n"
            "        _state['k'] = 1\n"
            "def _monitor():\n"
            "    with _lock:\n"
            "        return _state.get('k')\n" + _TWO_ROOT_PREAMBLE[17:])
        assert _analyze({"pipelinedp_tpu/fix.py": src},
                        "thread-escape") == []

    def test_partial_lock_race_names_candidate_guard(self):
        """One root locks, the other does not: a race whose fix-it
        names the lock the guarded access already holds."""
        (f,) = _analyze({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_state = {}\n"
            "def _worker():\n"
            "    with _lock:\n"
            "        _state['k'] = 1\n"
            "def _monitor():\n"
            "    return _state.get('k')\n" + _TWO_ROOT_PREAMBLE[17:])},
            "thread-escape")
        assert "race" in f.message
        assert "guarded_by('_lock', '_state')" in f.message

    def test_interprocedural_entry_locks_declassify_helpers(self):
        """A helper ONLY ever called under the lock analyzes as holding
        it (entry-lock intersection), so caller-locked discipline needs
        no annotation."""
        src = (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "_state = {}\n"
            "def _touch(k):\n"
            "    _state[k] = 1\n"
            "def _worker():\n"
            "    with _lock:\n"
            "        _touch('a')\n"
            "def _monitor():\n"
            "    with _lock:\n"
            "        _touch('b')\n" + _TWO_ROOT_PREAMBLE[17:])
        found = _analyze({"pipelinedp_tpu/fix.py": src}, "thread-escape")
        assert all("race" not in f.message.split(":")[0] for f in found)

    def test_queue_event_state_is_declassified(self):
        src = (
            "import queue\n"
            "import threading\n"
            "_q = queue.Queue()\n"
            "_done = threading.Event()\n"
            "def _worker():\n"
            "    _q.put(1)\n"
            "    _done.set()\n"
            "def _monitor():\n"
            "    _done.wait()\n"
            "    return _q.get()\n" + _TWO_ROOT_PREAMBLE[17:])
        assert _analyze({"pipelinedp_tpu/fix.py": src},
                        "thread-escape") == []

    def test_immutable_after_init_is_declassified(self):
        """Attributes written only in __init__ are published before any
        thread starts — reads from two roots are not a race."""
        src = (
            "import threading\n"
            "class Job:\n"
            "    def __init__(self, path):\n"
            "        self.path = path\n"
            "    def _worker(self):\n"
            "        return self.path\n"
            "    def _monitor(self):\n"
            "        return self.path\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._worker).start()\n"
            "        threading.Thread(target=self._monitor).start()\n")
        assert _analyze({"pipelinedp_tpu/fix.py": src},
                        "thread-escape") == []

    def test_mutable_attr_on_shared_instance_is_a_race(self):
        """The contrast case: the same attribute written outside
        __init__ from one root and read from another IS a race."""
        src = (
            "import threading\n"
            "class Job:\n"
            "    def __init__(self):\n"
            "        self.state = None\n"
            "    def _worker(self):\n"
            "        self.state = 'running'\n"
            "    def _monitor(self):\n"
            "        return self.state\n"
            "    def start(self):\n"
            "        threading.Thread(target=self._worker).start()\n"
            "        threading.Thread(target=self._monitor).start()\n")
        (f,) = _analyze({"pipelinedp_tpu/fix.py": src}, "thread-escape")
        assert "self.state" in f.message and "race" in f.message

    def test_per_root_constructed_instances_are_owned(self):
        """Two roots each constructing their OWN instance of a class
        touch different objects — ownership declassifies the pair."""
        src = (
            "import threading\n"
            "class Span:\n"
            "    def __init__(self):\n"
            "        self.attrs = {}\n"
            "    def set(self, **kw):\n"
            "        self.attrs.update(kw)\n"
            "def _worker():\n"
            "    Span().set(a=1)\n"
            "def _monitor():\n"
            "    Span().set(b=2)\n" + _TWO_ROOT_PREAMBLE[17:])
        assert _analyze({"pipelinedp_tpu/fix.py": src},
                        "thread-escape") == []

    def test_converges_on_recursive_thread_spawning(self):
        """A root that re-spawns itself (and recurses) must terminate
        and still report its races."""
        (f,) = _analyze({"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_state = {}\n"
            "def _worker(depth):\n"
            "    _state['d'] = depth\n"
            "    if depth:\n"
            "        _worker(depth - 1)\n"
            "    threading.Thread(target=_worker, args=(depth,)).start()\n"
            "def _monitor():\n"
            "    return _state.get('d')\n"
            "def start():\n"
            "    threading.Thread(target=_monitor).start()\n")},
            "thread-escape")
        assert "_state" in f.message

    def test_suppression_requires_reason(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_state = {}\n"
            "def _worker():\n"
            "    _state['k'] = 1  # staticcheck: disable=thread-escape\n"
            "def _monitor():\n"
            "    return _state.get('k')\n" + _TWO_ROOT_PREAMBLE[17:])}
        (f,) = _analyze(src, "thread-escape")
        assert "suppression ignored" in f.message


# ---------------------------------------------------------------------------
# Determinism rule
# ---------------------------------------------------------------------------


class TestDeterminism:

    def test_set_iteration_into_release_flow(self):
        (f,) = _analyze({"pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, col):\n"
            "    keys = set(col)\n"
            "    return [(k, 1) for k in keys]\n")}, "determinism")
        assert "driver release value" in f.message
        assert "set() iteration order" in f.message

    def test_sorted_sanitizes(self):
        assert _analyze({"pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, col):\n"
            "    keys = sorted(set(col))\n"
            "    return [(k, 1) for k in keys]\n")}, "determinism") == []

    def test_order_insensitive_reductions_sanitize(self):
        assert _analyze({"pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, col):\n"
            "    keys = set(col)\n"
            "    return len(keys), max(keys), sum(keys)\n")},
            "determinism") == []

    def test_multi_hop_path_in_message(self):
        (f,) = _analyze({"pipelinedp_tpu/executor.py": (
            "def _uniq(col):\n"
            "    return set(col)\n"
            "def lazy_aggregate(backend, col):\n"
            "    for k in _uniq(col):\n"
            "        yield k, 1\n")}, "determinism")
        assert "_uniq (pipelinedp_tpu/executor.py:4)" in f.message

    def test_listdir_into_journal_key(self):
        (f,) = _analyze({"pipelinedp_tpu/fix.py": (
            "import os\n"
            "def persist(journal, job):\n"
            "    for name in os.listdir('.'):\n"
            "        journal.put(job, name, {'v': 1})\n")}, "determinism")
        assert "journal key" in f.message
        assert "os.listdir() order" in f.message

    def test_set_into_fold_in_derivation(self):
        (f,) = _analyze({"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def derive(key, items):\n"
            "    for b in set(items):\n"
            "        yield jax.random.fold_in(key, b)\n")}, "determinism")
        assert "fold_in noise-key derivation" in f.message

    def test_set_literal_is_a_source(self):
        (f,) = _analyze({"pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, a, b):\n"
            "    return list({a, b})\n")}, "determinism")
        assert "set-literal iteration order" in f.message

    def test_event_set_is_not_a_source(self):
        """`ev.set()` must never match the set() constructor — exact
        canonical-name matching."""
        assert _analyze({"pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, ev, col):\n"
            "    done = ev.set()\n"
            "    return [done, list(col)]\n")}, "determinism") == []

    def test_dict_from_set_keeps_order_taint(self):
        (f,) = _analyze({"pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, col):\n"
            "    d = dict.fromkeys(set(col))\n"
            "    return [k for k in d]\n")}, "determinism")
        assert "set() iteration order" in f.message


# ---------------------------------------------------------------------------
# Regression: the real race the first full-tree run caught
# ---------------------------------------------------------------------------


class TestFirstRunRegression:

    def _combiners_sources(self, strip_lock: bool):
        import pipelinedp_tpu.combiners as combiners
        with open(combiners.__file__) as f:
            src = f.read()
        guarded = "    with _named_tuple_cache_lock:\n"
        decl = ('_GUARDED_BY = guarded_by("_named_tuple_cache_lock", '
                '"_named_tuple_cache")\n')
        assert guarded in src and decl in src, \
            "combiners namedtuple-cache layout changed"
        if strip_lock:
            # The pre-fix state: no lock around the get-or-create AND
            # no _GUARDED_BY declaration (a declared attr is
            # lock-discipline's territory, not thread-escape's).
            src = src.replace(decl, "")
            lines = src.splitlines(keepends=True)
            i = lines.index(guarded)
            j = i + 1
            while j < len(lines) and (lines[j].startswith("        ") or
                                      lines[j].strip() == ""):
                lines[j] = lines[j][4:] if lines[j].strip() else lines[j]
                j += 1
            del lines[i]
            src = "".join(lines)
        # Two service-worker-shaped roots constructing compound
        # combiners concurrently (the service pool's first-run shape).
        driver = (
            "import threading\n"
            "from pipelinedp_tpu.combiners import CompoundCombiner\n"
            "def _job_a():\n"
            "    return CompoundCombiner([], True)\n"
            "def _job_b():\n"
            "    return CompoundCombiner([], True)\n"
            "def start():\n"
            "    threading.Thread(target=_job_a).start()\n"
            "    threading.Thread(target=_job_b).start()\n")
        return {"pipelinedp_tpu/combiners.py": src,
                "pipelinedp_tpu/fix_driver.py": driver}

    def test_unlocked_namedtuple_cache_is_a_race(self):
        """Stripping the lock the first-run triage added re-surfaces
        the write-write race on _named_tuple_cache."""
        found = _analyze(self._combiners_sources(strip_lock=True),
                         "thread-escape")
        assert any("_named_tuple_cache" in f.message and
                   "race" in f.message for f in found), found

    def test_committed_combiners_cache_is_clean(self):
        assert _analyze(self._combiners_sources(strip_lock=False),
                        "thread-escape") == []
