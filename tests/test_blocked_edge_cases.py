"""Empty-dataset and zero-kept-blocks edge cases across all four blocked/
sharded drivers (aggregate_blocked, aggregate_blocked_sharded,
select_partitions_blocked, select_partitions_blocked_sharded)."""

import numpy as np
import pytest

import jax

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, executor
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.ops import selection_ops
from pipelinedp_tpu.parallel import large_p, make_mesh

P = 300
BLOCK = 64
L0 = 4


def _spec():
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=L0,
                                 max_contributions_per_partition=8,
                                 min_value=0.0,
                                 max_value=5.0)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    budget = accountant.request_budget(MechanismType.GENERIC)
    accountant.compute_budgets()
    selection = selection_ops.selection_params_from_host(
        params.partition_selection_strategy, budget.eps, budget.delta, L0,
        None)
    cfg = executor.make_kernel_config(params, compound, P,
                                      private_selection=True,
                                      selection_params=selection)
    stds = executor.compute_noise_stds(compound, params)
    return cfg, stds, executor.kernel_scalars(params), selection


def _empty():
    return (np.zeros(0, np.int32), np.zeros(0, np.int32), np.zeros(0),
            np.zeros(0, bool))


def _all_invalid(n=500):
    # Rows present but every one invalid: the selection keep probability
    # of every partition is 0, so every driver must emit nothing.
    rng = np.random.default_rng(0)
    return (rng.integers(0, 100, n).astype(np.int32),
            rng.integers(0, P, n).astype(np.int32), rng.uniform(0, 5, n),
            np.zeros(n, bool))


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(n_devices=8)


class TestAggregateBlockedEdges:

    @pytest.mark.parametrize("data", [_empty(), _all_invalid()],
                             ids=["empty", "all_invalid"])
    def test_zero_kept(self, data):
        cfg, stds, (min_v, max_v, min_s, max_s, mid), _ = _spec()
        kept, outputs = large_p.aggregate_blocked(
            *data, min_v, max_v, min_s, max_s, mid, np.asarray(stds),
            jax.random.PRNGKey(0), cfg, block_partitions=BLOCK)
        assert kept.shape == (0,) and kept.dtype == np.int64
        assert set(outputs) == {"count", "sum"}
        assert all(len(col) == 0 for col in outputs.values())


class TestAggregateBlockedShardedEdges:

    @pytest.mark.parametrize("data", [_empty(), _all_invalid()],
                             ids=["empty", "all_invalid"])
    def test_zero_kept(self, mesh, data):
        cfg, stds, (min_v, max_v, min_s, max_s, mid), _ = _spec()
        kept, outputs = large_p.aggregate_blocked_sharded(
            mesh, *data, min_v, max_v, min_s, max_s, mid, np.asarray(stds),
            jax.random.PRNGKey(0), cfg, block_partitions=BLOCK)
        assert kept.shape == (0,) and kept.dtype == np.int64
        assert set(outputs) == {"count", "sum"}
        assert all(len(col) == 0 for col in outputs.values())


class TestSelectBlockedEdges:

    @pytest.mark.parametrize("data", [_empty(), _all_invalid()],
                             ids=["empty", "all_invalid"])
    def test_zero_kept(self, data):
        _, _, _, selection = _spec()
        pid, pk, _, valid = data
        kept = large_p.select_partitions_blocked(pid, pk, valid,
                                                 jax.random.PRNGKey(1), L0,
                                                 P, selection,
                                                 block_partitions=BLOCK)
        assert kept.shape == (0,) and kept.dtype == np.int64


class TestSelectBlockedShardedEdges:

    @pytest.mark.parametrize("data", [_empty(), _all_invalid()],
                             ids=["empty", "all_invalid"])
    def test_zero_kept(self, mesh, data):
        _, _, _, selection = _spec()
        pid, pk, _, valid = data
        kept = large_p.select_partitions_blocked_sharded(
            mesh, pid, pk, valid, jax.random.PRNGKey(1), L0, P, selection,
            block_partitions=BLOCK)
        assert kept.shape == (0,) and kept.dtype == np.int64
