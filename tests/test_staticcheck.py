"""The static analyzer itself + the tier-1 tree gate.

Four layers:

  * Per-rule fixtures: every shipped rule has a positive snippet (the
    rule fires), a suppressed snippet (a valid inline suppression
    silences it) and a clean snippet (no finding) — plus a meta-test
    that the fixture table covers every registered rule, so a new rule
    cannot ship untested.
  * Machinery: suppression reasons (reason-required rules ignore
    reasonless waivers), baseline round-trip (--update-baseline then a
    clean run), note preservation, stale-entry detection, CLI formats
    and exit codes.
  * The ACCEPTANCE fixture: removing the `with _lock:` from the real
    telemetry.record() source produces a lock-discipline finding.
  * The tier-1 gate: the full pass over pipelinedp_tpu/ (+ the
    key/RNG-hygiene subset over benchmarks/ and examples/) has zero
    non-baselined findings; the baseline carries only noted
    host-transfer entries plus noted benchmark/example key waivers; the
    interprocedural families run with EMPTY baselines; and the lock
    graph over the tree is proven acyclic.
  * Satellites: SARIF output golden, --cache / --changed-only parity
    with a cold run (tests/test_callgraph.py covers the call graph and
    the dataflow engines themselves).
"""

import json
import subprocess
import sys

import pytest

from pipelinedp_tpu import staticcheck
from pipelinedp_tpu.staticcheck import baseline as sc_baseline

pytestmark = pytest.mark.staticcheck


def _analyze(sources, rule):
    """sources: {rel: src}. Returns active findings of `rule`."""
    mods = [staticcheck.parse_source(rel, src)
            for rel, src in sources.items()]
    return staticcheck.analyze(mods, only_rules=[rule]).active


@pytest.fixture(scope="session")
def tree_result():
    """ONE full-tree pass shared by the tree gate, the lock-graph
    proof and the full-tree SARIF exercise — the analysis (parse +
    interprocedural fixpoints over ~150 modules) is the suite's
    dominant fixed cost, so it runs once per session, not once per
    test class."""
    return staticcheck.run_tree()


# ---------------------------------------------------------------------------
# Per-rule fixtures. POSITIVE[rule] snippets each yield >= 1 finding of
# that rule; SUPPRESSED[rule] snippets are positives with a valid inline
# suppression; CLEAN[rule] snippets yield none.
# ---------------------------------------------------------------------------

POSITIVE = {
    "key-hygiene": {
        "pipelinedp_tpu/fix_keys.py": (
            "import jax\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (3,))\n"
            "    b = jax.random.uniform(key, (3,))\n"
            "    return a + b\n"),
    },
    "host-rng": {
        "pipelinedp_tpu/fix_rng.py": (
            "import numpy as np\n"
            "_rng = np.random.default_rng()\n"
            "def f():\n"
            "    return np.random.rand()\n"),
    },
    "host-transfer": {
        "pipelinedp_tpu/parallel/fix_transfer.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n"),
    },
    "lock-discipline": {
        "pipelinedp_tpu/fix_lock.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._state = 0\n"
            "    def bump(self):\n"
            "        self._state += 1\n"),
    },
    "jit-boundary": {
        "pipelinedp_tpu/fix_jit.py": (
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, static_argnames=('n',))\n"
            "def kernel(x, n):\n"
            "    return x * n\n"),
        # Python branch on a traced argument.
        "pipelinedp_tpu/fix_jit_if.py": (
            "import jax\n"
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n"),
        # Bare AOT executable outside runtime/aot.py: its compiles and
        # dispatches skip the attribution aot_probe carries.
        "pipelinedp_tpu/fix_jit_aot.py": (
            "import jax\n"
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    return x * 2\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n"
            "def warm(x):\n"
            "    return kernel.lower(x).compile()\n"),
    },
    "registry-drift": {
        "pipelinedp_tpu/runtime/telemetry.py": (
            "def _counter(name, help_text):\n"
            "    return (name, 'counter', help_text)\n"
            "REGISTRY = dict(\n"
            "    a=_counter('used_counter', 'h'),\n"
            "    b=_counter('ghost_counter', 'h'))\n"),
        "pipelinedp_tpu/fix_user.py": (
            "from pipelinedp_tpu.runtime import telemetry\n"
            "def f():\n"
            "    telemetry.record('used_counter')\n"
            "    telemetry.record('undeclared_counter')\n"),
    },
    "knob-validation": {
        "pipelinedp_tpu/runtime/entry.py": (
            "from pipelinedp_tpu import input_validators\n"
            "def runtime_entry(kind):\n"
            "    def deco(fn):\n"
            "        def wrapper(*args, timeout_s=None, new_knob=False,\n"
            "                    **kwargs):\n"
            "            if timeout_s is not None:\n"
            "                input_validators.validate_timeout_s(\n"
            "                    timeout_s, kind)\n"
            "            return fn(*args, **kwargs)\n"
            "        return wrapper\n"
            "    return deco\n"),
    },
    "broad-except": {
        "pipelinedp_tpu/fix_except.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:\n"
            "        return None\n"),
    },
    "release-taint": {
        # Raw factorize output crosses a helper, then lands in a
        # trace-span attribute un-noised: interprocedural positive.
        "pipelinedp_tpu/columnar.py": (
            "def factorize(raw):\n"
            "    return raw, raw\n"),
        "pipelinedp_tpu/fix_taint.py": (
            "from pipelinedp_tpu.columnar import factorize\n"
            "from pipelinedp_tpu.runtime import trace\n"
            "def first_key(raw):\n"
            "    codes, vocab = factorize(raw)\n"
            "    return vocab[0]\n"
            "def f(raw):\n"
            "    key = first_key(raw)\n"
            "    with trace.span('encode', first=key):\n"
            "        pass\n"),
    },
    "lock-order": {
        # Opposite-order acquisition (deadlock cycle) plus a blocking
        # join under a lock.
        "pipelinedp_tpu/fix_lockorder.py": (
            "import threading\n"
            "_lock_a = threading.Lock()\n"
            "_lock_b = threading.Lock()\n"
            "def f():\n"
            "    with _lock_a:\n"
            "        with _lock_b:\n"
            "            pass\n"
            "def g(t):\n"
            "    with _lock_b:\n"
            "        with _lock_a:\n"
            "            t.join()\n"),
    },
    "budget-flow": {
        # A MechanismSpec built outside budget_accounting.py never hits
        # the ledger.
        "pipelinedp_tpu/fix_budget.py": (
            "from pipelinedp_tpu.budget_accounting import MechanismSpec\n"
            "def rogue(mech_type):\n"
            "    return MechanismSpec(mechanism_type=mech_type)\n"),
    },
    "thread-escape": {
        # A module global written by one thread root and read by
        # another with no lock anywhere.
        "pipelinedp_tpu/fix_threads.py": (
            "import threading\n"
            "_shared = {}\n"
            "def _worker():\n"
            "    _shared['k'] = 1\n"
            "def _monitor():\n"
            "    return _shared.get('k')\n"
            "def start():\n"
            "    threading.Thread(target=_worker).start()\n"
            "    threading.Thread(target=_monitor).start()\n"),
    },
    "determinism": {
        # set() iteration order flowing into a driver release.
        "pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, col):\n"
            "    keys = set(col)\n"
            "    return [(k, 1) for k in keys]\n"),
    },
    "dtype-discipline": {
        # All three sub-patterns: an implicit f32 accumulator, a
        # fractional float-literal equality, and a reduction narrowed
        # to int32 in one expression.
        "pipelinedp_tpu/ops/fix_dtype.py": (
            "import jax.numpy as jnp\n"
            "def f(x):\n"
            "    total = jnp.sum(x)\n"
            "    ids = jnp.cumsum(x).astype(jnp.int32)\n"
            "    if total == 0.5:\n"
            "        return ids\n"
            "    return total\n"),
    },
}

SUPPRESSED = {
    "key-hygiene": {
        "pipelinedp_tpu/fix_keys.py": (
            "import jax\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (3,))\n"
            "    b = jax.random.uniform(key, (3,))  "
            "# staticcheck: disable=key-hygiene — fixture: deliberate "
            "reuse under test\n"
            "    return a + b\n"),
    },
    "host-rng": {
        "pipelinedp_tpu/fix_rng.py": (
            "import random\n"
            "_jitter = random.Random()  "
            "# staticcheck: disable=host-rng — backoff jitter, not noise\n"),
    },
    "host-transfer": {
        "pipelinedp_tpu/parallel/fix_transfer.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)  "
            "# staticcheck: disable=host-transfer — O(D) control table\n"),
    },
    "lock-discipline": {
        "pipelinedp_tpu/fix_lock.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._state = 0\n"
            "    def _bump_locked(self):  "
            "# staticcheck: disable=lock-discipline — caller holds _lock\n"
            "        self._state += 1\n"),
    },
    "jit-boundary": {
        "pipelinedp_tpu/fix_jit.py": (
            "import jax\n"
            "@jax.jit\n"
            "def kernel(x):  "
            "# staticcheck: disable=jit-boundary — fixture: attribution "
            "not wanted here\n"
            "    return x\n"),
        "pipelinedp_tpu/fix_jit_aot.py": (
            "import jax\n"
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    return x * 2\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n"
            "def warm(x):\n"
            "    return kernel.lower(x).compile()  "
            "# staticcheck: disable=jit-boundary — fixture: warmup-only "
            "executable, discarded after the shape probe\n"),
    },
    "registry-drift": {
        "pipelinedp_tpu/runtime/telemetry.py": (
            "def _counter(name, help_text):\n"
            "    return (name, 'counter', help_text)\n"
            "REGISTRY = dict(\n"
            "    b=_counter('ghost_counter', 'h'))  "
            "# staticcheck: disable=registry-drift — fixture ghost\n"),
    },
    "knob-validation": {
        "pipelinedp_tpu/runtime/entry.py": (
            "def runtime_entry(kind):\n"
            "    def deco(fn):\n"
            "        def wrapper(*args, new_knob=False, **kwargs):  "
            "# staticcheck: disable=knob-validation — fixture knob\n"
            "            return fn(*args, **kwargs)\n"
            "        return wrapper\n"
            "    return deco\n"),
    },
    "broad-except": {
        "pipelinedp_tpu/fix_except.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:  # noqa: BLE001 - probe may raise "
            "anything; None is the sentinel\n"
            "        return None\n"),
    },
    "release-taint": {
        "pipelinedp_tpu/columnar.py": (
            "def factorize(raw):\n"
            "    return raw, raw\n"),
        "pipelinedp_tpu/fix_taint.py": (
            "from pipelinedp_tpu.columnar import factorize\n"
            "from pipelinedp_tpu.runtime import trace\n"
            "def f(raw):\n"
            "    codes, vocab = factorize(raw)\n"
            "    with trace.span('encode', first=vocab[0]):  "
            "# staticcheck: disable=release-taint — fixture: sanctioned "
            "debug surface, gated off in production\n"
            "        pass\n"),
    },
    "lock-order": {
        "pipelinedp_tpu/fix_lockorder.py": (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "def f(t):\n"
            "    with _lock:\n"
            "        t.join()  "
            "# staticcheck: disable=lock-order — fixture: teardown "
            "path, no other thread can want this lock anymore\n"),
    },
    "budget-flow": {
        "pipelinedp_tpu/fix_budget.py": (
            "from pipelinedp_tpu.budget_accounting import MechanismSpec\n"
            "def probe(mech_type):\n"
            "    return MechanismSpec(mechanism_type=mech_type)  "
            "# staticcheck: disable=budget-flow — fixture: test-only "
            "spec probe, never released\n"),
    },
    "thread-escape": {
        # Findings anchor at the racing WRITE; the suppression sits
        # there.
        "pipelinedp_tpu/fix_threads.py": (
            "import threading\n"
            "_shared = {}\n"
            "def _worker():\n"
            "    _shared['k'] = 1  "
            "# staticcheck: disable=thread-escape — fixture: "
            "single-writer latch, reader tolerates staleness\n"
            "def _monitor():\n"
            "    return _shared.get('k')\n"
            "def start():\n"
            "    threading.Thread(target=_worker).start()\n"
            "    threading.Thread(target=_monitor).start()\n"),
    },
    "determinism": {
        "pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, col):\n"
            "    keys = set(col)\n"
            "    return [(k, 1) for k in keys]  "
            "# staticcheck: disable=determinism — fixture: sanctioned "
            "unordered debug release, gated off in production\n"),
    },
    "dtype-discipline": {
        "pipelinedp_tpu/ops/fix_dtype.py": (
            "import jax.numpy as jnp\n"
            "def f(x):\n"
            "    total = jnp.sum(x)  "
            "# staticcheck: disable=dtype-discipline — fixture: bool "
            "mask popcount, bounded by the block size\n"
            "    return total\n"),
    },
}

CLEAN = {
    "key-hygiene": {
        "pipelinedp_tpu/fix_keys.py": (
            "import jax\n"
            "def f(key):\n"
            "    k1, k2 = jax.random.split(key)\n"
            "    a = jax.random.normal(k1, (3,))\n"
            "    b = jax.random.uniform(k2, (3,))\n"
            "    return a + b\n"
            "def g(key, blocks):\n"
            "    out = []\n"
            "    for b in blocks:\n"
            "        kb = jax.random.fold_in(key, b)\n"
            "        out.append(jax.random.normal(kb, ()))\n"
            "    return out\n"),
    },
    "host-rng": {
        "pipelinedp_tpu/fix_rng.py": (
            "import numpy as np\n"
            "def f(seed):\n"
            "    rng = np.random.default_rng(seed)\n"
            "    return rng.uniform()\n"),
    },
    "host-transfer": {
        # Same call outside a device-resident directory: no finding.
        "pipelinedp_tpu/fix_transfer.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n"),
        # The multi-process count exchange: the on-device-reduced,
        # replicated stats vector crossing through mesh.host_fetch is
        # the ONLY sanctioned host traffic on the cross-host reshard
        # path — and host_fetch routing needs no suppression.
        "pipelinedp_tpu/parallel/fix_exchange.py": (
            "from pipelinedp_tpu.parallel.mesh import host_fetch\n"
            "def exchange_capacities(stats_dev):\n"
            "    max_send, max_recv, total = (\n"
            "        int(x) for x in host_fetch(stats_dev))\n"
            "    return max_send, max_recv, total\n"),
    },
    "lock-discipline": {
        "pipelinedp_tpu/fix_lock.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._state = 0\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._state += 1\n"),
    },
    "jit-boundary": {
        "pipelinedp_tpu/fix_jit.py": (
            "import functools\n"
            "import jax\n"
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "@functools.partial(jax.jit, static_argnames=('n',))\n"
            "def kernel(x, n):\n"
            "    if n > 2:\n"          # static arg: Python branch is fine
            "        return x * n\n"
            "    return x\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n"),
        # aot_probe is probe-equivalent attribution (it wraps probe_jit
        # and counts AOT compiles/dispatches itself), and the
        # .lower().compile() inside runtime/aot.py is the sanctioned
        # site.
        "pipelinedp_tpu/fix_jit_aot.py": (
            "import functools\n"
            "import jax\n"
            "from pipelinedp_tpu.runtime import aot as rt_aot\n"
            "@functools.partial(jax.jit, static_argnames=('n',))\n"
            "def kernel(x, n):\n"
            "    return x * n\n"
            "kernel = rt_aot.aot_probe('kernel', kernel, "
            "static_argnames=('n',))\n"),
    },
    "registry-drift": {
        "pipelinedp_tpu/runtime/telemetry.py": (
            "def _counter(name, help_text):\n"
            "    return (name, 'counter', help_text)\n"
            "REGISTRY = dict(a=_counter('used_counter', 'h'))\n"),
        "pipelinedp_tpu/fix_user.py": (
            "from pipelinedp_tpu.runtime import telemetry\n"
            "def f():\n"
            "    telemetry.record('used_counter')\n"),
    },
    "knob-validation": {
        "pipelinedp_tpu/runtime/entry.py": (
            "from pipelinedp_tpu import input_validators\n"
            "def runtime_entry(kind):\n"
            "    def deco(fn):\n"
            "        def wrapper(*args, timeout_s=None, **kwargs):\n"
            "            if timeout_s is not None:\n"
            "                input_validators.validate_timeout_s(\n"
            "                    timeout_s, kind)\n"
            "            return fn(*args, **kwargs)\n"
            "        return wrapper\n"
            "    return deco\n"),
    },
    "broad-except": {
        "pipelinedp_tpu/fix_except.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except ValueError:\n"
            "        return None\n"),
    },
    "release-taint": {
        # The raw value passes through a mechanism's add_noise before
        # the span attr; the row COUNT (len) is declassified metadata.
        "pipelinedp_tpu/columnar.py": (
            "def factorize(raw):\n"
            "    return raw, raw\n"),
        "pipelinedp_tpu/fix_taint.py": (
            "from pipelinedp_tpu.columnar import factorize\n"
            "from pipelinedp_tpu.runtime import trace\n"
            "def f(raw, mech):\n"
            "    codes, vocab = factorize(raw)\n"
            "    noised = mech.add_noise(vocab[0])\n"
            "    with trace.span('encode', first=noised,\n"
            "                    rows=len(codes)):\n"
            "        pass\n"),
    },
    "lock-order": {
        # Consistent global order, blocking waits outside the lock.
        "pipelinedp_tpu/fix_lockorder.py": (
            "import threading\n"
            "_lock_a = threading.Lock()\n"
            "_lock_b = threading.Lock()\n"
            "def f(t):\n"
            "    with _lock_a:\n"
            "        with _lock_b:\n"
            "            pass\n"
            "def g(t):\n"
            "    with _lock_a:\n"
            "        with _lock_b:\n"
            "            pass\n"
            "    t.join()\n"),
    },
    "budget-flow": {
        # Construction inside budget_accounting.py, registered in the
        # same suite from request_budget: the sanctioned shape.
        "pipelinedp_tpu/budget_accounting.py": (
            "class MechanismSpec:\n"
            "    def __init__(self, mechanism_type=None):\n"
            "        self.mechanism_type = mechanism_type\n"
            "class BudgetAccountant:\n"
            "    def request_budget(self, mech_type):\n"
            "        spec = MechanismSpec(mechanism_type=mech_type)\n"
            "        self._register_mechanism(spec)\n"
            "        return spec\n"
            "    def _register_mechanism(self, mechanism):\n"
            "        pass\n"),
    },
    "thread-escape": {
        # Queue-mediated handoff: concurrency-primitive state is
        # synchronized by construction.
        "pipelinedp_tpu/fix_threads.py": (
            "import queue\n"
            "import threading\n"
            "_q = queue.Queue()\n"
            "def _producer():\n"
            "    _q.put(1)\n"
            "def _consumer():\n"
            "    return _q.get()\n"
            "def start():\n"
            "    threading.Thread(target=_producer).start()\n"
            "    threading.Thread(target=_consumer).start()\n"),
    },
    "determinism": {
        # sorted() is the sanctioned sanitizer.
        "pipelinedp_tpu/executor.py": (
            "def lazy_aggregate(backend, col):\n"
            "    keys = sorted(set(col))\n"
            "    return [(k, 1) for k in keys]\n"),
    },
    "dtype-discipline": {
        # Declared accumulators (dtype= / operand .astype), an exact
        # integral-float sentinel compare, probed narrowing, and a
        # non-device module where the rule does not apply at all.
        "pipelinedp_tpu/ops/fix_dtype.py": (
            "import jax.numpy as jnp\n"
            "def f(x):\n"
            "    total = jnp.sum(x, dtype=x.dtype)\n"
            "    ids = jnp.cumsum(x.astype(jnp.int32))\n"
            "    if total == 0.0:\n"
            "        return ids\n"
            "    wide = jnp.sum(x, dtype=jnp.float64)\n"
            "    return wide.astype(jnp.int32)\n"),
        "pipelinedp_tpu/fix_dtype_host.py": (
            "import jax.numpy as jnp\n"
            "def g(x):\n"
            "    return jnp.sum(x)\n"),
    },
}


class TestRuleFixtures:

    @pytest.mark.parametrize("rule", sorted(POSITIVE))
    def test_positive_fixture_fires(self, rule):
        found = _analyze(POSITIVE[rule], rule)
        assert found, f"positive fixture for {rule!r} produced no finding"
        assert all(f.rule_id == rule for f in found)

    @pytest.mark.parametrize("rule", sorted(SUPPRESSED))
    def test_suppressed_fixture_is_silent(self, rule):
        assert _analyze(SUPPRESSED[rule], rule) == []

    @pytest.mark.parametrize("rule", sorted(CLEAN))
    def test_clean_fixture_is_silent(self, rule):
        assert _analyze(CLEAN[rule], rule) == []

    def test_every_shipped_rule_has_fixtures(self):
        """A new rule cannot ship without positive/suppressed/clean
        fixtures — the meta-test the issue asks for."""
        shipped = set(staticcheck.rule_ids())
        assert shipped == set(POSITIVE), (
            "every shipped rule needs a positive fixture (and vice "
            "versa)")
        assert shipped == set(SUPPRESSED)
        assert shipped == set(CLEAN)


class TestRuleDetails:

    def test_key_reuse_reported_on_second_draw(self):
        (f,) = _analyze(POSITIVE["key-hygiene"], "key-hygiene")
        assert f.line == 4 and "second jax.random draw" in f.message

    def test_key_reassignment_resets_tracking(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (3,))\n"
            "    key = jax.random.fold_in(key, 1)\n"
            "    b = jax.random.uniform(key, (3,))\n"
            "    return a + b\n")}
        assert _analyze(src, "key-hygiene") == []

    def test_key_drawn_in_loop_without_derivation(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def f(key, n):\n"
            "    out = []\n"
            "    for i in range(n):\n"
            "        out.append(jax.random.normal(key, ()))\n"
            "    return out\n")}
        (f,) = _analyze(src, "key-hygiene")
        assert "loop" in f.message

    def test_stray_prngkey_flagged_outside_make_noise_key(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def f():\n"
            "    return jax.random.PRNGKey(42)\n")}
        (f,) = _analyze(src, "key-hygiene")
        assert "make_noise_key" in f.message
        sanctioned = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def make_noise_key(seed):\n"
            "    return jax.random.PRNGKey(seed)\n")}
        assert _analyze(sanctioned, "key-hygiene") == []

    def test_seeded_function_local_generator_is_allowed(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import numpy as np\n"
            "def f(rng=None):\n"
            "    rng = rng or np.random.default_rng(np.random."
            "SeedSequence())\n"
            "    return rng.normal()\n")}
        assert _analyze(src, "host-rng") == []

    def test_lock_discipline_module_form(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "_lock = threading.Lock()\n"
            "_counters = {}\n"
            "_GUARDED_BY = guarded_by('_lock', '_counters')\n"
            "def good(name):\n"
            "    with _lock:\n"
            "        _counters[name] = 1\n"
            "def bad(name):\n"
            "    _counters[name] = 1\n")}
        (f,) = _analyze(src, "lock-discipline")
        assert f.line == 10

    def test_lock_discipline_nested_function_resets_lock(self):
        """A callback defined under the lock RUNS outside it."""
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                return self._state\n"
            "        return cb\n")}
        (f,) = _analyze(src, "lock-discipline")
        assert f.line == 8

    def test_telemetry_record_without_lock_is_a_finding(self):
        """ACCEPTANCE: stripping the `with _lock:` from the REAL
        telemetry.record() produces a lock-discipline finding."""
        import pipelinedp_tpu.runtime.telemetry as tele
        with open(tele.__file__) as f:
            src = f.read()
        guarded = "    with _lock:\n        counters[name] += n"
        assert guarded in src, "telemetry.record() layout changed"
        broken = src.replace(guarded, "    counters[name] += n")
        mods = [staticcheck.parse_source(
            "pipelinedp_tpu/runtime/telemetry.py", broken)]
        found = staticcheck.analyze(
            mods, only_rules=["lock-discipline"]).active
        assert any("counters" in f.message for f in found), found
        # And the committed source is clean.
        mods = [staticcheck.parse_source(
            "pipelinedp_tpu/runtime/telemetry.py", src)]
        assert staticcheck.analyze(
            mods, only_rules=["lock-discipline"]).active == []

    def test_jit_boundary_probe_wrap_recognized(self):
        src = dict(POSITIVE["jit-boundary"])
        src["pipelinedp_tpu/fix_jit.py"] += (
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n")
        found = _analyze(src, "jit-boundary")
        # fix_jit.py is now wrapped; only the traced-if fixture remains.
        assert all(f.file != "pipelinedp_tpu/fix_jit.py" for f in found)

    def test_broad_except_requires_reason_after_ble001(self):
        src = {"pipelinedp_tpu/fix.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:  # noqa: BLE001\n"
            "        return None\n")}
        (f,) = _analyze(src, "broad-except")
        assert f.line == 4


class TestSuppressionMachinery:

    def test_reason_required_rule_ignores_reasonless_suppression(self):
        src = {"pipelinedp_tpu/parallel/fix.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)  "
            "# staticcheck: disable=host-transfer\n")}
        (f,) = _analyze(src, "host-transfer")
        assert "suppression ignored" in f.message

    def test_comment_only_line_suppresses_next_line(self):
        src = {"pipelinedp_tpu/fix.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    # staticcheck: disable=broad-except — fixture\n"
            "    except Exception:\n"
            "        return None\n")}
        assert _analyze(src, "broad-except") == []

    def test_disable_all(self):
        src = {"pipelinedp_tpu/fix_rng.py": (
            "import numpy as np\n"
            "_rng = np.random.default_rng()  "
            "# staticcheck: disable=all — fixture\n")}
        assert _analyze(src, "host-rng") == []

    def test_unknown_rule_id_rejected(self):
        with pytest.raises(ValueError, match="unknown rule"):
            staticcheck.analyze([], only_rules=["no-such-rule"])


class TestBaseline:

    def _transfer_module(self, tmp_path):
        pkg = tmp_path / "parallel"
        pkg.mkdir()
        f = pkg / "fix.py"
        f.write_text("import numpy as np\n"
                     "def f(x):\n"
                     "    return np.asarray(x)\n")
        return str(tmp_path)

    def test_update_then_clean_round_trip(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        assert staticcheck.main([root, "--baseline", base]) == 1
        assert staticcheck.main(
            [root, "--baseline", base, "--update-baseline"]) == 0
        assert staticcheck.main([root, "--baseline", base]) == 0

    def test_update_preserves_notes(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        with open(base) as f:
            payload = json.load(f)
        payload["entries"][0]["note"] = "O(D) control table"
        with open(base, "w") as f:
            json.dump(payload, f)
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        with open(base) as f:
            payload = json.load(f)
        assert payload["entries"][0]["note"] == "O(D) control table"

    def test_edited_line_resurfaces_and_entry_goes_stale(self, tmp_path,
                                                         monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        fix = tmp_path / "parallel" / "fix.py"
        fix.write_text(fix.read_text().replace(
            "np.asarray(x)", "np.asarray(x[:2])"))
        _analysis, active, baselined, stale, _mods = staticcheck.run_tree(
            [root], baseline_path=base)
        assert len(active) == 1 and not baselined and len(stale) == 1

    def test_baseline_matches_by_text_not_line(self, tmp_path,
                                               monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        fix = tmp_path / "parallel" / "fix.py"
        fix.write_text("# a new leading comment shifts every line\n" +
                       fix.read_text())
        assert staticcheck.main([root, "--baseline", base]) == 0


class TestCli:

    def test_json_format(self, tmp_path, capsys):
        pkg = tmp_path / "parallel"
        pkg.mkdir()
        (pkg / "fix.py").write_text("import numpy as np\n"
                                    "x = np.asarray([1])\n")
        rc = staticcheck.main([str(tmp_path), "--no-baseline",
                               "--format=json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert payload["n_findings"] == 1
        assert payload["findings"][0]["rule_id"] == "host-transfer"
        assert payload["rules_version"] == staticcheck.RULES_VERSION

    def test_list_rules(self, capsys):
        assert staticcheck.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in staticcheck.rule_ids():
            assert rid in out

    def test_rule_flag_filters_to_one_family(self, tmp_path, capsys):
        """--rule (repeatable) runs exactly the named families."""
        pkg = tmp_path / "parallel"
        pkg.mkdir()
        # One host-transfer finding AND one broad-except finding.
        (pkg / "fix.py").write_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    try:\n"
            "        return np.asarray(x)\n"
            "    except Exception:\n"
            "        return None\n")
        rc = staticcheck.main([str(tmp_path), "--no-baseline",
                               "--format=json", "--rule",
                               "host-transfer"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {f["rule_id"] for f in payload["findings"]} == \
            {"host-transfer"}
        rc = staticcheck.main([str(tmp_path), "--no-baseline",
                               "--format=json", "--rule",
                               "host-transfer", "--rule", "broad-except"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {f["rule_id"] for f in payload["findings"]} == \
            {"host-transfer", "broad-except"}

    def test_help_documents_exit_codes(self, capsys):
        with pytest.raises(SystemExit):
            staticcheck.main(["--help"])
        assert "exit codes" in capsys.readouterr().out

    def test_module_entry_point_runs(self):
        proc = subprocess.run(
            [sys.executable, "-m", "pipelinedp_tpu.staticcheck",
             "--list-rules"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0
        assert "key-hygiene" in proc.stdout


class TestTreeGate:
    """The tier-1 gate: the committed tree is clean."""

    def test_full_tree_has_no_unbaselined_findings(self, tree_result):
        _analysis, active, _baselined, _stale, _mods = tree_result
        assert active == [], "\n".join(f.render() for f in active)

    def test_no_stale_baseline_entries(self, tree_result):
        _analysis, _active, _baselined, stale, _mods = tree_result
        assert stale == [], stale

    def test_baseline_policy(self):
        """Acceptance: the interprocedural families (release-taint,
        lock-order, budget-flow) and the structural product rules run
        with EMPTY baselines — real findings were fixed or reason-noted
        inline, never grandfathered. The baseline carries only (a) the
        host-transfer triage and (b) key/RNG-hygiene waivers scoped to
        the benchmarks/examples trees (fixed-seed synthetic-data keys),
        every entry justified by a note."""
        entries = sc_baseline.load()
        assert entries, "expected the committed host-transfer triage"
        unnoted = [e for e in entries if not e.get("note")]
        assert not unnoted, unnoted
        for e in entries:
            if e["rule"] == "host-transfer":
                continue
            assert e["rule"] in ("key-hygiene", "host-rng"), e
            assert e["file"].split("/")[0] in ("benchmarks",
                                               "examples"), e
        interprocedural = [e for e in entries if e["rule"] in
                           ("release-taint", "lock-order", "budget-flow",
                            "thread-escape", "determinism")]
        assert interprocedural == [], interprocedural

    def test_every_reasoned_suppression_is_used(self, tree_result):
        analysis = tree_result[0]
        # The committed tree relies on inline suppressions (mesh jitter,
        # caller-holds-lock helpers, ops host-side helpers): they must
        # actually match findings, or they are dead comments.
        assert analysis.suppressed, "expected in-tree suppressions"

    def test_lock_graph_over_runtime_is_acyclic(self, tree_result):
        """Acceptance: the lock-acquisition graph over runtime/ (and the
        rest of the package) is PROVEN acyclic — any cycle would be an
        active lock-order finding, and the committed tree has none.
        Reuses the session tree fixture's parsed modules instead of
        re-loading the tree."""
        from pipelinedp_tpu.staticcheck import dataflow, rules
        modules = [m for m in tree_result[4]
                   if m.rel.startswith("pipelinedp_tpu/")]
        graph = rules._call_graph(modules)
        report = dataflow.run_locks(graph, dataflow.LockConfig(
            declared=rules._declared_locks(modules),
            blocking_attrs=rules.LOCK_BLOCKING_ATTRS,
            blocking_dotted=rules.LOCK_BLOCKING_DOTTED,
            blocking_funcs=rules.LOCK_BLOCKING_FUNCS))
        assert dataflow.find_lock_cycles(report.edges) == []

    def test_aux_trees_are_analyzed(self, tree_result):
        """benchmarks/ and examples/ ride the default pass for the
        AUX_RULES subset (key-hygiene, host-rng)."""
        modules = tree_result[4]
        rels = {m.rel.split("/")[0] for m in modules}
        assert "benchmarks" in rels and "examples" in rels

    def test_all_seven_threaded_subsystems_are_roots(self, tree_result):
        """Acceptance: every threaded subsystem the repo actually runs
        is DISCOVERED as a thread-escape root — a subsystem missing
        here is invisible to the race analysis (the bench receipt's
        thread_roots count quantifies the same domain)."""
        from pipelinedp_tpu.staticcheck import rules, threads
        modules = [m for m in tree_result[4]
                   if m.rel.startswith("pipelinedp_tpu/")]
        roots = threads.discover_roots(rules._call_graph(modules))
        by_func = {r.func for r in roots}
        expected = {
            # service worker pool
            ("pipelinedp_tpu/service/service.py",
             "DPAggregationService._worker_loop"),
            # blocked drivers' drainer thread
            ("pipelinedp_tpu/parallel/large_p.py",
             "_dispatch_blocks_overlapped.drainer"),
            # map_overlapped feeder + encode pool
            ("pipelinedp_tpu/runtime/pipeline.py", "map_overlapped.feed"),
            ("pipelinedp_tpu/runtime/pipeline.py",
             "map_overlapped.encode"),
            # watchdog monitor
            ("pipelinedp_tpu/runtime/watchdog.py",
             "Watchdog._run_monitor"),
            # metrics exporters (file loop + HTTP handler)
            ("pipelinedp_tpu/runtime/observability.py",
             "MetricsExporter._file_loop"),
            ("pipelinedp_tpu/runtime/observability.py",
             "_ScrapeHandler.do_GET"),
            # multihost children (subprocess entry)
            ("pipelinedp_tpu/runtime/multihost.py", "_child_main"),
        }
        missing = expected - by_func
        assert not missing, missing


class TestInterproceduralRules:
    """Detail behavior of the three dataflow families."""

    def test_taint_finding_carries_source_to_sink_path(self):
        (f,) = _analyze(POSITIVE["release-taint"], "release-taint")
        assert "columnar.factorize" in f.message
        assert "first_key" in f.message, f.message  # the intermediate hop
        assert "->" in f.message

    def test_taint_passes_through_unknown_callee(self):
        """Unknown-callee conservatism: a third-party hop never launders
        a tainted value."""
        src = dict(POSITIVE["release-taint"])
        src["pipelinedp_tpu/fix_taint.py"] = (
            "import mystery\n"
            "from pipelinedp_tpu.columnar import factorize\n"
            "from pipelinedp_tpu.runtime import trace\n"
            "def f(raw):\n"
            "    codes, vocab = factorize(raw)\n"
            "    blended = mystery.transform(vocab)\n"
            "    with trace.span('encode', first=blended):\n"
            "        pass\n")
        (f,) = _analyze(src, "release-taint")
        assert "columnar.factorize" in f.message

    def test_taint_cleared_by_registered_kernel_sanitizer(self):
        src = dict(CLEAN["release-taint"])
        src["pipelinedp_tpu/executor.py"] = (
            "def select_partitions_kernel(pid):\n"
            "    return pid\n")
        src["pipelinedp_tpu/fix_taint.py"] = (
            "from pipelinedp_tpu.columnar import factorize\n"
            "from pipelinedp_tpu.executor import select_partitions_kernel\n"
            "from pipelinedp_tpu.runtime import trace\n"
            "def f(raw):\n"
            "    codes, vocab = factorize(raw)\n"
            "    keep = select_partitions_kernel(codes)\n"
            "    with trace.span('select', kept=keep):\n"
            "        pass\n")
        assert _analyze(src, "release-taint") == []

    def test_driver_release_is_a_sink(self):
        src = {
            "pipelinedp_tpu/columnar.py": ("def encode(rows):\n"
                                           "    return rows\n"),
            "pipelinedp_tpu/executor.py": (
                "from pipelinedp_tpu.columnar import encode\n"
                "def lazy_aggregate(backend, col):\n"
                "    encoded = encode(col)\n"
                "    def generator():\n"
                "        yield encoded\n"
                "    return generator()\n"),
        }
        (f,) = _analyze(src, "release-taint")
        assert "driver release value" in f.message
        assert f.line == 5  # the yield, not the generator() forwarding

    def test_lock_cycle_reported(self):
        found = _analyze(POSITIVE["lock-order"], "lock-order")
        cycles = [f for f in found if "cycle" in f.message]
        assert cycles, found
        assert "_lock_a" in cycles[0].message and "_lock_b" in cycles[0].message

    def test_blocking_under_lock_reported_with_path(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "def helper(t):\n"
            "    t.join()\n"
            "def f(t):\n"
            "    with _lock:\n"
            "        helper(t)\n")}
        (f,) = _analyze(src, "lock-order")
        assert ".join()" in f.message and "helper" in f.message
        assert f.line == 7  # flagged at the held call site

    def test_released_lock_before_call_is_clean(self):
        """Scope accuracy: a call AFTER the with block holds nothing."""
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "_lock = threading.Lock()\n"
            "def f(t):\n"
            "    with _lock:\n"
            "        x = 1\n"
            "    t.join()\n"
            "    return x\n")}
        assert _analyze(src, "lock-order") == []

    def test_caller_holds_helper_verified_at_call_sites(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def _bump(self):  "
            "# staticcheck: disable=lock-discipline — caller holds "
            "_lock\n"
            "        self._state += 1\n"
            "    def good(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n"
            "    def bad(self):\n"
            "        self._bump()\n")}
        (f,) = _analyze(src, "lock-order")
        assert "caller holds" in f.message
        assert f.line == 11  # bad()'s unlocked call, not good()'s

    def test_spec_not_registered_in_suite_is_flagged(self):
        src = {"pipelinedp_tpu/budget_accounting.py": (
            "class MechanismSpec:\n"
            "    pass\n"
            "class Acc:\n"
            "    def request_budget(self, t):\n"
            "        spec = MechanismSpec()\n"
            "        return spec\n")}
        (f,) = _analyze(src, "budget-flow")
        assert "_register_mechanism" in f.message

    def test_discarded_accountant_request_budget_flagged(self):
        src = {"pipelinedp_tpu/fix.py": (
            "def setup(budget_accountant, t):\n"
            "    budget_accountant.request_budget(t)\n")}
        (f,) = _analyze(src, "budget-flow")
        assert "discarded" in f.message

    def test_combiner_request_budget_hook_not_flagged(self):
        """A combiner's request_budget stores its spec itself and
        returns None — the discard check is accountant-receivers only."""
        src = {"pipelinedp_tpu/fix.py": (
            "def setup(combiner, acc):\n"
            "    combiner.request_budget(acc)\n")}
        assert _analyze(src, "budget-flow") == []

    def test_register_outside_request_budget_flagged(self):
        src = {"pipelinedp_tpu/fix.py": (
            "def sneak(acc, mech):\n"
            "    acc._register_mechanism(mech)\n")}
        (f,) = _analyze(src, "budget-flow")
        assert "graph-build" in f.message


class TestSarif:
    """--format=sarif renders findings for standard CI viewers."""

    def _finding_tree(self, tmp_path):
        pkg = tmp_path / "parallel"
        pkg.mkdir()
        (pkg / "fix.py").write_text("import numpy as np\n"
                                    "x = np.asarray([1])\n")
        return str(tmp_path)

    def test_sarif_schema_golden(self, tmp_path, capsys):
        rc = staticcheck.main([self._finding_tree(tmp_path),
                               "--no-baseline", "--format=sarif"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert payload["version"] == "2.1.0"
        assert payload["$schema"].endswith("sarif-schema-2.1.0.json")
        run = payload["runs"][0]
        driver = run["tool"]["driver"]
        assert driver["name"] == "pipelinedp-tpu-staticcheck"
        assert driver["version"] == staticcheck.RULES_VERSION
        assert {r["id"] for r in driver["rules"]} == \
            set(staticcheck.rule_ids())
        (result,) = run["results"]
        assert result["ruleId"] == "host-transfer"
        assert result["level"] == "error"
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("parallel/fix.py")
        assert loc["region"]["startLine"] == 2
        assert result["message"]["text"]

    def test_sarif_clean_run_has_empty_results(self, tmp_path, capsys):
        (tmp_path / "ok.py").write_text("x = 1\n")
        rc = staticcheck.main([str(tmp_path), "--no-baseline",
                               "--format=sarif"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["runs"][0]["results"] == []

    def test_sarif_covers_new_rule_families(self):
        """The driver rule table carries the v3 families (CI viewers
        resolve ruleId against it)."""
        from pipelinedp_tpu.staticcheck.cli import to_sarif
        driver = to_sarif([], [])["runs"][0]["tool"]["driver"]
        ids = {r["id"] for r in driver["rules"]}
        assert {"thread-escape", "determinism"} <= ids
        assert driver["version"] == staticcheck.RULES_VERSION

    def test_sarif_over_full_tree_renders(self, tree_result):
        """Full-tree SARIF export (on the shared session analysis —
        no re-analysis) is schema-shaped and result-free on the clean
        committed tree."""
        from pipelinedp_tpu.staticcheck.cli import to_sarif
        _analysis, active, _baselined, stale, _mods = tree_result
        payload = to_sarif(active, stale)
        assert payload["version"] == "2.1.0"
        assert payload["runs"][0]["results"] == []


class TestIncremental:
    """--cache / --changed-only: byte-identical findings to a cold run."""

    def _tree(self, tmp_path):
        pkg = tmp_path / "parallel"
        pkg.mkdir()
        (pkg / "fix.py").write_text("import numpy as np\n"
                                    "def f(x):\n"
                                    "    return np.asarray(x)\n")
        (tmp_path / "other.py").write_text("def g():\n    return 1\n")
        return str(tmp_path)

    def _findings_json(self, capsys):
        payload = json.loads(capsys.readouterr().out)
        return payload["findings"], payload

    def test_cache_warm_run_is_byte_identical(self, tmp_path, capsys):
        root = self._tree(tmp_path)
        cache = str(tmp_path / "model.pkl")
        staticcheck.main([root, "--no-baseline", "--format=json",
                          "--cache", cache])
        cold, cold_payload = self._findings_json(capsys)
        assert cold_payload["cache"]["misses"] == 2
        staticcheck.main([root, "--no-baseline", "--format=json",
                          "--cache", cache])
        warm, warm_payload = self._findings_json(capsys)
        assert warm == cold
        assert warm_payload["cache"]["hits"] == 2
        assert warm_payload["cache"]["misses"] == 0

    def test_cache_detects_content_change(self, tmp_path, capsys):
        root = self._tree(tmp_path)
        cache = str(tmp_path / "model.pkl")
        staticcheck.main([root, "--no-baseline", "--format=json",
                          "--cache", cache])
        capsys.readouterr()
        fix = tmp_path / "parallel" / "fix.py"
        fix.write_text(fix.read_text() + "y = np.array([2])\n")
        rc = staticcheck.main([root, "--no-baseline", "--format=json",
                               "--cache", cache])
        findings, payload = self._findings_json(capsys)
        assert rc == 1
        assert len(findings) == 2  # the edit's new finding is seen
        assert payload["cache"]["misses"] == 1

    def test_changed_only_matches_cold_run(self, tmp_path, capsys):
        """Acceptance: --changed-only + cache produce byte-identical
        findings to a full cold run (git-diff-aware trust)."""
        root = self._tree(tmp_path)
        subprocess.run(["git", "init", "-q"], cwd=root, check=True)
        subprocess.run(["git", "-c", "user.email=t@t", "-c",
                        "user.name=t", "add", "-A"], cwd=root,
                       check=True)
        subprocess.run(["git", "-c", "user.email=t@t", "-c",
                        "user.name=t", "commit", "-qm", "seed"],
                       cwd=root, check=True)
        staticcheck.main([root, "--no-baseline", "--format=json"])
        cold, _ = self._findings_json(capsys)
        cache = str(tmp_path / "model.pkl")
        staticcheck.main([root, "--no-baseline", "--format=json",
                          "--cache", cache])
        capsys.readouterr()
        # Edit one file; the other is served from the cache untouched.
        fix = tmp_path / "parallel" / "fix.py"
        fix.write_text(fix.read_text().replace("asarray", "array"))
        rc = staticcheck.main([root, "--no-baseline", "--format=json",
                               "--cache", cache, "--changed-only"])
        changed, payload = self._findings_json(capsys)
        assert rc == 1
        assert payload["cache"]["trusted"] >= 1
        staticcheck.main([root, "--no-baseline", "--format=json"])
        cold_after, _ = self._findings_json(capsys)
        assert changed == cold_after
        assert changed != cold  # the edit really moved the finding

    def test_changed_only_requires_cache(self, capsys):
        assert staticcheck.main(["--changed-only"]) == 2

    def test_rules_version_bump_invalidates_cache(self, tmp_path,
                                                  capsys, monkeypatch):
        """A RULES_VERSION bump must cold-parse: --changed-only trusts
        cache entries without re-hashing, so an entry written under the
        old rule set would otherwise be served to a NEW rule set
        entirely unchecked."""
        from pipelinedp_tpu.staticcheck import cache as sc_cache
        from pipelinedp_tpu.staticcheck import core as sc_core
        root = self._tree(tmp_path)
        cache = str(tmp_path / "model.pkl")
        staticcheck.main([root, "--no-baseline", "--format=json",
                          "--cache", cache])
        capsys.readouterr()
        # Same version: warm hits.
        warm = sc_cache.ModelCache(cache)
        warm.get(str(tmp_path / "other.py"))
        assert warm.hits == 1
        # Bumped version: the whole cache is discarded, every file
        # re-parses.
        monkeypatch.setattr(sc_core, "RULES_VERSION",
                            sc_core.RULES_VERSION + "-bumped")
        cold = sc_cache.ModelCache(cache)
        cold.get(str(tmp_path / "other.py"))
        assert cold.hits == 0 and cold.misses == 1
        # And the bumped-version save round-trips under its own key.
        cold.save()
        again = sc_cache.ModelCache(cache)
        again.get(str(tmp_path / "other.py"))
        assert again.hits == 1
