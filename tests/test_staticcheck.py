"""The static analyzer itself + the tier-1 tree gate.

Four layers:

  * Per-rule fixtures: every shipped rule has a positive snippet (the
    rule fires), a suppressed snippet (a valid inline suppression
    silences it) and a clean snippet (no finding) — plus a meta-test
    that the fixture table covers every registered rule, so a new rule
    cannot ship untested.
  * Machinery: suppression reasons (reason-required rules ignore
    reasonless waivers), baseline round-trip (--update-baseline then a
    clean run), note preservation, stale-entry detection, CLI formats
    and exit codes.
  * The ACCEPTANCE fixture: removing the `with _lock:` from the real
    telemetry.record() source produces a lock-discipline finding.
  * The tier-1 gate: the full pass over pipelinedp_tpu/ has zero
    non-baselined findings, and the baseline carries only host-transfer
    entries, each with a non-empty note.
"""

import json
import subprocess
import sys

import pytest

from pipelinedp_tpu import staticcheck
from pipelinedp_tpu.staticcheck import baseline as sc_baseline

pytestmark = pytest.mark.staticcheck


def _analyze(sources, rule):
    """sources: {rel: src}. Returns active findings of `rule`."""
    mods = [staticcheck.parse_source(rel, src)
            for rel, src in sources.items()]
    return staticcheck.analyze(mods, only_rules=[rule]).active


# ---------------------------------------------------------------------------
# Per-rule fixtures. POSITIVE[rule] snippets each yield >= 1 finding of
# that rule; SUPPRESSED[rule] snippets are positives with a valid inline
# suppression; CLEAN[rule] snippets yield none.
# ---------------------------------------------------------------------------

POSITIVE = {
    "key-hygiene": {
        "pipelinedp_tpu/fix_keys.py": (
            "import jax\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (3,))\n"
            "    b = jax.random.uniform(key, (3,))\n"
            "    return a + b\n"),
    },
    "host-rng": {
        "pipelinedp_tpu/fix_rng.py": (
            "import numpy as np\n"
            "_rng = np.random.default_rng()\n"
            "def f():\n"
            "    return np.random.rand()\n"),
    },
    "host-transfer": {
        "pipelinedp_tpu/parallel/fix_transfer.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n"),
    },
    "lock-discipline": {
        "pipelinedp_tpu/fix_lock.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._state = 0\n"
            "    def bump(self):\n"
            "        self._state += 1\n"),
    },
    "jit-boundary": {
        "pipelinedp_tpu/fix_jit.py": (
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, static_argnames=('n',))\n"
            "def kernel(x, n):\n"
            "    return x * n\n"),
        # Python branch on a traced argument.
        "pipelinedp_tpu/fix_jit_if.py": (
            "import jax\n"
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n"),
    },
    "registry-drift": {
        "pipelinedp_tpu/runtime/telemetry.py": (
            "def _counter(name, help_text):\n"
            "    return (name, 'counter', help_text)\n"
            "REGISTRY = dict(\n"
            "    a=_counter('used_counter', 'h'),\n"
            "    b=_counter('ghost_counter', 'h'))\n"),
        "pipelinedp_tpu/fix_user.py": (
            "from pipelinedp_tpu.runtime import telemetry\n"
            "def f():\n"
            "    telemetry.record('used_counter')\n"
            "    telemetry.record('undeclared_counter')\n"),
    },
    "knob-validation": {
        "pipelinedp_tpu/runtime/entry.py": (
            "from pipelinedp_tpu import input_validators\n"
            "def runtime_entry(kind):\n"
            "    def deco(fn):\n"
            "        def wrapper(*args, timeout_s=None, new_knob=False,\n"
            "                    **kwargs):\n"
            "            if timeout_s is not None:\n"
            "                input_validators.validate_timeout_s(\n"
            "                    timeout_s, kind)\n"
            "            return fn(*args, **kwargs)\n"
            "        return wrapper\n"
            "    return deco\n"),
    },
    "broad-except": {
        "pipelinedp_tpu/fix_except.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:\n"
            "        return None\n"),
    },
}

SUPPRESSED = {
    "key-hygiene": {
        "pipelinedp_tpu/fix_keys.py": (
            "import jax\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (3,))\n"
            "    b = jax.random.uniform(key, (3,))  "
            "# staticcheck: disable=key-hygiene — fixture: deliberate "
            "reuse under test\n"
            "    return a + b\n"),
    },
    "host-rng": {
        "pipelinedp_tpu/fix_rng.py": (
            "import random\n"
            "_jitter = random.Random()  "
            "# staticcheck: disable=host-rng — backoff jitter, not noise\n"),
    },
    "host-transfer": {
        "pipelinedp_tpu/parallel/fix_transfer.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)  "
            "# staticcheck: disable=host-transfer — O(D) control table\n"),
    },
    "lock-discipline": {
        "pipelinedp_tpu/fix_lock.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._state = 0\n"
            "    def _bump_locked(self):  "
            "# staticcheck: disable=lock-discipline — caller holds _lock\n"
            "        self._state += 1\n"),
    },
    "jit-boundary": {
        "pipelinedp_tpu/fix_jit.py": (
            "import jax\n"
            "@jax.jit\n"
            "def kernel(x):  "
            "# staticcheck: disable=jit-boundary — fixture: attribution "
            "not wanted here\n"
            "    return x\n"),
    },
    "registry-drift": {
        "pipelinedp_tpu/runtime/telemetry.py": (
            "def _counter(name, help_text):\n"
            "    return (name, 'counter', help_text)\n"
            "REGISTRY = dict(\n"
            "    b=_counter('ghost_counter', 'h'))  "
            "# staticcheck: disable=registry-drift — fixture ghost\n"),
    },
    "knob-validation": {
        "pipelinedp_tpu/runtime/entry.py": (
            "def runtime_entry(kind):\n"
            "    def deco(fn):\n"
            "        def wrapper(*args, new_knob=False, **kwargs):  "
            "# staticcheck: disable=knob-validation — fixture knob\n"
            "            return fn(*args, **kwargs)\n"
            "        return wrapper\n"
            "    return deco\n"),
    },
    "broad-except": {
        "pipelinedp_tpu/fix_except.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:  # noqa: BLE001 - probe may raise "
            "anything; None is the sentinel\n"
            "        return None\n"),
    },
}

CLEAN = {
    "key-hygiene": {
        "pipelinedp_tpu/fix_keys.py": (
            "import jax\n"
            "def f(key):\n"
            "    k1, k2 = jax.random.split(key)\n"
            "    a = jax.random.normal(k1, (3,))\n"
            "    b = jax.random.uniform(k2, (3,))\n"
            "    return a + b\n"
            "def g(key, blocks):\n"
            "    out = []\n"
            "    for b in blocks:\n"
            "        kb = jax.random.fold_in(key, b)\n"
            "        out.append(jax.random.normal(kb, ()))\n"
            "    return out\n"),
    },
    "host-rng": {
        "pipelinedp_tpu/fix_rng.py": (
            "import numpy as np\n"
            "def f(seed):\n"
            "    rng = np.random.default_rng(seed)\n"
            "    return rng.uniform()\n"),
    },
    "host-transfer": {
        # Same call outside a device-resident directory: no finding.
        "pipelinedp_tpu/fix_transfer.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n"),
        # The multi-process count exchange: the on-device-reduced,
        # replicated stats vector crossing through mesh.host_fetch is
        # the ONLY sanctioned host traffic on the cross-host reshard
        # path — and host_fetch routing needs no suppression.
        "pipelinedp_tpu/parallel/fix_exchange.py": (
            "from pipelinedp_tpu.parallel.mesh import host_fetch\n"
            "def exchange_capacities(stats_dev):\n"
            "    max_send, max_recv, total = (\n"
            "        int(x) for x in host_fetch(stats_dev))\n"
            "    return max_send, max_recv, total\n"),
    },
    "lock-discipline": {
        "pipelinedp_tpu/fix_lock.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._state = 0\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._state += 1\n"),
    },
    "jit-boundary": {
        "pipelinedp_tpu/fix_jit.py": (
            "import functools\n"
            "import jax\n"
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "@functools.partial(jax.jit, static_argnames=('n',))\n"
            "def kernel(x, n):\n"
            "    if n > 2:\n"          # static arg: Python branch is fine
            "        return x * n\n"
            "    return x\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n"),
    },
    "registry-drift": {
        "pipelinedp_tpu/runtime/telemetry.py": (
            "def _counter(name, help_text):\n"
            "    return (name, 'counter', help_text)\n"
            "REGISTRY = dict(a=_counter('used_counter', 'h'))\n"),
        "pipelinedp_tpu/fix_user.py": (
            "from pipelinedp_tpu.runtime import telemetry\n"
            "def f():\n"
            "    telemetry.record('used_counter')\n"),
    },
    "knob-validation": {
        "pipelinedp_tpu/runtime/entry.py": (
            "from pipelinedp_tpu import input_validators\n"
            "def runtime_entry(kind):\n"
            "    def deco(fn):\n"
            "        def wrapper(*args, timeout_s=None, **kwargs):\n"
            "            if timeout_s is not None:\n"
            "                input_validators.validate_timeout_s(\n"
            "                    timeout_s, kind)\n"
            "            return fn(*args, **kwargs)\n"
            "        return wrapper\n"
            "    return deco\n"),
    },
    "broad-except": {
        "pipelinedp_tpu/fix_except.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except ValueError:\n"
            "        return None\n"),
    },
}


class TestRuleFixtures:

    @pytest.mark.parametrize("rule", sorted(POSITIVE))
    def test_positive_fixture_fires(self, rule):
        found = _analyze(POSITIVE[rule], rule)
        assert found, f"positive fixture for {rule!r} produced no finding"
        assert all(f.rule_id == rule for f in found)

    @pytest.mark.parametrize("rule", sorted(SUPPRESSED))
    def test_suppressed_fixture_is_silent(self, rule):
        assert _analyze(SUPPRESSED[rule], rule) == []

    @pytest.mark.parametrize("rule", sorted(CLEAN))
    def test_clean_fixture_is_silent(self, rule):
        assert _analyze(CLEAN[rule], rule) == []

    def test_every_shipped_rule_has_fixtures(self):
        """A new rule cannot ship without positive/suppressed/clean
        fixtures — the meta-test the issue asks for."""
        shipped = set(staticcheck.rule_ids())
        assert shipped == set(POSITIVE), (
            "every shipped rule needs a positive fixture (and vice "
            "versa)")
        assert shipped == set(SUPPRESSED)
        assert shipped == set(CLEAN)


class TestRuleDetails:

    def test_key_reuse_reported_on_second_draw(self):
        (f,) = _analyze(POSITIVE["key-hygiene"], "key-hygiene")
        assert f.line == 4 and "second jax.random draw" in f.message

    def test_key_reassignment_resets_tracking(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def f(key):\n"
            "    a = jax.random.normal(key, (3,))\n"
            "    key = jax.random.fold_in(key, 1)\n"
            "    b = jax.random.uniform(key, (3,))\n"
            "    return a + b\n")}
        assert _analyze(src, "key-hygiene") == []

    def test_key_drawn_in_loop_without_derivation(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def f(key, n):\n"
            "    out = []\n"
            "    for i in range(n):\n"
            "        out.append(jax.random.normal(key, ()))\n"
            "    return out\n")}
        (f,) = _analyze(src, "key-hygiene")
        assert "loop" in f.message

    def test_stray_prngkey_flagged_outside_make_noise_key(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def f():\n"
            "    return jax.random.PRNGKey(42)\n")}
        (f,) = _analyze(src, "key-hygiene")
        assert "make_noise_key" in f.message
        sanctioned = {"pipelinedp_tpu/fix.py": (
            "import jax\n"
            "def make_noise_key(seed):\n"
            "    return jax.random.PRNGKey(seed)\n")}
        assert _analyze(sanctioned, "key-hygiene") == []

    def test_seeded_function_local_generator_is_allowed(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import numpy as np\n"
            "def f(rng=None):\n"
            "    rng = rng or np.random.default_rng(np.random."
            "SeedSequence())\n"
            "    return rng.normal()\n")}
        assert _analyze(src, "host-rng") == []

    def test_lock_discipline_module_form(self):
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "_lock = threading.Lock()\n"
            "_counters = {}\n"
            "_GUARDED_BY = guarded_by('_lock', '_counters')\n"
            "def good(name):\n"
            "    with _lock:\n"
            "        _counters[name] = 1\n"
            "def bad(name):\n"
            "    _counters[name] = 1\n")}
        (f,) = _analyze(src, "lock-discipline")
        assert f.line == 10

    def test_lock_discipline_nested_function_resets_lock(self):
        """A callback defined under the lock RUNS outside it."""
        src = {"pipelinedp_tpu/fix.py": (
            "import threading\n"
            "from pipelinedp_tpu.runtime.concurrency import guarded_by\n"
            "class C:\n"
            "    _GUARDED_BY = guarded_by('_lock', '_state')\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                return self._state\n"
            "        return cb\n")}
        (f,) = _analyze(src, "lock-discipline")
        assert f.line == 8

    def test_telemetry_record_without_lock_is_a_finding(self):
        """ACCEPTANCE: stripping the `with _lock:` from the REAL
        telemetry.record() produces a lock-discipline finding."""
        import pipelinedp_tpu.runtime.telemetry as tele
        with open(tele.__file__) as f:
            src = f.read()
        guarded = "    with _lock:\n        counters[name] += n"
        assert guarded in src, "telemetry.record() layout changed"
        broken = src.replace(guarded, "    counters[name] += n")
        mods = [staticcheck.parse_source(
            "pipelinedp_tpu/runtime/telemetry.py", broken)]
        found = staticcheck.analyze(
            mods, only_rules=["lock-discipline"]).active
        assert any("counters" in f.message for f in found), found
        # And the committed source is clean.
        mods = [staticcheck.parse_source(
            "pipelinedp_tpu/runtime/telemetry.py", src)]
        assert staticcheck.analyze(
            mods, only_rules=["lock-discipline"]).active == []

    def test_jit_boundary_probe_wrap_recognized(self):
        src = dict(POSITIVE["jit-boundary"])
        src["pipelinedp_tpu/fix_jit.py"] += (
            "from pipelinedp_tpu.runtime import trace as rt_trace\n"
            "kernel = rt_trace.probe_jit('kernel', kernel)\n")
        found = _analyze(src, "jit-boundary")
        # fix_jit.py is now wrapped; only the traced-if fixture remains.
        assert all(f.file != "pipelinedp_tpu/fix_jit.py" for f in found)

    def test_broad_except_requires_reason_after_ble001(self):
        src = {"pipelinedp_tpu/fix.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:  # noqa: BLE001\n"
            "        return None\n")}
        (f,) = _analyze(src, "broad-except")
        assert f.line == 4


class TestSuppressionMachinery:

    def test_reason_required_rule_ignores_reasonless_suppression(self):
        src = {"pipelinedp_tpu/parallel/fix.py": (
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)  "
            "# staticcheck: disable=host-transfer\n")}
        (f,) = _analyze(src, "host-transfer")
        assert "suppression ignored" in f.message

    def test_comment_only_line_suppresses_next_line(self):
        src = {"pipelinedp_tpu/fix.py": (
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    # staticcheck: disable=broad-except — fixture\n"
            "    except Exception:\n"
            "        return None\n")}
        assert _analyze(src, "broad-except") == []

    def test_disable_all(self):
        src = {"pipelinedp_tpu/fix_rng.py": (
            "import numpy as np\n"
            "_rng = np.random.default_rng()  "
            "# staticcheck: disable=all — fixture\n")}
        assert _analyze(src, "host-rng") == []

    def test_unknown_rule_id_rejected(self):
        with pytest.raises(ValueError, match="unknown rule"):
            staticcheck.analyze([], only_rules=["no-such-rule"])


class TestBaseline:

    def _transfer_module(self, tmp_path):
        pkg = tmp_path / "parallel"
        pkg.mkdir()
        f = pkg / "fix.py"
        f.write_text("import numpy as np\n"
                     "def f(x):\n"
                     "    return np.asarray(x)\n")
        return str(tmp_path)

    def test_update_then_clean_round_trip(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        assert staticcheck.main([root, "--baseline", base]) == 1
        assert staticcheck.main(
            [root, "--baseline", base, "--update-baseline"]) == 0
        assert staticcheck.main([root, "--baseline", base]) == 0

    def test_update_preserves_notes(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        with open(base) as f:
            payload = json.load(f)
        payload["entries"][0]["note"] = "O(D) control table"
        with open(base, "w") as f:
            json.dump(payload, f)
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        with open(base) as f:
            payload = json.load(f)
        assert payload["entries"][0]["note"] == "O(D) control table"

    def test_edited_line_resurfaces_and_entry_goes_stale(self, tmp_path,
                                                         monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        fix = tmp_path / "parallel" / "fix.py"
        fix.write_text(fix.read_text().replace(
            "np.asarray(x)", "np.asarray(x[:2])"))
        _analysis, active, baselined, stale, _mods = staticcheck.run_tree(
            [root], baseline_path=base)
        assert len(active) == 1 and not baselined and len(stale) == 1

    def test_baseline_matches_by_text_not_line(self, tmp_path,
                                               monkeypatch):
        monkeypatch.chdir(tmp_path)
        root = self._transfer_module(tmp_path)
        base = str(tmp_path / "baseline.json")
        staticcheck.main([root, "--baseline", base, "--update-baseline"])
        fix = tmp_path / "parallel" / "fix.py"
        fix.write_text("# a new leading comment shifts every line\n" +
                       fix.read_text())
        assert staticcheck.main([root, "--baseline", base]) == 0


class TestCli:

    def test_json_format(self, tmp_path, capsys):
        pkg = tmp_path / "parallel"
        pkg.mkdir()
        (pkg / "fix.py").write_text("import numpy as np\n"
                                    "x = np.asarray([1])\n")
        rc = staticcheck.main([str(tmp_path), "--no-baseline",
                               "--format=json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert payload["n_findings"] == 1
        assert payload["findings"][0]["rule_id"] == "host-transfer"
        assert payload["rules_version"] == staticcheck.RULES_VERSION

    def test_list_rules(self, capsys):
        assert staticcheck.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in staticcheck.rule_ids():
            assert rid in out

    def test_module_entry_point_runs(self):
        proc = subprocess.run(
            [sys.executable, "-m", "pipelinedp_tpu.staticcheck",
             "--list-rules"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0
        assert "key-hygiene" in proc.stdout


class TestTreeGate:
    """The tier-1 gate: the committed tree is clean."""

    @pytest.fixture(scope="class")
    def tree_result(self):
        return staticcheck.run_tree()

    def test_full_tree_has_no_unbaselined_findings(self, tree_result):
        _analysis, active, _baselined, _stale, _mods = tree_result
        assert active == [], "\n".join(f.render() for f in active)

    def test_no_stale_baseline_entries(self, tree_result):
        _analysis, _active, _baselined, stale, _mods = tree_result
        assert stale == [], stale

    def test_baseline_carries_only_noted_host_transfer_entries(self):
        """Acceptance: rules (1), (2), (4), (5), (6) run with an EMPTY
        baseline — real findings were fixed, not grandfathered; only the
        host-transfer triage lives in the baseline, every entry
        justified by a note."""
        entries = sc_baseline.load()
        assert entries, "expected the committed host-transfer triage"
        assert {e["rule"] for e in entries} == {"host-transfer"}
        unnoted = [e for e in entries if not e.get("note")]
        assert not unnoted, unnoted

    def test_every_reasoned_suppression_is_used(self, tree_result):
        analysis = tree_result[0]
        # The committed tree relies on inline suppressions (mesh jitter,
        # caller-holds-lock helpers, ops host-side helpers): they must
        # actually match findings, or they are dead comments.
        assert analysis.suppressed, "expected in-tree suppressions"
