"""Tests for private_contribution_bounds (modeled on the reference's
tests/private_contribution_bounds_test.py patterns: candidate generation,
scoring values, deterministic choice at huge calculation_eps).
"""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import private_contribution_bounds as pcb
from pipelinedp_tpu.dataset_histograms import histograms as hist


def _params(noise=pdp.NoiseKind.LAPLACE,
            aggregation_eps=1.0,
            aggregation_delta=0.0,
            calculation_eps=1.0,
            upper_bound=100):
    return pdp.CalculatePrivateContributionBoundsParams(
        aggregation_noise_kind=noise,
        aggregation_eps=aggregation_eps,
        aggregation_delta=aggregation_delta,
        calculation_eps=calculation_eps,
        max_partitions_contributed_upper_bound=upper_bound)


def _l0_histogram(bin_specs):
    bins = [
        hist.FrequencyBin(lower=l, upper=l + 1, count=c, sum=l * c, max=l)
        for l, c in bin_specs
    ]
    return hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, bins)


class TestGeneratePossibleContributionBounds:

    def test_small(self):
        assert pcb.generate_possible_contribution_bounds(10) == list(
            range(1, 11))

    def test_three_digit_grid(self):
        bounds = pcb.generate_possible_contribution_bounds(10200)
        assert bounds[:999] == list(range(1, 1000))
        assert bounds[999:1003] == [1000, 1010, 1020, 1030]
        assert bounds[-3:] == [10000, 10100, 10200]

    def test_all_have_three_significant_digits(self):
        for b in pcb.generate_possible_contribution_bounds(10**6):
            assert b % (10**max(0, len(str(b)) - 3)) == 0


class TestL0ScoringFunction:

    def test_score_components_laplace(self):
        params = _params(upper_bound=10)
        histogram = _l0_histogram([(1, 5), (4, 2)])
        f = pcb.L0ScoringFunction(params, number_of_partitions=100,
                                  l0_histogram=histogram)
        # B = min(10, 100) = 10; laplace count noise std for l0=k, linf=1:
        # sqrt(2)*k/eps
        k = 2
        expected_noise = 100 * np.sqrt(2) * k / 1.0
        # dropped: 5 users at 1 → max(1-2,0)=0; 2 users at 4 → (4-2)*2 = 4
        expected_dropped = 4
        assert f.score(k) == pytest.approx(-0.5 * expected_noise -
                                           0.5 * expected_dropped)

    def test_score_components_gaussian(self):
        from pipelinedp_tpu import dp_computations as dp
        params = _params(noise=pdp.NoiseKind.GAUSSIAN, aggregation_delta=1e-5,
                         upper_bound=10)
        histogram = _l0_histogram([(1, 5), (4, 2)])
        f = pcb.L0ScoringFunction(params, number_of_partitions=100,
                                  l0_histogram=histogram)
        k = 2
        # Gaussian count noise std at l0=k, linf=1: analytic sigma for
        # (eps, delta) with l2 sensitivity sqrt(k).
        expected_noise = 100 * dp.compute_sigma(1.0, 1e-5, np.sqrt(k))
        expected_dropped = 4  # 2 users at l0=4 lose (4 - 2) partitions each
        assert f.score(k) == pytest.approx(
            -0.5 * expected_noise - 0.5 * expected_dropped, rel=1e-6)

    def test_global_sensitivity_capped_by_partitions(self):
        params = _params(upper_bound=1000)
        f = pcb.L0ScoringFunction(params, number_of_partitions=7,
                                  l0_histogram=_l0_histogram([(1, 1)]))
        assert f.global_sensitivity == 7
        assert f.is_monotonic

    def test_score_all_matches_scalar(self):
        params = _params(noise=pdp.NoiseKind.GAUSSIAN, aggregation_delta=1e-5,
                         upper_bound=50)
        histogram = _l0_histogram([(1, 10), (3, 5), (20, 2), (60, 1)])
        f = pcb.L0ScoringFunction(params, number_of_partitions=40,
                                  l0_histogram=histogram)
        ks = np.array([1, 2, 5, 10, 40])
        vectorized = f.score_all(ks)
        for k, v in zip(ks, vectorized):
            assert v == pytest.approx(f.score(int(k))), k


class TestPrivateL0Calculator:

    def test_deterministic_choice_with_huge_eps(self):
        # Huge calculation_eps → exponential mechanism ≈ argmax score.
        params = _params(calculation_eps=1e6, upper_bound=4)
        backend = pdp.LocalBackend()
        partitions = ['a', 'b', 'c', 'a']
        histogram = _l0_histogram([(1, 1000), (3, 1)])
        histograms_col = [
            hist.DatasetHistograms(histogram, None, None, None, None, None)
        ]
        calculator = pcb.PrivateL0Calculator(params, partitions,
                                             histograms_col, backend)
        result = list(calculator.calculate())
        assert len(result) == 1
        # Almost all users contribute to 1 partition; noise impact grows with
        # k, so k=1 maximizes the score.
        assert result[0] == 1

    def test_engine_entry_point(self):
        data = [(uid, pk) for uid in range(20) for pk in ('a', 'b')]
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda x: x[0],
            partition_extractor=lambda x: x[1],
            value_extractor=lambda x: 1)
        budget = pdp.NaiveBudgetAccountant(total_epsilon=1e6, total_delta=1e-5)
        engine = pdp.DPEngine(budget, pdp.LocalBackend())
        params = pdp.CalculatePrivateContributionBoundsParams(
            aggregation_noise_kind=pdp.NoiseKind.LAPLACE,
            aggregation_eps=1e6,
            aggregation_delta=0,
            calculation_eps=1e6,
            max_partitions_contributed_upper_bound=5)
        result = list(
            engine.calculate_private_contribution_bounds(
                data, params, extractors, partitions=['a', 'b']))
        assert len(result) == 1
        bounds = result[0]
        assert isinstance(bounds, pdp.PrivateContributionBounds)
        # every user contributes to exactly 2 partitions → l0=2 is optimal
        assert bounds.max_partitions_contributed == 2
