"""Tests for native partition-selection strategies.

The truncated-geometric closed form is validated against a direct evaluation
of the defining DP recurrence — the strongest possible internal check.
"""

import math

import numpy as np
import pytest

from pipelinedp_tpu import partition_selection as ps
from pipelinedp_tpu.aggregate_params import PartitionSelectionStrategy


def _truncated_geometric_recurrence(eps, delta, l0, n_max):
    """Direct O(n) evaluation of pi_n (Desfontaines et al. 2020)."""
    eps1, delta1 = eps / l0, delta / l0
    e = math.exp(eps1)
    pis = [0.0]
    for _ in range(n_max):
        prev = pis[-1]
        pi = min(e * prev + delta1, 1 - (1 - prev - delta1) / e, 1.0)
        pis.append(pi)
    return pis


class TestTruncatedGeometric:

    @pytest.mark.parametrize("eps,delta,l0", [(1.0, 1e-5, 1), (0.5, 1e-6, 3),
                                              (2.0, 1e-4, 10),
                                              (0.1, 1e-8, 1)])
    def test_closed_form_matches_recurrence(self, eps, delta, l0):
        selector = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, eps, delta, l0)
        n_max = 500
        expected = _truncated_geometric_recurrence(eps, delta, l0, n_max)
        for n in list(range(0, 50)) + [100, 200, 499]:
            assert selector.probability_of_keep(n) == pytest.approx(
                expected[n], rel=1e-9, abs=1e-15), f"n={n}"

    def test_monotone_and_limits(self):
        selector = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1.0, 1e-6, 2)
        probs = selector.probability_of_keep_vec(np.arange(0, 1000))
        assert probs[0] == 0.0
        assert np.all(np.diff(probs) >= -1e-15)
        assert probs[-1] == pytest.approx(1.0)
        # pi_1 = delta' for small delta.
        assert selector.probability_of_keep(1) == pytest.approx(1e-6 / 2)

    def test_large_n_stable(self):
        selector = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1.0, 1e-6, 1)
        assert selector.probability_of_keep(10**9) == 1.0

    def test_should_keep_extremes(self):
        selector = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1.0, 1e-6, 1)
        assert not selector.should_keep(0)
        assert selector.should_keep(10**6)


class TestThresholding:

    @pytest.mark.parametrize("strategy", [
        PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_delta_bound_and_monotonicity(self, strategy):
        eps, delta, l0 = 1.0, 1e-6, 3
        selector = ps.create_partition_selection_strategy(
            strategy, eps, delta, l0)
        # A partition with one user must keep with probability <= delta.
        assert selector.probability_of_keep(1) <= delta
        probs = selector.probability_of_keep_vec(np.arange(0, 200))
        assert np.all(np.diff(probs) >= -1e-15)
        assert probs[-1] > 0.999

    def test_laplace_threshold_midpoint(self):
        selector = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.LAPLACE_THRESHOLDING, 1.0, 1e-6, 1)
        t = selector.threshold
        # At n = threshold the keep probability is exactly 1/2.
        assert selector._probability_of_keep_shifted(np.array(
            [t]))[0] == pytest.approx(0.5)

    def test_gaussian_sigma_positive(self):
        selector = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING, 1.0, 1e-6, 4)
        assert selector.sigma > 0
        assert selector.threshold > 1


class TestPreThreshold:

    def test_pre_threshold_zeroes_small_counts(self):
        selector = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
            1.0,
            1e-6,
            1,
            pre_threshold=10)
        no_pre = ps.create_partition_selection_strategy(
            PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1.0, 1e-6, 1)
        for n in range(10):
            assert selector.probability_of_keep(n) == 0.0
        # Shifted by pre_threshold - 1.
        assert selector.probability_of_keep(15) == pytest.approx(
            no_pre.probability_of_keep(6))


class TestValidation:

    def test_invalid_args(self):
        create = ps.create_partition_selection_strategy
        strategy = PartitionSelectionStrategy.TRUNCATED_GEOMETRIC
        with pytest.raises(ValueError):
            create(strategy, 0, 1e-6, 1)
        with pytest.raises(ValueError):
            create(strategy, 1.0, 0, 1)
        with pytest.raises(ValueError):
            create(strategy, 1.0, 1e-6, 0)
        with pytest.raises(ValueError):
            create(strategy, 1.0, 1e-6, 1, pre_threshold=0)
