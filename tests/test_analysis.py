"""Tests for the analysis package (modeled on the reference's
analysis/tests/: data-structure validation, Poisson-binomial, per-partition
combiners, cross-partition combiners, utility-analysis e2e, tuning e2e,
pre-aggregation parity, dataset summary)."""

import dataclasses

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import analysis
from pipelinedp_tpu import combiners as dp_combiners
from pipelinedp_tpu.analysis import (cross_partition_combiners,
                                     data_structures, metrics,
                                     per_partition_combiners,
                                     poisson_binomial)
from pipelinedp_tpu.budget_accounting import MechanismSpec
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.dataset_histograms import computing_histograms as ch

BACKEND = pdp.LocalBackend()

DATA = [(uid, f"pk{uid % 3}", 1.0 + (uid % 5)) for uid in range(30)
        for _ in range(1 + uid % 2)]
EXTRACTORS = pdp.DataExtractors(privacy_id_extractor=lambda x: x[0],
                                partition_extractor=lambda x: x[1],
                                value_extractor=lambda x: x[2])


def _agg_params(metrics_list=None, **kwargs):
    defaults = dict(
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        metrics=metrics_list or [pdp.Metrics.COUNT],
        max_partitions_contributed=1,
        max_contributions_per_partition=1,
    )
    if metrics_list and pdp.Metrics.SUM in metrics_list:
        defaults.update(min_sum_per_partition=0.0, max_sum_per_partition=5.0)
    defaults.update(kwargs)
    return pdp.AggregateParams(**defaults)


class TestMultiParameterConfiguration:

    def test_requires_one_attribute(self):
        with pytest.raises(ValueError, match="at least 1"):
            data_structures.MultiParameterConfiguration()

    def test_same_length_enforced(self):
        with pytest.raises(ValueError, match="same length"):
            data_structures.MultiParameterConfiguration(
                max_partitions_contributed=[1, 2],
                max_contributions_per_partition=[1])

    def test_min_max_sum_together(self):
        with pytest.raises(ValueError, match="both set or both None"):
            data_structures.MultiParameterConfiguration(
                max_sum_per_partition=[1.0])

    def test_get_aggregate_params(self):
        config = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 3])
        params = _agg_params()
        assert config.size == 3
        got = [
            config.get_aggregate_params(params, i).max_partitions_contributed
            for i in range(3)
        ]
        assert got == [1, 2, 3]
        # blueprint untouched
        assert params.max_partitions_contributed == 1


class TestPoissonBinomial:

    def test_exact_binomial_case(self):
        # equal ps → binomial pmf
        pmf = poisson_binomial.compute_pmf([0.5] * 4)
        expected = np.array([1, 4, 6, 4, 1]) / 16
        np.testing.assert_allclose(pmf.probabilities, expected, atol=1e-12)
        assert pmf.start == 0

    def test_exact_sums_to_one(self):
        rng = np.random.default_rng(5)
        ps = rng.uniform(0, 1, size=50)
        pmf = poisson_binomial.compute_pmf(list(ps))
        assert pmf.probabilities.sum() == pytest.approx(1.0)

    def test_approximation_close_to_exact(self):
        rng = np.random.default_rng(7)
        ps = list(rng.uniform(0.2, 0.9, size=200))
        exact = poisson_binomial.compute_pmf(ps)
        exp, std, skew = poisson_binomial.compute_exp_std_skewness(ps)
        approx = poisson_binomial.compute_pmf_approximation(
            exp, std, skew, len(ps))
        # Compare overlapping region.
        for i, p_approx in enumerate(approx.probabilities, approx.start):
            assert p_approx == pytest.approx(exact.probabilities[i], abs=1e-3)

    def test_approximation_zero_sigma(self):
        pmf = poisson_binomial.compute_pmf_approximation(5.0, 0.0, 0.0, 10)
        assert pmf.start == 5
        np.testing.assert_array_equal(pmf.probabilities, [1.0])


def _combiner_params(eps=1e6,
                     delta=1e-6,
                     metrics_list=None,
                     **kwargs) -> dp_combiners.CombinerParams:
    spec = MechanismSpec(MechanismType.GAUSSIAN)
    spec.set_eps_delta(eps, delta)
    return dp_combiners.CombinerParams(spec,
                                       _agg_params(metrics_list, **kwargs))


class TestPerPartitionCombiners:

    def test_sum_combiner_accumulator(self):
        params = _combiner_params(metrics_list=[pdp.Metrics.SUM],
                                  max_partitions_contributed=2)
        combiner = per_partition_combiners.SumCombiner(params)
        counts = np.array([1, 1, 1])
        sums = np.array([3.0, 7.0, -1.0])  # clip to [0, 5]
        n_partitions = np.array([4, 1, 2])
        acc = combiner.create_accumulator((counts, sums, n_partitions))
        partition_sum, min_err, max_err, l0_err, l0_var = acc
        assert partition_sum == pytest.approx(9.0)
        assert min_err == pytest.approx(1.0)  # -1 → 0
        assert max_err == pytest.approx(-2.0)  # 7 → 5
        # keep probs: min(1, 2/4)=0.5, 1, 1 → contributions 3*0.5 dropped
        assert l0_err == pytest.approx(-(3.0 * 0.5))
        assert l0_var == pytest.approx(3.0**2 * 0.5 * 0.5)

    def test_count_combiner_uses_counts(self):
        params = _combiner_params(max_partitions_contributed=1,
                                  max_contributions_per_partition=2)
        combiner = per_partition_combiners.CountCombiner(params)
        counts = np.array([3, 1])
        sums = np.array([100.0, 100.0])  # ignored
        n_partitions = np.array([1, 1])
        acc = combiner.create_accumulator((counts, sums, n_partitions))
        partition_sum, _, max_err, l0_err, _ = acc
        assert partition_sum == pytest.approx(4.0)
        assert max_err == pytest.approx(-1.0)  # 3 clipped to 2
        assert l0_err == pytest.approx(0.0)

    def test_privacy_id_count_combiner(self):
        params = _combiner_params()
        combiner = per_partition_combiners.PrivacyIdCountCombiner(params)
        counts = np.array([5, 2, 0])
        acc = combiner.create_accumulator(
            (counts, np.zeros(3), np.array([1, 1, 1])))
        assert acc[0] == pytest.approx(2.0)  # indicators: 1+1+0

    def test_partition_selection_combiner_high_eps(self):
        params = _combiner_params(eps=1e3, delta=1e-4)
        combiner = per_partition_combiners.PartitionSelectionCombiner(params)
        counts = np.array([1] * 50)
        acc = combiner.create_accumulator(
            (counts, np.zeros(50), np.ones(50, dtype=int)))
        prob = combiner.compute_metrics(acc)
        assert prob == pytest.approx(1.0, abs=1e-6)

    def test_merge_switches_to_moments(self):
        params = _combiner_params()
        combiner = per_partition_combiners.PartitionSelectionCombiner(params)
        big = ([0.5] * 80, None)
        other = ([0.5] * 40, None)
        probs, moments = combiner.merge_accumulators(big, other)
        assert probs is None
        assert moments.count == 120
        assert moments.expectation == pytest.approx(60.0)

    def test_raw_statistics_combiner(self):
        combiner = per_partition_combiners.RawStatisticsCombiner()
        acc = combiner.create_accumulator(
            (np.array([2, 3, 1]), np.zeros(3), np.ones(3, dtype=int)))
        assert combiner.compute_metrics(acc) == metrics.RawStatistics(
            privacy_id_count=3, count=6)

    def test_compound_sparse_to_dense(self):
        params = _combiner_params()
        compound = per_partition_combiners.CompoundCombiner(
            [per_partition_combiners.CountCombiner(params)],
            return_named_tuple=False)
        acc = compound.create_accumulator((2, 4.0, 3))
        assert acc[0] == ([2], [4.0], [3])
        assert acc[1] is None
        # merging > 2*n_combiners rows converts to dense (later small sparse
        # residue may coexist with the dense part until compute_metrics)
        for i in range(5):
            acc = compound.merge_accumulators(
                acc, compound.create_accumulator((1, 1.0, 1)))
        _, dense = acc
        assert dense is not None
        result = compound.compute_metrics(acc)
        assert len(result) == 1
        assert result[0].sum == pytest.approx(7.0)  # counts 2+5*1


class TestCrossPartitionCombiners:

    def _sum_metrics(self, value=10.0):
        return metrics.SumMetrics(aggregation=pdp.Metrics.COUNT,
                                  sum=value,
                                  clipping_to_min_error=0.0,
                                  clipping_to_max_error=-2.0,
                                  expected_l0_bounding_error=-3.0,
                                  std_l0_bounding_error=2.0,
                                  std_noise=4.0,
                                  noise_kind=pdp.NoiseKind.GAUSSIAN)

    def test_data_dropped(self):
        info = cross_partition_combiners._sum_metrics_to_data_dropped(
            self._sum_metrics(), 0.5, pdp.Metrics.COUNT)
        assert info.l0 == pytest.approx(3.0)
        assert info.linf == pytest.approx(2.0)
        # survived = 10 - 3 - 2 = 5, dropped half
        assert info.partition_selection == pytest.approx(2.5)

    def test_value_errors(self):
        err = cross_partition_combiners._sum_metrics_to_value_error(
            self._sum_metrics(), keep_prob=1.0, weight=1.0)
        assert err.mean == pytest.approx(-5.0)
        assert err.variance == pytest.approx(4.0 + 16.0)
        assert err.rmse == pytest.approx(np.sqrt(25.0 + 20.0))

    def test_combiner_roundtrip_public(self):
        combiner = cross_partition_combiners.CrossPartitionCombiner(
            [pdp.Metrics.COUNT], public_partitions=True)
        per_partition = metrics.PerPartitionMetrics(
            1.0, metrics.RawStatistics(3, 6), [self._sum_metrics()])
        acc = combiner.create_accumulator(per_partition)
        acc = combiner.merge_accumulators(
            acc, combiner.create_accumulator(per_partition))
        report = combiner.compute_metrics(acc)
        assert report.partitions_info.num_dataset_partitions == 2
        assert len(report.metric_errors) == 1
        # two identical partitions → averaged rmse equals single-partition
        assert report.metric_errors[0].absolute_error.rmse == pytest.approx(
            np.sqrt(45.0))


class TestUtilityAnalysisE2E:

    def test_public_partitions_single_config(self):
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=_agg_params(
                [pdp.Metrics.COUNT],
                max_partitions_contributed=10,
                max_contributions_per_partition=10))
        public = ["pk0", "pk1", "pk2"]
        reports_col, per_partition_col = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS, public_partitions=public)
        reports = list(reports_col)
        assert len(reports) == 1
        report = reports[0]
        assert report.configuration_index == 0
        assert report.partitions_info.public_partitions
        assert report.partitions_info.num_dataset_partitions == 3
        errors = report.metric_errors[0]
        # bounds are loose → no contribution-bounding error
        assert errors.absolute_error.mean == pytest.approx(0.0, abs=1e-9)
        assert errors.ratio_data_dropped.l0 == pytest.approx(0.0, abs=1e-9)
        # per-partition output exists for every (pk, config)
        per_partition = list(per_partition_col)
        assert len(per_partition) == 3
        assert all(key[1] == 0 for key, _ in per_partition)

    def test_private_partitions_multi_config(self):
        config = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 5],
            max_contributions_per_partition=[1, 5])
        options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            multi_param_configuration=config)
        reports_col, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS)
        reports = sorted(list(reports_col),
                         key=lambda r: r.configuration_index)
        assert [r.configuration_index for r in reports] == [0, 1]
        for report in reports:
            assert not report.partitions_info.public_partitions
            assert report.partitions_info.kept_partitions is not None
            assert report.partitions_info.strategy is not None
        # config 1 has looser bounds → less bounding error, more noise
        drop0 = reports[0].metric_errors[0].ratio_data_dropped
        drop1 = reports[1].metric_errors[0].ratio_data_dropped
        assert drop0.l0 + drop0.linf >= drop1.l0 + drop1.linf
        assert (reports[0].metric_errors[0].noise_std <
                reports[1].metric_errors[0].noise_std)

    def test_strategy_sweep_annotates_each_config_with_own_strategy(self):
        # Regression: reference annotates every report with the LAST config's
        # strategy (configuration_index is unset when the annotation runs).
        strategies = [
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
            pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
        ]
        config = data_structures.MultiParameterConfiguration(
            partition_selection_strategy=strategies)
        options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            multi_param_configuration=config)
        reports_col, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS)
        reports = sorted(list(reports_col),
                         key=lambda r: r.configuration_index)
        assert [r.partitions_info.strategy for r in reports] == strategies

    def test_sum_analysis(self):
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.SUM],
                                         max_partitions_contributed=10))
        reports_col, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS,
            public_partitions=["pk0", "pk1", "pk2"])
        report = list(reports_col)[0]
        assert report.metric_errors[0].metric == pdp.Metrics.SUM
        assert report.utility_report_histogram is not None

    def test_analyze_engine_rejects_aggregate(self):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1,
                                               total_delta=1e-6)
        engine = analysis.UtilityAnalysisEngine(accountant, BACKEND)
        with pytest.raises(ValueError, match="can't be called"):
            engine.aggregate(DATA, _agg_params(), EXTRACTORS)

    def test_pre_aggregated_analysis(self):
        preagg = list(analysis.preaggregate(DATA, BACKEND, EXTRACTORS))
        pre_extractors = pdp.PreAggregateExtractors(
            partition_extractor=lambda row: row[0],
            preaggregate_extractor=lambda row: row[1])
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=_agg_params(
                [pdp.Metrics.COUNT],
                max_partitions_contributed=10,
                max_contributions_per_partition=10),
            pre_aggregated_data=True)
        reports_col, _ = analysis.perform_utility_analysis(
            preagg, BACKEND, options, pre_extractors,
            public_partitions=["pk0", "pk1", "pk2"])
        report = list(reports_col)[0]
        raw_options = dataclasses.replace(options, pre_aggregated_data=False)
        raw_report = list(
            analysis.perform_utility_analysis(
                DATA, BACKEND, raw_options, EXTRACTORS,
                public_partitions=["pk0", "pk1", "pk2"])[0])[0]
        assert report.metric_errors[0].absolute_error.rmse == pytest.approx(
            raw_report.metric_errors[0].absolute_error.rmse)


class TestPreAggregation:

    def test_preaggregate_values(self):
        data = [(1, 'a', 2.0), (1, 'a', 3.0), (1, 'b', 1.0), (2, 'a', 4.0)]
        ext = pdp.DataExtractors(privacy_id_extractor=lambda x: x[0],
                                 partition_extractor=lambda x: x[1],
                                 value_extractor=lambda x: x[2])
        got = sorted(analysis.preaggregate(data, BACKEND, ext))
        # (pk, (count, sum, n_partitions, n_contributions))
        assert got == [('a', (1, 4.0, 1, 1)), ('a', (2, 5.0, 2, 3)),
                       ('b', (1, 1.0, 2, 3))]


class TestParameterTuning:

    def test_constant_relative_step_candidates(self):
        from pipelinedp_tpu.analysis import parameter_tuning as pt
        h = ch._frequencies_to_histogram(
            np.array([1, 10, 100]), np.array([5, 5, 5]),
            name=__import__(
                'pipelinedp_tpu.dataset_histograms.histograms',
                fromlist=['HistogramType']).HistogramType.L0_CONTRIBUTIONS)
        candidates = pt._find_candidates_constant_relative_step(h, 5)
        assert candidates[0] == 1
        assert candidates[-1] == 100
        assert candidates == sorted(set(candidates))

    def test_tune_e2e_count(self):
        from pipelinedp_tpu.analysis import parameter_tuning as pt
        histograms = list(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))[0]
        options = pt.TuneOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            function_to_minimize=pt.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=pt.ParametersToTune(
                max_partitions_contributed=True,
                max_contributions_per_partition=True),
            number_of_parameter_candidates=9)
        result_col, _ = pt.tune(DATA, BACKEND, histograms, options,
                                EXTRACTORS,
                                public_partitions=["pk0", "pk1", "pk2"])
        result = list(result_col)[0]
        assert isinstance(result, pt.TuneResult)
        n = result.utility_analysis_parameters.size
        assert 0 <= result.index_best < n
        assert len(result.utility_reports) == n

    def test_tune_rejects_two_metrics(self):
        from pipelinedp_tpu.analysis import parameter_tuning as pt
        options = pt.TuneOptions(
            epsilon=1,
            delta=1e-5,
            aggregate_params=_agg_params(
                [pdp.Metrics.COUNT, pdp.Metrics.PRIVACY_ID_COUNT]),
            function_to_minimize=pt.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=pt.ParametersToTune(
                max_partitions_contributed=True))
        with pytest.raises(ValueError, match="only one metric"):
            pt._check_tune_args(options, True)


class TestDatasetSummary:

    def test_summary_counts(self):
        public = ["pk0", "pk1", "pk_unused"]
        summary = list(
            analysis.compute_public_partitions_summary(
                DATA, BACKEND, EXTRACTORS, public))[0]
        assert summary.num_dataset_public_partitions == 2
        assert summary.num_dataset_non_public_partitions == 1  # pk2
        assert summary.num_empty_public_partitions == 1  # pk_unused
