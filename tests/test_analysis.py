"""Tests for the analysis package (modeled on the reference's
analysis/tests/: data-structure validation, Poisson-binomial, the error-model
math, cross-partition reduction, utility-analysis e2e, tuning e2e,
pre-aggregation parity, dataset summary) — plus dense-kernel vs distributed
path parity, which the reference cannot test (it has only one path)."""

import dataclasses

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import analysis
from pipelinedp_tpu import partition_selection
from pipelinedp_tpu.analysis import (cross_partition_combiners,
                                     data_structures, error_model as em,
                                     kernels, metrics, parameter_tuning as pt,
                                     per_partition_combiners,
                                     poisson_binomial, utility_analysis)
from pipelinedp_tpu.budget_accounting import MechanismSpec
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.dataset_histograms import computing_histograms as ch

BACKEND = pdp.LocalBackend()

DATA = [(uid, f"pk{uid % 3}", 1.0 + (uid % 5)) for uid in range(30)
        for _ in range(1 + uid % 2)]
EXTRACTORS = pdp.DataExtractors(privacy_id_extractor=lambda x: x[0],
                                partition_extractor=lambda x: x[1],
                                value_extractor=lambda x: x[2])


def _agg_params(metrics_list=None, **kwargs):
    defaults = dict(
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        metrics=metrics_list or [pdp.Metrics.COUNT],
        max_partitions_contributed=1,
        max_contributions_per_partition=1,
    )
    if metrics_list and pdp.Metrics.SUM in metrics_list:
        defaults.update(min_sum_per_partition=0.0, max_sum_per_partition=5.0)
    defaults.update(kwargs)
    return pdp.AggregateParams(**defaults)


class TestMultiParameterConfiguration:

    def test_requires_one_attribute(self):
        with pytest.raises(ValueError, match="at least 1"):
            data_structures.MultiParameterConfiguration()

    def test_same_length_enforced(self):
        with pytest.raises(ValueError, match="same length"):
            data_structures.MultiParameterConfiguration(
                max_partitions_contributed=[1, 2],
                max_contributions_per_partition=[1])

    def test_min_max_sum_together(self):
        with pytest.raises(ValueError, match="both set or both None"):
            data_structures.MultiParameterConfiguration(
                max_sum_per_partition=[1.0])

    def test_get_aggregate_params(self):
        config = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 3])
        params = _agg_params()
        assert config.size == 3
        got = [
            config.get_aggregate_params(params, i).max_partitions_contributed
            for i in range(3)
        ]
        assert got == [1, 2, 3]
        # blueprint untouched
        assert params.max_partitions_contributed == 1


class TestPoissonBinomial:

    def test_exact_binomial_case(self):
        # equal ps → binomial pmf
        pmf = poisson_binomial.compute_pmf([0.5] * 4)
        expected = np.array([1, 4, 6, 4, 1]) / 16
        np.testing.assert_allclose(pmf.probabilities, expected, atol=1e-12)
        assert pmf.start == 0

    def test_exact_sums_to_one(self):
        rng = np.random.default_rng(5)
        ps = rng.uniform(0, 1, size=50)
        pmf = poisson_binomial.compute_pmf(list(ps))
        assert pmf.probabilities.sum() == pytest.approx(1.0)

    def test_approximation_close_to_exact(self):
        rng = np.random.default_rng(7)
        ps = list(rng.uniform(0.2, 0.9, size=200))
        exact = poisson_binomial.compute_pmf(ps)
        exp, std, skew = poisson_binomial.compute_exp_std_skewness(ps)
        approx = poisson_binomial.compute_pmf_approximation(
            exp, std, skew, len(ps))
        # Compare overlapping region.
        for i, p_approx in enumerate(approx.probabilities, approx.start):
            assert p_approx == pytest.approx(exact.probabilities[i], abs=1e-3)

    def test_approximation_zero_sigma(self):
        pmf = poisson_binomial.compute_pmf_approximation(5.0, 0.0, 0.0, 10)
        assert pmf.start == 5
        np.testing.assert_array_equal(pmf.probabilities, [1.0])


class TestErrorModel:
    """Unit tests of the closed-form stats math (same numeric expectations as
    the reference's per-partition combiner tests)."""

    def test_sum_stats(self):
        params = _agg_params([pdp.Metrics.SUM], max_partitions_contributed=2)
        stats = em.partition_stats(
            counts=np.array([1, 1, 1]),
            sums=np.array([3.0, 7.0, -1.0]),  # clip to [0, 5]
            n_partitions=np.array([4, 1, 2]),
            config_params=[params],
            metric_list=[pdp.Metrics.SUM])
        row = stats[0, 0]
        assert row[em.RAW] == pytest.approx(9.0)
        assert row[em.CLIP_MIN] == pytest.approx(1.0)  # -1 → 0
        assert row[em.CLIP_MAX] == pytest.approx(-2.0)  # 7 → 5
        # keep fractions: min(1, 2/4)=0.5, 1, 1 → 3*0.5 expected dropped
        assert row[em.L0_MEAN] == pytest.approx(-1.5)
        assert row[em.L0_VAR] == pytest.approx(3.0**2 * 0.25)

    def test_count_stats_use_counts(self):
        params = _agg_params(max_partitions_contributed=1,
                             max_contributions_per_partition=2)
        stats = em.partition_stats(
            counts=np.array([3, 1]),
            sums=np.array([100.0, 100.0]),  # ignored for COUNT
            n_partitions=np.array([1, 1]),
            config_params=[params],
            metric_list=[pdp.Metrics.COUNT])
        row = stats[0, 0]
        assert row[em.RAW] == pytest.approx(4.0)
        assert row[em.CLIP_MAX] == pytest.approx(-1.0)  # 3 clipped to 2
        assert row[em.L0_MEAN] == pytest.approx(0.0)

    def test_privacy_id_count_stats(self):
        stats = em.partition_stats(counts=np.array([5, 2, 0]),
                                   sums=np.zeros(3),
                                   n_partitions=np.array([1, 1, 1]),
                                   config_params=[_agg_params()],
                                   metric_list=[pdp.Metrics.PRIVACY_ID_COUNT])
        assert stats[0, 0, em.RAW] == pytest.approx(2.0)  # indicators 1+1+0

    def test_multi_config_broadcast(self):
        # 3 configs analyzed in one call: l0 = 1, 2, 4 against n_partitions=4.
        configs = [
            _agg_params(max_partitions_contributed=l0) for l0 in (1, 2, 4)
        ]
        stats = em.partition_stats(counts=np.array([1]),
                                   sums=np.zeros(1),
                                   n_partitions=np.array([4]),
                                   config_params=configs,
                                   metric_list=[pdp.Metrics.COUNT])
        np.testing.assert_allclose(stats[:, 0, em.L0_MEAN],
                                   [-0.75, -0.5, 0.0])

    def test_keep_probability_high_eps(self):
        selector = partition_selection.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1e3, 1e-4, 1,
            None)
        prob = em.host_keep_probability(np.ones(50), selector)
        assert prob == pytest.approx(1.0, abs=1e-6)

    def test_keep_probability_empty_partition(self):
        selector = partition_selection.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1.0, 1e-6, 1,
            None)
        assert em.host_keep_probability(np.zeros(0), selector) == 0.0

    def test_report_terms(self):
        # SumMetrics(sum=10, min_err=0, max_err=-2, l0_err=-3, l0_std=2,
        # noise_std=4) — same numbers as the reference's value-error test.
        stats = np.array([10.0, 0.0, -2.0, -3.0, 4.0])
        row = em.metric_report_terms(stats, 1.0, 1.0, 4.0)
        assert row[em.ABS_MEAN] == pytest.approx(-5.0)
        assert row[em.ABS_VAR] == pytest.approx(4.0 + 16.0)
        assert row[em.ABS_RMSE] == pytest.approx(np.sqrt(25.0 + 20.0))
        assert row[em.REL_RMSE] == pytest.approx(np.sqrt(45.0) / 10.0)

    def test_report_terms_data_dropped(self):
        stats = np.array([10.0, 0.0, -2.0, -3.0, 4.0])
        row = em.metric_report_terms(stats, 0.5, 1.0, 4.0)
        assert row[em.DROP_L0] == pytest.approx(3.0)
        assert row[em.DROP_LINF] == pytest.approx(2.0)
        # survived = 10 - 3 - 2 = 5, half dropped by selection
        assert row[em.DROP_PS] == pytest.approx(2.5)

    def test_report_terms_zero_value_relative(self):
        row = em.metric_report_terms(np.zeros(5), 1.0, 1.0, 4.0)
        assert row[em.REL_RMSE] == 0.0
        assert row[em.ABS_RMSE] == pytest.approx(4.0)


def _make_analyzer(metrics_list=None, configs=None, private=True, **kwargs):
    params_list = configs or [_agg_params(metrics_list, **kwargs)]
    metric_list = em.ordered_metrics(params_list[0])
    spec = MechanismSpec(MechanismType.GAUSSIAN)
    spec.set_eps_delta(1e3, 1e-4)
    sel_spec = None
    if private:
        sel_spec = MechanismSpec(MechanismType.GENERIC)
        sel_spec.set_eps_delta(1e3, 1e-4)
    return per_partition_combiners.PerPartitionAnalyzer(
        config_params=params_list,
        metric_list=metric_list,
        metric_specs=[spec] * len(metric_list),
        selection_spec=sel_spec)


class TestPerPartitionAnalyzer:

    def test_flat_output_layout(self):
        analyzer = _make_analyzer([pdp.Metrics.COUNT, pdp.Metrics.SUM])
        flat = analyzer.analyze_rows([(2, 3.0, 1, 2), (1, 1.0, 2, 3)])
        assert isinstance(flat[0], metrics.RawStatistics)
        assert flat[0] == metrics.RawStatistics(privacy_id_count=2, count=3)
        assert isinstance(flat[1], float)  # keep probability
        # canonical metric order: SUM before COUNT
        assert flat[2].aggregation == pdp.Metrics.SUM
        assert flat[3].aggregation == pdp.Metrics.COUNT
        assert len(flat) == 4

    def test_none_markers_ignored(self):
        analyzer = _make_analyzer(private=False)
        flat = analyzer.analyze_rows([None])
        assert flat[0] == metrics.RawStatistics(privacy_id_count=0, count=0)
        assert flat[1].sum == 0.0

    def test_high_eps_keep_probability(self):
        analyzer = _make_analyzer()
        flat = analyzer.analyze_rows([(1, 0.0, 1, 1)] * 50)
        assert flat[1] == pytest.approx(1.0, abs=1e-6)

    def test_pickle_roundtrip(self):
        import pickle
        analyzer = _make_analyzer()
        analyzer.resolve_mechanisms()
        clone = pickle.loads(pickle.dumps(analyzer))
        flat = clone.compute(clone.create_accumulator((1, 2.0, 1, 1)))
        assert flat[2].sum == pytest.approx(1.0)  # COUNT raw

    def test_accumulator_switches_to_dense(self):
        analyzer = _make_analyzer()
        cap = per_partition_combiners.SPARSE_CAP
        acc = analyzer.create_accumulator((1, 0.0, 1, 1))
        for _ in range(cap + 10):
            acc = analyzer.merge_accumulators(
                acc, analyzer.create_accumulator((1, 0.0, 1, 1)))
        assert acc[0] == "d"  # bounded: O(K) memory, not O(rows)
        flat = analyzer.compute(acc)
        assert flat[0].privacy_id_count == cap + 11
        assert flat[2].sum == pytest.approx(cap + 11)

    def test_accumulator_matches_full_row_analysis(self):
        # Incremental merge (crossing the sparse->dense switch) must agree
        # with analyzing the complete row list at once.
        analyzer = _make_analyzer()
        rng = np.random.default_rng(3)
        rows = [(int(c), float(s), int(n), int(c))
                for c, s, n in zip(rng.integers(1, 5, 150),
                                   rng.random(150) * 4,
                                   rng.integers(1, 9, 150))]
        acc = analyzer.create_accumulator(rows[0])
        for row in rows[1:]:
            acc = analyzer.merge_accumulators(
                acc, analyzer.create_accumulator(row))
        merged = analyzer.compute(acc)
        direct = analyzer.analyze_rows(list(rows))
        assert merged[0] == direct[0]
        assert merged[1] == pytest.approx(direct[1], abs=1e-9)  # keep prob
        for a, b in zip(merged[2:], direct[2:]):
            assert a.sum == pytest.approx(b.sum)
            assert a.expected_l0_bounding_error == pytest.approx(
                b.expected_l0_bounding_error)


class TestCrossPartitionAggregator:

    def _per_partition(self, value=10.0):
        sm = metrics.SumMetrics(aggregation=pdp.Metrics.COUNT,
                                sum=value,
                                clipping_to_min_error=0.0,
                                clipping_to_max_error=-2.0,
                                expected_l0_bounding_error=-3.0,
                                std_l0_bounding_error=2.0,
                                std_noise=4.0,
                                noise_kind=pdp.NoiseKind.GAUSSIAN)
        return metrics.PerPartitionMetrics(1.0, metrics.RawStatistics(3, 6),
                                           [sm])

    def test_roundtrip_public(self):
        aggregator = cross_partition_combiners.CrossPartitionAggregator(
            [pdp.Metrics.COUNT], public_partitions=True)
        acc = aggregator.create_accumulator([self._per_partition()])
        acc = aggregator.merge_accumulators(
            acc, aggregator.create_accumulator([self._per_partition()]))
        reports = aggregator.compute_reports(
            acc, np.array([[4.0]]), [pdp.NoiseKind.GAUSSIAN])
        assert len(reports) == 1
        report = reports[0]
        assert report.partitions_info.num_dataset_partitions == 2
        # two identical partitions → averaged rmse equals single-partition
        assert report.metric_errors[0].absolute_error.rmse == pytest.approx(
            np.sqrt(45.0))
        drop = report.metric_errors[0].ratio_data_dropped
        assert drop.l0 == pytest.approx(3.0 / 10.0)

    def test_merge_is_vector_addition(self):
        aggregator = cross_partition_combiners.CrossPartitionAggregator(
            [pdp.Metrics.COUNT], public_partitions=False)
        a1 = aggregator.create_accumulator([self._per_partition(10.0)])
        a2 = aggregator.create_accumulator([self._per_partition(20.0)])
        merged = aggregator.merge_accumulators(a1, a2)
        np.testing.assert_allclose(merged[0], a1[0] + a2[0])
        np.testing.assert_allclose(merged[1], a1[1] + a2[1])


def _numeric_leaves(obj, path=""):
    """Yields (path, float) for every numeric field of nested dataclasses."""
    if dataclasses.is_dataclass(obj):
        for f in dataclasses.fields(obj):
            yield from _numeric_leaves(getattr(obj, f.name),
                                       f"{path}.{f.name}")
    elif isinstance(obj, (list, tuple)):
        for i, item in enumerate(obj):
            yield from _numeric_leaves(item, f"{path}[{i}]")
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        yield path, float(obj)


def assert_reports_close(r1, r2, rel=1e-9, abs_tol=1e-9):
    leaves1 = dict(_numeric_leaves(r1))
    leaves2 = dict(_numeric_leaves(r2))
    assert leaves1.keys() == leaves2.keys()
    for path, v1 in leaves1.items():
        assert v1 == pytest.approx(leaves2[path], rel=rel, abs=abs_tol), path


def _run_distributed(data, options, data_extractors, public=None):
    """Drives the distributed cross-partition path (the one Beam/Spark use)
    over the LocalBackend op vocabulary."""
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=options.epsilon,
                                           total_delta=options.delta)
    engine = analysis.UtilityAnalysisEngine(accountant, BACKEND)
    reports, per_part = utility_analysis._perform_distributed(
        data, BACKEND, engine, accountant, options, data_extractors, public)
    return list(reports), list(per_part)


class TestDenseDistributedParity:
    """The dense XLA sweep and the distributed per-partition path implement
    the same error model; their reports must agree."""

    def _options(self, public, multi=True, metrics_list=None):
        config = None
        if multi:
            config = data_structures.MultiParameterConfiguration(
                max_partitions_contributed=[1, 2, 3],
                max_contributions_per_partition=[1, 2, 2])
        return data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params(metrics_list),
            multi_param_configuration=config)

    def test_public_exact_parity(self):
        options = self._options(public=True,
                                metrics_list=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM])
        public = ["pk0", "pk1", "pk2", "pk_missing"]
        dense_reports, dense_pp = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS, public_partitions=public)
        dense_pp = list(dense_pp)
        dist_reports, dist_pp = _run_distributed(DATA, options, EXTRACTORS,
                                                 public)
        dense_reports = sorted(dense_reports,
                               key=lambda r: r.configuration_index)
        dist_reports = sorted(dist_reports,
                              key=lambda r: r.configuration_index)
        assert len(dense_reports) == len(dist_reports) == 3
        for d, h in zip(dense_reports, dist_reports):
            # Public path has no PMF approximation → tight agreement.
            assert_reports_close(d, h, rel=1e-6, abs_tol=1e-9)
        assert len(dense_pp) == len(dist_pp) == 4 * 3
        assert dict((k, v.metric_errors[0].sum) for k, v in dense_pp) == \
            pytest.approx(dict((k, v.metric_errors[0].sum) for k, v in dist_pp))

    def test_private_parity_within_pmf_tolerance(self):
        options = self._options(public=False)
        dense_reports, dense_pp = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS)
        dist_reports, _ = _run_distributed(DATA, options, EXTRACTORS)
        dense_reports = sorted(dense_reports,
                               key=lambda r: r.configuration_index)
        dist_reports = sorted(dist_reports,
                              key=lambda r: r.configuration_index)
        for d, h in zip(dense_reports, dist_reports):
            # Private selection: the device integrates a windowed
            # refined-normal PMF, the host the exact Poisson binomial for
            # small partitions — a few % drift is expected.
            assert_reports_close(d, h, rel=0.05, abs_tol=0.05)

    def test_noise_kind_sweep_parity(self):
        # noise_kind varies per configuration; noise stds must follow each
        # config's mechanism on both paths.
        config = data_structures.MultiParameterConfiguration(
            noise_kind=[pdp.NoiseKind.GAUSSIAN, pdp.NoiseKind.LAPLACE])
        options = data_structures.UtilityAnalysisOptions(
            epsilon=5,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            multi_param_configuration=config)
        public = ["pk0", "pk1", "pk2"]
        dense, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS, public_partitions=public)
        dist, _ = _run_distributed(DATA, options, EXTRACTORS, public)
        dense = sorted(dense, key=lambda r: r.configuration_index)
        dist = sorted(dist, key=lambda r: r.configuration_index)
        assert dense[0].metric_errors[0].noise_kind == pdp.NoiseKind.GAUSSIAN
        assert dense[1].metric_errors[0].noise_kind == pdp.NoiseKind.LAPLACE
        assert (dense[0].metric_errors[0].noise_std !=
                dense[1].metric_errors[0].noise_std)
        for d, h in zip(dense, dist):
            assert_reports_close(d, h, rel=1e-6, abs_tol=1e-9)

    def test_pre_threshold_parity(self):
        # pre_threshold shifts the selection curve; both paths must model it.
        options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT],
                                         pre_threshold=10))
        dense, _ = analysis.perform_utility_analysis(DATA, BACKEND, options,
                                                     EXTRACTORS)
        dist, _ = _run_distributed(DATA, options, EXTRACTORS)
        d, h = list(dense)[0], list(dist)[0]
        assert_reports_close(d, h, rel=0.05, abs_tol=0.05)
        # 10 privacy ids per partition, pre_threshold=10: keep probability
        # must be strictly below the unthresholded run's.
        base_options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]))
        base, _ = analysis.perform_utility_analysis(DATA, BACKEND,
                                                    base_options, EXTRACTORS)
        assert (d.partitions_info.kept_partitions.mean <
                list(base)[0].partitions_info.kept_partitions.mean)

    def test_private_parity_large_partitions(self):
        # >100 privacy ids per partition: both paths use the moment-based
        # approximation → tighter agreement.
        data = [(uid, f"pk{uid % 2}", 1.0) for uid in range(300)]
        options = self._options(public=False, multi=False)
        dense_reports, _ = analysis.perform_utility_analysis(
            data, BACKEND, options, EXTRACTORS)
        dist_reports, _ = _run_distributed(data, options, EXTRACTORS)
        assert_reports_close(
            sorted(dense_reports, key=lambda r: r.configuration_index)[0],
            sorted(dist_reports, key=lambda r: r.configuration_index)[0],
            rel=0.01,
            abs_tol=0.01)


class TestAnalysisSharded:
    """The ε-sweep over an 8-device mesh: per-shard segment sums + one psum
    must reproduce the single-device sweep exactly (the sweep draws no
    randomness)."""

    @pytest.mark.parametrize("public", [True, False])
    def test_mesh_matches_single_device(self, public):
        from pipelinedp_tpu.parallel import make_mesh
        mesh = make_mesh(n_devices=8)
        config = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 2, 3, 5],
            max_contributions_per_partition=[1, 2, 4, 4])
        options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params(
                [pdp.Metrics.COUNT, pdp.Metrics.SUM]),
            multi_param_configuration=config)
        publics = ["pk0", "pk1", "pk2"] if public else None
        mesh_reports, mesh_pp = analysis.perform_utility_analysis(
            DATA,
            pdp.TPUBackend(mesh=mesh),
            options,
            EXTRACTORS,
            public_partitions=publics)
        single_reports, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS, public_partitions=publics)
        mesh_reports = sorted(mesh_reports,
                              key=lambda r: r.configuration_index)
        single_reports = sorted(single_reports,
                                key=lambda r: r.configuration_index)
        assert len(mesh_reports) == 4
        for m, s in zip(mesh_reports, single_reports):
            assert_reports_close(m, s, rel=1e-9, abs_tol=1e-9)
        assert len(list(mesh_pp)) == 3 * 4


class TestAnalysisOnMultiProc:
    """The distributed analysis path through REAL process boundaries: the
    PerPartitionAnalyzer and its accumulators must pickle to workers and the
    reports must match the dense single-program path."""

    def test_matches_dense_path(self):
        backend = pdp.MultiProcLocalBackend(n_jobs=2)
        options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            multi_param_configuration=data_structures.
            MultiParameterConfiguration(max_partitions_contributed=[1, 3]))
        public = ["pk0", "pk1", "pk2"]
        mp_reports, mp_pp = analysis.perform_utility_analysis(
            DATA, backend, options, EXTRACTORS, public_partitions=public)
        mp_reports = sorted(mp_reports, key=lambda r: r.configuration_index)
        dense_reports, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS, public_partitions=public)
        dense_reports = sorted(dense_reports,
                               key=lambda r: r.configuration_index)
        assert len(mp_reports) == 2
        for mp, dense in zip(mp_reports, dense_reports):
            assert_reports_close(mp, dense, rel=1e-6, abs_tol=1e-9)
        assert len(list(mp_pp)) == 3 * 2


class TestKeepProbBatchKernel:

    @pytest.mark.parametrize("strategy", [
        pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
        pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_matches_host_selector(self, strategy):
        import jax.numpy as jnp
        params = [
            _agg_params(max_partitions_contributed=l0,
                        partition_selection_strategy=strategy)
            for l0 in (1, 3)
        ]
        cfg = kernels.build_config_arrays(params, [pdp.Metrics.COUNT],
                                          np.ones((2, 1)), (2.0, 1e-5))
        counts = np.arange(0, 60, dtype=np.float64)
        got = np.asarray(
            kernels._keep_prob_batch(jnp.asarray(np.tile(counts, (2, 1))),
                                     cfg))
        for ki, p in enumerate(params):
            selector = partition_selection.create_partition_selection_strategy(
                strategy, 2.0, 1e-5, p.max_partitions_contributed, None)
            expected = selector.probability_of_keep_vec(
                counts.astype(np.int64))
            np.testing.assert_allclose(got[ki], expected, atol=1e-9)


class TestUtilityAnalysisE2E:

    def test_public_partitions_single_config(self):
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=_agg_params(
                [pdp.Metrics.COUNT],
                max_partitions_contributed=10,
                max_contributions_per_partition=10))
        public = ["pk0", "pk1", "pk2"]
        reports_col, per_partition_col = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS, public_partitions=public)
        reports = list(reports_col)
        assert len(reports) == 1
        report = reports[0]
        assert report.configuration_index == 0
        assert report.partitions_info.public_partitions
        assert report.partitions_info.num_dataset_partitions == 3
        errors = report.metric_errors[0]
        # bounds are loose → no contribution-bounding error
        assert errors.absolute_error.mean == pytest.approx(0.0, abs=1e-9)
        assert errors.ratio_data_dropped.l0 == pytest.approx(0.0, abs=1e-9)
        # per-partition output exists for every (pk, config)
        per_partition = list(per_partition_col)
        assert len(per_partition) == 3
        assert all(key[1] == 0 for key, _ in per_partition)

    def test_empty_public_partition_counted(self):
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]))
        public = ["pk0", "pk1", "pk2", "pk_unused"]
        reports_col, per_partition_col = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS, public_partitions=public)
        report = list(reports_col)[0]
        assert report.partitions_info.num_dataset_partitions == 3
        assert report.partitions_info.num_empty_partitions == 1
        assert len(list(per_partition_col)) == 4

    def test_private_partitions_multi_config(self):
        config = data_structures.MultiParameterConfiguration(
            max_partitions_contributed=[1, 5],
            max_contributions_per_partition=[1, 5])
        options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            multi_param_configuration=config)
        reports_col, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS)
        reports = sorted(list(reports_col),
                         key=lambda r: r.configuration_index)
        assert [r.configuration_index for r in reports] == [0, 1]
        for report in reports:
            assert not report.partitions_info.public_partitions
            assert report.partitions_info.kept_partitions is not None
            assert report.partitions_info.strategy is not None
        # config 1 has looser bounds → less bounding error, more noise
        drop0 = reports[0].metric_errors[0].ratio_data_dropped
        drop1 = reports[1].metric_errors[0].ratio_data_dropped
        assert drop0.l0 + drop0.linf >= drop1.l0 + drop1.linf
        assert (reports[0].metric_errors[0].noise_std <
                reports[1].metric_errors[0].noise_std)

    def test_strategy_sweep_annotates_each_config_with_own_strategy(self):
        # Regression: reference annotates every report with the LAST config's
        # strategy (configuration_index is unset when the annotation runs).
        strategies = [
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
            pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
        ]
        config = data_structures.MultiParameterConfiguration(
            partition_selection_strategy=strategies)
        options = data_structures.UtilityAnalysisOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            multi_param_configuration=config)
        reports_col, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS)
        reports = sorted(list(reports_col),
                         key=lambda r: r.configuration_index)
        assert [r.partitions_info.strategy for r in reports] == strategies

    def test_sum_analysis(self):
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.SUM],
                                         max_partitions_contributed=10))
        reports_col, _ = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS,
            public_partitions=["pk0", "pk1", "pk2"])
        report = list(reports_col)[0]
        assert report.metric_errors[0].metric == pdp.Metrics.SUM
        assert report.utility_report_histogram is not None

    def test_select_partitions_analysis(self):
        # metrics=[] analyzes partition selection alone (the reference's
        # select_partitions tuning input): no metric errors, kept-partition
        # statistics only, bucketed by privacy-id count.
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=pdp.AggregateParams(
                metrics=[],
                noise_kind=pdp.NoiseKind.GAUSSIAN,
                max_partitions_contributed=1,
                max_contributions_per_partition=1))
        reports_col, per_partition_col = analysis.perform_utility_analysis(
            DATA, BACKEND, options, EXTRACTORS)
        report = list(reports_col)[0]
        assert report.metric_errors is None
        assert report.partitions_info.num_dataset_partitions == 3
        # huge eps -> every partition kept with probability ~1
        assert report.partitions_info.kept_partitions.mean == pytest.approx(
            3.0, abs=1e-3)
        pp = list(per_partition_col)
        assert len(pp) == 3
        assert all(m.metric_errors == [] for _, m in pp)
        # Distributed path agrees.
        dist_reports, _ = _run_distributed(DATA, options, EXTRACTORS)
        assert_reports_close(report,
                             sorted(dist_reports,
                                    key=lambda r: r.configuration_index)[0],
                             rel=0.02,
                             abs_tol=0.02)

    def test_select_partitions_tuning(self):
        histograms = list(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))[0]
        options = pt.TuneOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=pdp.AggregateParams(
                metrics=[],
                noise_kind=pdp.NoiseKind.GAUSSIAN,
                max_partitions_contributed=1,
                max_contributions_per_partition=1),
            function_to_minimize=pt.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=pt.ParametersToTune(
                max_partitions_contributed=True),
            number_of_parameter_candidates=4)
        result_col, _ = pt.tune(DATA, BACKEND, histograms, options,
                                EXTRACTORS)
        result = list(result_col)[0]
        assert result.index_best == -1  # no RMSE to rank for selection
        assert len(result.utility_reports) == (
            result.utility_analysis_parameters.size)
        assert all(r.metric_errors is None for r in result.utility_reports)

    def test_analyze_engine_rejects_aggregate(self):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1,
                                               total_delta=1e-6)
        engine = analysis.UtilityAnalysisEngine(accountant, BACKEND)
        with pytest.raises(ValueError, match="can't be called"):
            engine.aggregate(DATA, _agg_params(), EXTRACTORS)

    def test_pre_aggregated_analysis(self):
        preagg = list(analysis.preaggregate(DATA, BACKEND, EXTRACTORS))
        pre_extractors = pdp.PreAggregateExtractors(
            partition_extractor=lambda row: row[0],
            preaggregate_extractor=lambda row: row[1])
        options = data_structures.UtilityAnalysisOptions(
            epsilon=1e3,
            delta=1e-5,
            aggregate_params=_agg_params(
                [pdp.Metrics.COUNT],
                max_partitions_contributed=10,
                max_contributions_per_partition=10),
            pre_aggregated_data=True)
        reports_col, _ = analysis.perform_utility_analysis(
            preagg, BACKEND, options, pre_extractors,
            public_partitions=["pk0", "pk1", "pk2"])
        report = list(reports_col)[0]
        raw_options = dataclasses.replace(options, pre_aggregated_data=False)
        raw_report = list(
            analysis.perform_utility_analysis(
                DATA, BACKEND, raw_options, EXTRACTORS,
                public_partitions=["pk0", "pk1", "pk2"])[0])[0]
        assert report.metric_errors[0].absolute_error.rmse == pytest.approx(
            raw_report.metric_errors[0].absolute_error.rmse)


class TestProbabilityComputations:

    def test_exact_quantiles_match_monte_carlo(self):
        from pipelinedp_tpu.analysis import probability_computations as pc
        rng = np.random.default_rng(0)
        for b, s in [(1.0, 1.0), (3.0, 0.5), (0.2, 2.0)]:
            qs = [0.05, 0.5, 0.95]
            exact = pc.compute_sum_laplace_gaussian_quantiles(b, s, qs, 0)
            mc = np.quantile(
                rng.laplace(scale=b, size=500_000) +
                rng.normal(scale=s, size=500_000), qs)
            np.testing.assert_allclose(exact, mc, atol=0.05 * (b + s))

    def test_symmetry_and_degenerate_components(self):
        from pipelinedp_tpu.analysis import probability_computations as pc
        assert abs(
            pc.compute_sum_laplace_gaussian_quantiles(2.0, 3.0, [0.5],
                                                      0)[0]) < 1e-9
        # Pure Laplace / pure Gaussian reduce to the component quantiles.
        from scipy import stats
        got = pc.compute_sum_laplace_gaussian_quantiles(1.5, 0.0, [0.9], 0)
        assert got[0] == pytest.approx(stats.laplace.ppf(0.9, scale=1.5),
                                       abs=1e-9)
        got = pc.compute_sum_laplace_gaussian_quantiles(0.0, 2.0, [0.9], 0)
        assert got[0] == pytest.approx(stats.norm.ppf(0.9, scale=2.0),
                                       abs=1e-9)

    def test_cdf_extreme_tails_finite(self):
        from pipelinedp_tpu.analysis import probability_computations as pc
        # The e^{x/b} tilt must not overflow far in the tails.
        vals = pc.laplace_gaussian_cdf(np.array([-1e4, 0.0, 1e4]), 1.0, 1.0)
        assert vals[0] == 0.0 and vals[2] == 1.0
        assert vals[1] == pytest.approx(0.5, abs=1e-12)


class TestPreAggregation:

    def test_preaggregate_values(self):
        data = [(1, 'a', 2.0), (1, 'a', 3.0), (1, 'b', 1.0), (2, 'a', 4.0)]
        ext = pdp.DataExtractors(privacy_id_extractor=lambda x: x[0],
                                 partition_extractor=lambda x: x[1],
                                 value_extractor=lambda x: x[2])
        got = sorted(analysis.preaggregate(data, BACKEND, ext))
        # (pk, (count, sum, n_partitions, n_contributions))
        assert got == [('a', (1, 4.0, 1, 1)), ('a', (2, 5.0, 2, 3)),
                       ('b', (1, 1.0, 2, 3))]


class TestParameterTuning:

    def test_geometric_candidates(self):
        candidates = pt.geometric_candidates(100, 5)
        assert candidates[0] == 1
        assert candidates[-1] == 100
        assert candidates == sorted(set(candidates))
        assert len(candidates) <= 5

    def test_geometric_candidates_edge_cases(self):
        assert pt.geometric_candidates(1, 10) == [1]
        assert pt.geometric_candidates(5, 1) == [1]
        # n > max_value → every integer
        assert pt.geometric_candidates(3, 100) == [1, 2, 3]

    def test_quantile_candidates_cover_max(self):
        histograms = list(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))[0]
        hist = histograms.linf_sum_contributions_histogram
        candidates = pt.quantile_candidates(hist, 4)
        assert candidates == sorted(set(candidates))
        assert candidates[-1] == pytest.approx(hist.max_value())

    def test_cross_product_budget(self):
        c1, c2 = pt.cross_product_candidates(
            lambda n: pt.geometric_candidates(100, n),
            lambda n: pt.geometric_candidates(100, n), 9)
        assert len(c1) == len(c2) <= 9
        # short axis re-spends budget on the other one
        c1, c2 = pt.cross_product_candidates(
            lambda n: pt.geometric_candidates(1, n),
            lambda n: pt.geometric_candidates(10**6, n), 9)
        assert set(c1) == {1}
        assert len(c2) == 9

    def test_tune_e2e_count(self):
        histograms = list(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))[0]
        options = pt.TuneOptions(
            epsilon=10,
            delta=1e-5,
            aggregate_params=_agg_params([pdp.Metrics.COUNT]),
            function_to_minimize=pt.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=pt.ParametersToTune(
                max_partitions_contributed=True,
                max_contributions_per_partition=True),
            number_of_parameter_candidates=9)
        result_col, _ = pt.tune(DATA, BACKEND, histograms, options,
                                EXTRACTORS,
                                public_partitions=["pk0", "pk1", "pk2"])
        result = list(result_col)[0]
        assert isinstance(result, pt.TuneResult)
        n = result.utility_analysis_parameters.size
        assert 0 <= result.index_best < n
        assert len(result.utility_reports) == n

    def test_tune_rejects_two_metrics(self):
        options = pt.TuneOptions(
            epsilon=1,
            delta=1e-5,
            aggregate_params=_agg_params(
                [pdp.Metrics.COUNT, pdp.Metrics.PRIVACY_ID_COUNT]),
            function_to_minimize=pt.MinimizingFunction.ABSOLUTE_ERROR,
            parameters_to_tune=pt.ParametersToTune(
                max_partitions_contributed=True))
        with pytest.raises(ValueError, match="only one metric"):
            pt._check_tune_args(options, True)


class TestDatasetSummary:

    def test_summary_counts(self):
        public = ["pk0", "pk1", "pk_unused"]
        summary = list(
            analysis.compute_public_partitions_summary(
                DATA, BACKEND, EXTRACTORS, public))[0]
        assert summary.num_dataset_public_partitions == 2
        assert summary.num_dataset_non_public_partitions == 1  # pk2
        assert summary.num_empty_public_partitions == 1  # pk_unused


class TestErrorModelMonteCarlo:
    """Validates the closed-form error model against brute-force simulation
    of the actual bounding process — a check the reference never had (its
    combiner tests only assert the formulas against themselves).

    Ground truth: each privacy id keeps a given partition with probability
    q = min(1, l0 / n_partitions_contributed) (uniform l0-subset sampling),
    its contribution is clipped to the metric bounds, and the partition is
    released iff the DP selector keeps the surviving id count.
    """

    # One partition: per-user (count in this partition, partitions touched).
    USERS = [(1, 1), (3, 2), (5, 4), (2, 8), (7, 3), (4, 16), (1, 2)]
    L0 = 2
    LINF = 4
    N_TRIALS = 40_000

    def _model_stats(self):
        counts = np.array([float(c) for c, _ in self.USERS])
        n_parts = np.array([float(n) for _, n in self.USERS])
        q = em.keep_fraction(n_parts, float(self.L0))
        stats = em.metric_stat_terms(counts, 0.0, float(self.LINF),
                                     q).sum(axis=-2)
        return counts, q, stats

    def _simulate_errors(self, rng):
        counts = np.array([float(c) for c, _ in self.USERS])
        clipped = np.clip(counts, 0.0, float(self.LINF))
        n_parts = np.array([float(n) for _, n in self.USERS])
        q = np.minimum(1.0, self.L0 / n_parts)
        keep = rng.random((self.N_TRIALS, len(counts))) < q
        released = (keep * clipped).sum(axis=1)
        return released - counts.sum(), keep.sum(axis=1)

    def test_bounding_error_mean_and_variance_match_simulation(self):
        counts, q, stats = self._model_stats()
        model_mean = (stats[em.L0_MEAN] + stats[em.CLIP_MIN] +
                      stats[em.CLIP_MAX])
        model_var = stats[em.L0_VAR]
        errors, _ = self._simulate_errors(np.random.default_rng(7))
        # 5-sigma confidence bands on the empirical moments.
        mean_tol = 5 * np.sqrt(model_var / self.N_TRIALS)
        assert errors.mean() == pytest.approx(model_mean, abs=mean_tol)
        assert errors.var() == pytest.approx(model_var, rel=0.05)

    def test_rmse_report_term_matches_simulation(self):
        counts, q, stats = self._model_stats()
        noise_std = 3.0
        row = em.metric_report_terms(stats, keep_prob=1.0, weight=1.0,
                                     noise_std=noise_std)
        rng = np.random.default_rng(8)
        errors, _ = self._simulate_errors(rng)
        noisy = errors + rng.normal(0.0, noise_std, len(errors))
        emp_rmse = np.sqrt((noisy**2).mean())
        assert float(row[em.ABS_RMSE]) == pytest.approx(emp_rmse, rel=0.03)

    def test_keep_probability_matches_simulation(self):
        _, q, _ = self._model_stats()
        selector = partition_selection.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
            epsilon=1.0, delta=1e-3,
            max_partitions_contributed=self.L0)
        model_p = em.host_keep_probability(np.asarray(q), selector)
        _, kept_counts = self._simulate_errors(np.random.default_rng(9))
        emp_p = selector.probability_of_keep_vec(kept_counts).mean()
        assert model_p == pytest.approx(float(emp_p), abs=0.01)

    def test_moment_path_matches_exact_path(self):
        _, q, _ = self._model_stats()
        selector = partition_selection.create_partition_selection_strategy(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
            epsilon=1.0, delta=1e-3,
            max_partitions_contributed=self.L0)
        exact = em.host_keep_probability(np.asarray(q), selector)
        moments = em.selection_moment_terms(np.asarray(q)).sum(axis=-2)
        approx = em.host_keep_probability_from_moments(
            float(moments[em.SEL_MU]), float(moments[em.SEL_VAR]),
            float(moments[em.SEL_SKEW3]), len(q), selector)
        # The refined-normal approximation on 7 Bernoullis is coarse but
        # must land near the exact Poisson-binomial integration.
        assert approx == pytest.approx(exact, abs=0.05)
