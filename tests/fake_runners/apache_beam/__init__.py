"""Minimal eager in-memory Apache Beam fake (the fake-runner harness).

apache_beam cannot be installed in this environment, so this package
implements just enough of its public API — transforms, pipelines, labels,
side inputs, combiners — for pipelinedp_tpu's BeamBackend and private_beam
adapters to EXECUTE end-to-end rather than importorskip. Semantics mirrored
deliberately:

  * label uniqueness is enforced per pipeline (duplicate labels raise, the
    failure mode UniqueLabelsGenerator exists to prevent);
  * every transform is applied through Pipeline.apply via `|` / `>>`
    plumbing, exactly as the adapters compose them;
  * CoGroupByKey produces (key, {tag: [values]}) with per-tag LISTS
    (matching real Beam, which materializes them), CombinePerKey takes a
    callable over the iterable of values, side inputs arrive as extra
    args;
  * GroupByKey/CombinePerKey values are handed to user code as LAZY
    REITERABLES (_GroupedIterable), not lists — re-iteration is allowed
    (Beam guarantees it) but len()/indexing/mutation raise TypeError, the
    exact bug class a DirectRunner list hides and a real shuffle exposes;
  * windowing is rejected loudly (WindowInto / window.* raise
    NotImplementedError): execution is eager in one global window, and a
    pipeline that needs windows must not silently get global semantics.

Execution is eager over Python lists — a DirectRunner without the runner —
with one worker-boundary fidelity guarantee: every user closure is shipped
through cloudpickle (what Beam's pickler does at job submission) before it
runs, so a lambda/combiner that could not survive the driver->worker hop on
a real cluster fails here too. Shipping happens at thunk-execution time,
matching real timing: pipeline.run() (hence serialization) occurs after
budget_accountant.compute_budgets(), so shipped MechanismSpec copies carry
finalized eps/delta and late mutation of driver-side objects is NOT visible
to workers.
"""

import random as _random

import cloudpickle as _cloudpickle

from apache_beam import io
from apache_beam import pvalue
from apache_beam.pvalue import PCollection
from apache_beam.transforms.ptransform import PTransform


def _ship(obj):
    """Simulate the driver->worker serialization boundary (closures AND
    side-input values both cross it on a real runner)."""
    return _cloudpickle.loads(_cloudpickle.dumps(obj))


class _GroupedIterable:
    """The lazy reiterable a real runner hands to per-key consumers.

    Iterable — and RE-iterable, as Beam's GroupByKey contract guarantees —
    but deliberately not a list: len(), indexing, slicing, and mutation
    raise TypeError so adapter code that assumes materialized lists fails
    here the way it would on a real shuffle. `iterations` counts fresh
    passes so tests can assert single-pass consumption where an adapter
    promises it.
    """

    __slots__ = ("_values", "iterations")

    def __init__(self, values):
        self._values = tuple(values)
        self.iterations = 0

    def __iter__(self):
        self.iterations += 1
        return iter(self._values)

    def __len__(self):
        raise TypeError(
            "grouped values are a lazy iterable, not a list: len() is "
            "unavailable on a real runner — iterate (or materialize "
            "explicitly) instead")

    def __getitem__(self, _):
        raise TypeError(
            "grouped values are a lazy iterable, not a list: indexing is "
            "unavailable on a real runner — iterate instead")

    def __eq__(self, other):  # tests compare materialized results
        return NotImplemented

    def __repr__(self):
        return f"_GroupedIterable(<{len(self._values)} values>)"


class WindowInto(PTransform):
    """Rejecting stub: the fake runner executes eagerly in one global
    window; silently dropping window semantics would corrupt any pipeline
    that actually needs them."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "windowing is not supported by the fake Beam runner (eager "
            "execution in a single global window)")


class _RejectedWindowFn:

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "windowing is not supported by the fake Beam runner (eager "
            "execution in a single global window)")


class _WindowModule:
    FixedWindows = _RejectedWindowFn
    SlidingWindows = _RejectedWindowFn
    Sessions = _RejectedWindowFn
    GlobalWindows = _RejectedWindowFn


window = _WindowModule()


class _PipelineResult:

    def wait_until_finish(self):
        return "DONE"


class Pipeline:

    def __init__(self, *args, **kwargs):
        self._labels = set()
        self._collections = []

    def _register(self, pcoll):
        self._collections.append(pcoll)

    def apply(self, transform, pvalueish):
        if not isinstance(transform, PTransform):
            raise TypeError(f"Expected a PTransform object, got {transform}")
        label = transform.label
        if label in self._labels:
            raise RuntimeError(
                f"A transform with label {label!r} already exists in the "
                "pipeline. To apply a transform with a specified label, use "
                "the label >> transform syntax.")
        self._labels.add(label)
        return transform.expand(pvalueish)

    def __or__(self, transform):
        return self.apply(transform, self)

    def run(self):
        for pcoll in self._collections:
            _ = pcoll._data  # force thunks (and their side effects)
        return _PipelineResult()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is None:
            self.run().wait_until_finish()


def _data(pcoll):
    return list(pcoll._data)


def _resolve_sides(sides):
    return [s.resolve() if isinstance(s, pvalue.AsList) else s for s in sides]


def _out(pvalueish, data):
    if isinstance(pvalueish, Pipeline):
        return PCollection(pvalueish, data)
    return PCollection(pvalueish.pipeline, data)


class Create(PTransform):

    def __init__(self, values):
        super().__init__()
        self._values = list(values)

    def expand(self, pipeline):
        return PCollection(pipeline, list(self._values))


class Map(PTransform):

    def __init__(self, fn, *sides):
        super().__init__()
        self._fn, self._sides = fn, sides

    def expand(self, pcoll):

        def thunk():
            fn = _ship(self._fn)
            sides = _ship(_resolve_sides(self._sides))
            return [fn(x, *sides) for x in _data(pcoll)]

        return _out(pcoll, thunk)


class MapTuple(PTransform):

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def expand(self, pcoll):

        def thunk():
            fn = _ship(self._fn)
            return [fn(*x) for x in _data(pcoll)]

        return _out(pcoll, thunk)


class FlatMap(PTransform):

    def __init__(self, fn, *sides):
        super().__init__()
        self._fn, self._sides = fn, sides

    def expand(self, pcoll):

        def thunk():
            fn = _ship(self._fn)
            sides = _ship(_resolve_sides(self._sides))
            out = []
            for x in _data(pcoll):
                out.extend(fn(x, *sides))
            return out

        return _out(pcoll, thunk)


class Filter(PTransform):

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def expand(self, pcoll):

        def thunk():
            fn = _ship(self._fn)
            return [x for x in _data(pcoll) if fn(x)]

        return _out(pcoll, thunk)


class GroupByKey(PTransform):

    def expand(self, pcoll):

        def thunk():
            grouped = {}
            for k, v in _data(pcoll):
                grouped.setdefault(k, []).append(v)
            return [(k, _GroupedIterable(vs)) for k, vs in grouped.items()]

        return _out(pcoll, thunk)


class Keys(PTransform):

    def expand(self, pcoll):
        return _out(pcoll, lambda: [k for k, _ in _data(pcoll)])


class Values(PTransform):

    def expand(self, pcoll):
        return _out(pcoll, lambda: [v for _, v in _data(pcoll)])


class Distinct(PTransform):

    def expand(self, pcoll):
        return _out(pcoll, lambda: list(dict.fromkeys(_data(pcoll))))


class Flatten(PTransform):

    def expand(self, pcolls):

        def thunk():
            out = []
            for pcoll in pcolls:
                out.extend(_data(pcoll))
            return out

        return PCollection(pcolls[0].pipeline, thunk)


class CoGroupByKey(PTransform):
    """(key, {tag: [values]}) join of a dict of keyed PCollections."""

    def expand(self, tagged):

        def thunk():
            joined = {}
            for tag, pcoll in tagged.items():
                for k, v in _data(pcoll):
                    joined.setdefault(k,
                                      {t: [] for t in tagged})[tag].append(v)
            # Real Beam's CoGroupByKey materializes per-tag LISTS (unlike
            # GroupByKey's lazy iterables), so list semantics are the
            # faithful model here.
            return list(joined.items())

        pipeline = next(iter(tagged.values())).pipeline
        return PCollection(pipeline, thunk)


class DoFn:

    def process(self, element):
        raise NotImplementedError


class ParDo(PTransform):

    def __init__(self, dofn):
        super().__init__()
        self._dofn = dofn

    def expand(self, pcoll):

        def thunk():
            dofn = _ship(self._dofn)
            out = []
            for x in _data(pcoll):
                result = dofn.process(x)
                if result is not None:
                    out.extend(result)
            return out

        return _out(pcoll, thunk)


class CombinePerKey(PTransform):
    """fn receives the iterable of all values of a key."""

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def expand(self, pcoll):

        def thunk():
            fn = _ship(self._fn)
            grouped = {}
            for k, v in _data(pcoll):
                grouped.setdefault(k, []).append(v)
            return [(k, fn(_GroupedIterable(vs)))
                    for k, vs in grouped.items()]

        return _out(pcoll, thunk)


class _Sample:

    @staticmethod
    def FixedSizePerKey(n):

        class _SampleT(PTransform):

            def expand(self, pcoll):

                def thunk():
                    grouped = {}
                    for k, v in _data(pcoll):
                        grouped.setdefault(k, []).append(v)
                    return [(k, _random.sample(vs, min(n, len(vs))))
                            for k, vs in grouped.items()]

                return _out(pcoll, thunk)

        return _SampleT()


class _Count:

    @staticmethod
    def PerElement():

        class _CountT(PTransform):

            def expand(self, pcoll):

                def thunk():
                    counts = {}
                    for x in _data(pcoll):
                        counts[x] = counts.get(x, 0) + 1
                    return list(counts.items())

                return _out(pcoll, thunk)

        return _CountT()


def _ToList():

    class _ToListT(PTransform):

        def expand(self, pcoll):
            return _out(pcoll, lambda: [_data(pcoll)])

    return _ToListT()


class _Combiners:
    Sample = _Sample
    Count = _Count
    ToList = staticmethod(_ToList)


combiners = _Combiners()
