"""io module of the in-memory Beam fake: file sinks."""

from apache_beam.transforms.ptransform import PTransform


class WriteToText(PTransform):
    """Writes one element per line, with real WriteToText's shard naming."""

    def __init__(self, file_path_prefix, file_name_suffix=""):
        super().__init__()
        self._prefix = file_path_prefix
        self._suffix = file_name_suffix

    def expand(self, pcoll):
        from apache_beam.pvalue import PCollection

        def thunk():
            name = f"{self._prefix}-00000-of-00001{self._suffix}"
            with open(name, "w") as out:
                for element in pcoll._data:
                    out.write(f"{element}\n")
            return [name]

        return PCollection(pcoll.pipeline, thunk)
