"""pvalue module of the in-memory Beam fake."""


class PCollection:
    """Deferred PCollection: a pipeline handle plus a thunk producing the
    element list, materialized (and cached) on first iteration — mirroring
    Beam's run-at-pipeline-execution semantics, which the DP engine relies
    on (noise parameters are only final after compute_budgets())."""

    def __init__(self, pipeline, thunk):
        self.pipeline = pipeline
        if not callable(thunk):
            values = list(thunk)
            thunk = lambda: values
        self._thunk = thunk
        self._materialized = None
        # Pipeline.run() forces every collection so side-effecting
        # transforms (Map(print), io.WriteToText) fire at run time.
        register = getattr(pipeline, "_register", None)
        if register is not None:
            register(self)

    @property
    def _data(self):
        if self._materialized is None:
            self._materialized = list(self._thunk())
        return self._materialized

    def __or__(self, transform):
        return self.pipeline.apply(transform, self)

    def __iter__(self):
        return iter(self._data)


class AsList:
    """Side-input marker: resolved to a list at transform expansion."""

    def __init__(self, pcoll):
        self.pcoll = pcoll

    def resolve(self):
        return list(self.pcoll._data)
