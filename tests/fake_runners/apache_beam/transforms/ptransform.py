"""PTransform base for the in-memory Beam fake (see package __init__)."""


class PTransform:
    """Labeled transform. Mirrors the real API points the adapters touch:
    `label >> transform` relabeling (__rrshift__), application via `|` from
    PCollections / dicts / tuples, and expand()."""

    def __init__(self, label=None):
        self.label = label or type(self).__name__

    def __rrshift__(self, label):
        self.label = label
        return self

    def __ror__(self, left):
        # dict | CoGroupByKey(), tuple | Flatten(): Python falls through to
        # __ror__ because dict/tuple don't implement | with a PTransform.
        pipeline = _find_pipeline(left)
        return pipeline.apply(self, left)

    def expand(self, pvalueish):
        raise NotImplementedError


def _find_pipeline(pvalueish):
    values = (pvalueish.values()
              if isinstance(pvalueish, dict) else list(pvalueish))
    for value in values:
        pipeline = getattr(value, "pipeline", None)
        if pipeline is not None:
            return pipeline
    raise ValueError("no PCollection (hence no pipeline) in %r" % (pvalueish,))
