from apache_beam.transforms import ptransform  # noqa: F401
