"""Executes the Beam adapter stack over the in-memory fake runner.

Run with PYTHONPATH including tests/fake_runners (so `import apache_beam`
resolves to the fake) and the repo root. Exercises the REAL adapter code —
pipeline_backend.BeamBackend, private_beam's PTransforms, label uniqueness,
DPEngine on Beam collections, and the distributed utility-analysis path —
none of which can execute under the plain test suite (apache_beam is not
installable here).
"""

import os
import sys

if os.environ.get("JAX_PLATFORMS"):
    # Honor the env var even when a sitecustomize-registered TPU plugin
    # would override it (same programmatic reset as tests/conftest.py).
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import apache_beam as beam
assert "fake_runners" in beam.__file__, beam.__file__

import pipelinedp_tpu as pdp
from pipelinedp_tpu import pipeline_backend, private_beam
from pipelinedp_tpu import private_collection

ROWS = [(f"u{i % 30}", f"pk{i % 4}", float(i % 5)) for i in range(400)]
HUGE_EPS = 1e6


def check(name, condition, detail=""):
    if not condition:
        print(f"FAILED: {name} {detail}", file=sys.stderr)
        sys.exit(1)
    print(f"ok: {name}")


def raw_counts():
    counts = {}
    for _, pk, _ in ROWS:
        counts[pk] = counts.get(pk, 0) + 1
    return counts


_create_counter = [0]


def pcol_of(pipeline, data):
    _create_counter[0] += 1
    return pipeline | f"create input {_create_counter[0]}" >> beam.Create(
        data)


def test_backend_ops_match_local():
    backend = pipeline_backend.BeamBackend()
    local = pdp.LocalBackend()
    pipeline = beam.Pipeline()
    kv = [("a", 1), ("b", 2), ("a", 3), ("c", 4)]

    def run_both(op, *args):
        got = list(op(backend)(pcol_of(pipeline, kv), *args))
        want = list(op(local)(iter(kv), *args))
        return got, want

    got, want = run_both(lambda b: lambda c: b.map(c, lambda x:
                                                   (x[0], x[1] * 10), "m"))
    check("map", sorted(got) == sorted(want))
    got, want = run_both(
        lambda b: lambda c: b.map_tuple(c, lambda k, v: (k, v + 1), "mt"))
    check("map_tuple", sorted(got) == sorted(want))
    got, want = run_both(
        lambda b: lambda c: b.map_values(c, lambda v: -v, "mv"))
    check("map_values", sorted(got) == sorted(want))
    got, want = run_both(
        lambda b: lambda c: b.filter(c, lambda x: x[1] > 1, "f"))
    check("filter", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.keys(c, "k"))
    check("keys", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.values(c, "v"))
    check("values", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.distinct(c, "d"))
    check("distinct", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.sum_per_key(c, "s"))
    check("sum_per_key", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.count_per_element(c, "ce"))
    check("count_per_element", sorted(got) == sorted(want))
    got = {
        k: sorted(v)
        for k, v in pipeline_backend.BeamBackend().group_by_key(
            pcol_of(pipeline, kv), "g")
    }
    check("group_by_key", got == {"a": [1, 3], "b": [2], "c": [4]})
    got = sorted(
        backend.filter_by_key(pcol_of(pipeline, kv), ["a", "c"], "fbk"))
    check("filter_by_key(list)", got == [("a", 1), ("a", 3), ("c", 4)])
    keys_pcol = pipeline | "keys pcol" >> beam.Create(["b"])
    got = sorted(backend.filter_by_key(pcol_of(pipeline, kv), keys_pcol,
                                       "fbk2"))
    check("filter_by_key(pcol)", got == [("b", 2)])
    got = sorted(
        backend.flatten((pcol_of(pipeline, kv),
                         pipeline | "more" >> beam.Create([("z", 9)])),
                        "fl"))
    check("flatten", got == sorted(kv + [("z", 9)]))
    got = list(backend.to_list(pcol_of(pipeline, kv), "tl"))
    check("to_list", len(got) == 1 and sorted(got[0]) == sorted(kv))
    got = list(
        backend.map_with_side_inputs(pcol_of(pipeline, [1, 2]),
                                     lambda x, side: x + sum(side),
                                     [pipeline | "side" >> beam.Create(
                                         [10, 20])], "msi"))
    check("map_with_side_inputs", sorted(got) == [31, 32])
    got = sorted(
        backend.sample_fixed_per_key(pcol_of(pipeline, kv), 1, "sfpk"))
    check("sample_fixed_per_key",
          [k for k, _ in got] == ["a", "b", "c"] and all(
              len(v) == 1 for _, v in got))


def test_duplicate_labels_raise():
    pipeline = beam.Pipeline()
    pcol = pipeline | "input" >> beam.Create([1, 2])
    _ = pcol | "stage" >> beam.Map(lambda x: x)
    try:
        _ = pcol | "stage" >> beam.Map(lambda x: x)
    except RuntimeError as e:
        check("duplicate label raises", "already exists" in str(e))
    else:
        check("duplicate label raises", False)


def test_dp_engine_on_beam():
    backend = pipeline_backend.BeamBackend()
    pipeline = beam.Pipeline()
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant, backend)
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 max_partitions_contributed=4,
                                 max_contributions_per_partition=20,
                                 min_value=0.0,
                                 max_value=5.0)
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    result = engine.aggregate(pcol_of(pipeline, ROWS), params, extractors,
                              [f"pk{i}" for i in range(4)])
    accountant.compute_budgets()
    got = dict(result)
    for pk, want in raw_counts().items():
        assert abs(got[pk].count - want) < 0.5, (pk, got[pk].count, want)
    check("DPEngine.aggregate on BeamBackend", True)


def test_private_beam_transforms():
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    with beam.Pipeline() as pipeline:
        pcol = pipeline | "read" >> beam.Create(ROWS)
        private = pcol | private_beam.MakePrivate(
            budget_accountant=accountant,
            privacy_id_extractor=lambda r: r[0])
        mapped = private | private_beam.Map(lambda r: (r[1], r[2]))
        count = mapped | private_beam.Count(
            pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                            max_partitions_contributed=4,
                            max_contributions_per_partition=20,
                            partition_extractor=lambda r: r[0]),
            public_partitions=[f"pk{i}" for i in range(4)])
        sums = mapped | private_beam.Sum(
            pdp.SumParams(noise_kind=pdp.NoiseKind.LAPLACE,
                          max_partitions_contributed=4,
                          max_contributions_per_partition=20,
                          min_value=0.0,
                          max_value=5.0,
                          partition_extractor=lambda r: r[0],
                          value_extractor=lambda r: r[1]),
            public_partitions=[f"pk{i}" for i in range(4)])
        selected = (private | private_beam.SelectPartitions(
            pdp.SelectPartitionsParams(max_partitions_contributed=4),
            partition_extractor=lambda r: r[1]))
        accountant.compute_budgets()
        got_counts = dict(count)
        for pk, want in raw_counts().items():
            assert abs(got_counts[pk] - want) < 0.5, (pk, got_counts[pk])
        got_sums = dict(sums)
        check("private_beam Count/Sum",
              set(got_sums) == set(raw_counts()))
        check("private_beam SelectPartitions",
              set(selected) == set(raw_counts()))


def test_private_beam_mean_variance_pid_count():
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    raw_vals = {}
    for _, pk, v in ROWS:
        raw_vals.setdefault(pk, []).append(v)
    with beam.Pipeline() as pipeline:
        pcol = pipeline | "read mv" >> beam.Create(ROWS)
        private = pcol | "mp mv" >> private_beam.MakePrivate(
            budget_accountant=accountant,
            privacy_id_extractor=lambda r: r[0])
        flat = private | private_beam.FlatMap(lambda r: [(r[1], r[2])] * 2)
        mean = flat | private_beam.Mean(
            pdp.MeanParams(noise_kind=pdp.NoiseKind.GAUSSIAN,
                           max_partitions_contributed=4,
                           max_contributions_per_partition=40,
                           min_value=0.0,
                           max_value=5.0,
                           partition_extractor=lambda r: r[0],
                           value_extractor=lambda r: r[1]),
            public_partitions=[f"pk{i}" for i in range(4)])
        var = flat | private_beam.Variance(
            pdp.VarianceParams(noise_kind=pdp.NoiseKind.GAUSSIAN,
                               max_partitions_contributed=4,
                               max_contributions_per_partition=40,
                               min_value=0.0,
                               max_value=5.0,
                               partition_extractor=lambda r: r[0],
                               value_extractor=lambda r: r[1]),
            public_partitions=[f"pk{i}" for i in range(4)])
        pid_count = private | private_beam.PrivacyIdCount(
            pdp.PrivacyIdCountParams(noise_kind=pdp.NoiseKind.GAUSSIAN,
                                     max_partitions_contributed=4,
                                     partition_extractor=lambda r: r[1]),
            public_partitions=[f"pk{i}" for i in range(4)])
        accountant.compute_budgets()
        import numpy as _np
        got_mean = dict(mean)
        got_var = dict(var)
        ok_mean = all(
            abs(got_mean[pk] - _np.mean(vs)) < 0.05
            for pk, vs in raw_vals.items())
        # FlatMap duplicated every value, which leaves mean/variance of the
        # duplicated stream identical to the raw one.
        ok_var = all(
            abs(got_var[pk] - _np.var(vs)) < 0.1
            for pk, vs in raw_vals.items())
        check("private_beam FlatMap + Mean", ok_mean)
        check("private_beam Variance", ok_var)
        got_pid = dict(pid_count)
        raw_pids = {}
        for pid, pk, _ in ROWS:
            raw_pids.setdefault(pk, set()).add(pid)
        check("private_beam PrivacyIdCount",
              all(abs(got_pid[pk] - len(pids)) < 0.5
                  for pk, pids in raw_pids.items()))


def test_private_beam_combine_per_key():

    class _SumCombineFn(private_collection.PrivateCombineFn):

        def create_accumulator(self):
            return 0.0

        def add_input_for_private_output(self, accumulator, value):
            return accumulator + min(max(value, 0.0), 5.0)

        def merge_accumulators(self, accumulators):
            return sum(accumulators)

        def extract_private_output(self, accumulator, budget,
                                   aggregate_params):
            assert budget.eps > 0
            return accumulator

        def request_budget(self, budget_accountant):
            return budget_accountant.request_budget(
                pdp.MechanismType.LAPLACE)

    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    with beam.Pipeline() as pipeline:
        pcol = pipeline | "cpk read" >> beam.Create(ROWS)
        private = pcol | private_beam.MakePrivate(
            budget_accountant=accountant,
            privacy_id_extractor=lambda r: r[0])
        keyed = private | private_beam.Map(lambda r: (r[1], r[2]))
        combined = keyed | private_beam.CombinePerKey(
            _SumCombineFn(),
            private_collection.CombinePerKeyParams(
                max_partitions_contributed=4,
                max_contributions_per_partition=20))
        accountant.compute_budgets()
        got = dict(combined)
        check("private_beam CombinePerKey", len(got) == 4)


def test_private_contribution_bounds_on_beam():
    # Reference parity: DP L0-bound calculation runs on Beam
    # (/root/reference/tests/dp_engine_test.py
    # test_calculate_private_contribution_works_on_beam).
    backend = pipeline_backend.BeamBackend()
    pipeline = beam.Pipeline()
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant, backend)
    params = pdp.CalculatePrivateContributionBoundsParams(
        aggregation_noise_kind=pdp.NoiseKind.LAPLACE,
        aggregation_eps=1.0,
        aggregation_delta=0.0,
        calculation_eps=1.0,
        max_partitions_contributed_upper_bound=8)
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    partitions = pipeline | "bounds partitions" >> beam.Create(
        [f"pk{i}" for i in range(4)])
    result = engine.calculate_private_contribution_bounds(
        pcol_of(pipeline, ROWS), params, extractors, partitions)
    bounds = list(result)[0]
    check("calculate_private_contribution_bounds on BeamBackend",
          1 <= bounds.max_partitions_contributed <= 8)


def test_utility_analysis_on_beam():
    from pipelinedp_tpu import analysis
    from pipelinedp_tpu.analysis import data_structures
    backend = pipeline_backend.BeamBackend()
    pipeline = beam.Pipeline()
    options = data_structures.UtilityAnalysisOptions(
        epsilon=10,
        delta=1e-5,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=2,
            max_contributions_per_partition=5))
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    reports, per_partition = analysis.perform_utility_analysis(
        pcol_of(pipeline, ROWS), backend, options, extractors)
    reports = sorted(reports, key=lambda r: r.configuration_index)
    check("utility analysis on BeamBackend",
          len(reports) == 1 and
          reports[0].partitions_info.num_dataset_partitions == 4)
    check("per-partition output on BeamBackend",
          len(list(per_partition)) == 4)


def test_worker_serialization_boundary():
    """The fake runner ships every closure through cloudpickle; prove the
    boundary is real: unserializable closures fail, and workers operate on
    COPIES of captured driver objects (so driver-side mutation after the
    ship is invisible — the reason compute_budgets() must precede run())."""
    import threading
    pipeline = beam.Pipeline()
    lock = threading.Lock()  # not serializable, even by cloudpickle
    pcol = pcol_of(pipeline, [1, 2, 3])
    bad = pcol | "capture lock" >> beam.Map(lambda x: (lock, x)[1])
    try:
        list(bad._data)
        check("unserializable closure rejected at the worker boundary",
              False)
    except TypeError:
        check("unserializable closure rejected at the worker boundary",
              True)

    pipeline2 = beam.Pipeline()
    driver_side = []  # captured by the closure; workers get a copy
    pcol2 = pcol_of(pipeline2, [1, 2, 3])
    out = pcol2 | "append" >> beam.Map(
        lambda x: (driver_side.append(x), x)[1])
    result = list(out._data)
    check("workers mutate a shipped COPY, not the driver object",
          result == [1, 2, 3] and driver_side == [])


def test_grouped_values_are_lazy_reiterables():
    """GroupByKey/CombinePerKey values must behave like a real shuffle's
    lazy iterables: re-iterable, but len()/indexing raise TypeError (the
    bug class a DirectRunner list hides)."""
    pipeline = beam.Pipeline()
    pcol = pcol_of(pipeline, [("a", 1), ("a", 2), ("b", 3)])
    grouped = pcol | "gbk strict" >> beam.GroupByKey()
    items = dict(grouped._data)
    vs = items["a"]
    check("grouped values are re-iterable",
          sorted(vs) == [1, 2] and sorted(vs) == [1, 2])
    for op, fn in (("len", lambda: len(vs)), ("index", lambda: vs[0]),
                   ("bool", lambda: bool(vs))):
        try:
            fn()
            check(f"grouped values reject {op}()", False)
        except TypeError:
            check(f"grouped values reject {op}()", True)
    combined = pcol | "combine strict" >> beam.CombinePerKey(
        lambda values: sum(values))
    check("CombinePerKey fn receives an iterable (sum works)",
          dict(combined._data) == {"a": 3, "b": 3})

    pipeline2 = beam.Pipeline()
    pcol2 = pcol_of(pipeline2, [("a", 1)])
    try:
        _ = pcol2 | "combine list op" >> beam.CombinePerKey(
            lambda values: values[0])
        list(_._data)
        check("CombinePerKey fn indexing grouped values rejected", False)
    except TypeError:
        check("CombinePerKey fn indexing grouped values rejected", True)


def test_windowing_rejected():
    """The eager fake must refuse windowed pipelines rather than silently
    run them in one global window."""
    try:
        beam.WindowInto(object())
        check("WindowInto rejected", False)
    except NotImplementedError:
        check("WindowInto rejected", True)
    try:
        beam.window.FixedWindows(60)
        check("window.FixedWindows rejected", False)
    except NotImplementedError:
        check("window.FixedWindows rejected", True)


if __name__ == "__main__":
    test_backend_ops_match_local()
    test_duplicate_labels_raise()
    test_dp_engine_on_beam()
    test_private_beam_transforms()
    test_private_beam_mean_variance_pid_count()
    test_private_beam_combine_per_key()
    test_private_contribution_bounds_on_beam()
    test_utility_analysis_on_beam()
    test_worker_serialization_boundary()
    test_grouped_values_are_lazy_reiterables()
    test_windowing_rejected()
    print("BEAM_CHECKS_PASSED")
