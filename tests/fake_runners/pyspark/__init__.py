"""Minimal eager in-memory PySpark fake (the fake-runner harness).

pyspark cannot be installed in this environment; this module implements the
RDD/SparkContext surface pipelinedp_tpu's SparkRDDBackend and private_spark
adapters use, executing eagerly over Python lists — local[1] without the
JVM. groupByKey values are re-iterable ResultIterables (mirroring
pyspark.resultiterable — list-backed, so len() works, unlike Beam's lazy
iterables), join has inner-join semantics, and union concatenates.

Worker-boundary fidelity: every closure handed to a transformation is
shipped through cloudpickle (PySpark's own closure serializer) when the
thunk runs — i.e. at action time, after compute_budgets() in correct DP
usage — so closures that could not reach a real executor fail here too,
and workers observe a COPY of captured driver objects, not live references.
"""

import random as _random

import cloudpickle as _cloudpickle


def _ship(fn):
    """Simulate the driver->executor serialization boundary."""
    return _cloudpickle.loads(_cloudpickle.dumps(fn))


class ResultIterable:
    """Re-iterable group value (mirrors pyspark.resultiterable)."""

    def __init__(self, values):
        self._values = list(values)

    def __iter__(self):
        return iter(self._values)

    def __len__(self):
        return len(self._values)


class RDD:
    """Deferred RDD: transformations build thunks; actions (collect/count)
    materialize — mirroring Spark's lazy evaluation, which the DP engine
    relies on (noise parameters are final only after compute_budgets())."""

    def __init__(self, data, ctx):
        if callable(data):
            self._thunk = data
        else:
            values = list(data)
            self._thunk = lambda: values
        self._materialized = None
        self.ctx = ctx

    @property
    def _data(self):
        if self._materialized is None:
            self._materialized = list(self._thunk())
        return self._materialized

    @property
    def context(self):
        return self.ctx

    def map(self, fn):

        def thunk():
            f = _ship(fn)
            return [f(x) for x in self._data]

        return RDD(thunk, self.ctx)

    def flatMap(self, fn):

        def thunk():
            f = _ship(fn)
            out = []
            for x in self._data:
                out.extend(f(x))
            return out

        return RDD(thunk, self.ctx)

    def mapValues(self, fn):

        def thunk():
            f = _ship(fn)
            return [(k, f(v)) for k, v in self._data]

        return RDD(thunk, self.ctx)

    def flatMapValues(self, fn):

        def thunk():
            f = _ship(fn)
            out = []
            for k, v in self._data:
                out.extend((k, w) for w in f(v))
            return out

        return RDD(thunk, self.ctx)

    def groupByKey(self):

        def thunk():
            grouped = {}
            for k, v in self._data:
                grouped.setdefault(k, []).append(v)
            # Spark yields re-iterable ResultIterables, not iterators.
            return [(k, ResultIterable(vs)) for k, vs in grouped.items()]

        return RDD(thunk, self.ctx)

    def filter(self, fn):

        def thunk():
            f = _ship(fn)
            return [x for x in self._data if f(x)]

        return RDD(thunk, self.ctx)

    def join(self, other):

        def thunk():
            right = {}
            for k, v in other._data:
                right.setdefault(k, []).append(v)
            out = []
            for k, v in self._data:
                for w in right.get(k, []):
                    out.append((k, (v, w)))
            return out

        return RDD(thunk, self.ctx)

    def keys(self):
        return RDD(lambda: [k for k, _ in self._data], self.ctx)

    def values(self):
        return RDD(lambda: [v for _, v in self._data], self.ctx)

    def reduceByKey(self, fn):

        def thunk():
            f = _ship(fn)
            grouped = {}
            for k, v in self._data:
                grouped[k] = f(grouped[k], v) if k in grouped else v
            return list(grouped.items())

        return RDD(thunk, self.ctx)

    def distinct(self):
        return RDD(lambda: list(dict.fromkeys(self._data)), self.ctx)

    def sample(self, withReplacement, fraction, seed=None):

        def thunk():
            rng = _random.Random(seed)
            return [x for x in self._data if rng.random() < fraction]

        return RDD(thunk, self.ctx)

    def collect(self):
        return list(self._data)

    def count(self):
        return len(self._data)

    def cache(self):
        return self


class SparkConf:

    def setMaster(self, master):
        return self

    def setAppName(self, name):
        return self


class SparkContext:

    def __init__(self, *args, conf=None, **kwargs):
        del args, conf, kwargs

    def parallelize(self, data, numSlices=None):
        return RDD(data, self)

    def union(self, rdds):

        def thunk():
            out = []
            for rdd in rdds:
                out.extend(rdd._data)
            return out

        return RDD(thunk, self)

    def stop(self):
        pass
