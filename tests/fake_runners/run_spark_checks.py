"""Executes the Spark adapter stack over the in-memory fake runner.

Run with PYTHONPATH including tests/fake_runners (so `import pyspark`
resolves to the fake) and the repo root. Exercises the REAL adapter code —
pipeline_backend.SparkRDDBackend, private_spark's PrivateRDD, DPEngine on
RDDs, and the distributed utility-analysis path.
"""

import os
import sys

if os.environ.get("JAX_PLATFORMS"):
    # Honor the env var even when a sitecustomize-registered TPU plugin
    # would override it (same programmatic reset as tests/conftest.py).
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import pyspark
assert "fake_runners" in pyspark.__file__, pyspark.__file__

import pipelinedp_tpu as pdp
from pipelinedp_tpu import pipeline_backend, private_spark

ROWS = [(f"u{i % 30}", f"pk{i % 4}", float(i % 5)) for i in range(400)]
HUGE_EPS = 1e6
SC = pyspark.SparkContext()


def check(name, condition, detail=""):
    if not condition:
        print(f"FAILED: {name} {detail}", file=sys.stderr)
        sys.exit(1)
    print(f"ok: {name}")


def raw_counts():
    counts = {}
    for _, pk, _ in ROWS:
        counts[pk] = counts.get(pk, 0) + 1
    return counts


def test_backend_ops_match_local():
    backend = pipeline_backend.SparkRDDBackend(SC)
    local = pdp.LocalBackend()
    kv = [("a", 1), ("b", 2), ("a", 3), ("c", 4)]

    def run_both(op):
        got = list(op(backend)(SC.parallelize(kv)).collect())
        want = list(op(local)(iter(kv)))
        return got, want

    got, want = run_both(lambda b: lambda c: b.map(c, lambda x:
                                                   (x[0], x[1] * 10), "m"))
    check("map", sorted(got) == sorted(want))
    got, want = run_both(
        lambda b: lambda c: b.map_tuple(c, lambda k, v: (k, v + 1), "mt"))
    check("map_tuple", sorted(got) == sorted(want))
    got, want = run_both(
        lambda b: lambda c: b.map_values(c, lambda v: -v, "mv"))
    check("map_values", sorted(got) == sorted(want))
    got, want = run_both(
        lambda b: lambda c: b.filter(c, lambda x: x[1] > 1, "f"))
    check("filter", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.keys(c, "k"))
    check("keys", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.values(c, "v"))
    check("values", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.distinct(c, "d"))
    check("distinct", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.sum_per_key(c, "s"))
    check("sum_per_key", sorted(got) == sorted(want))
    got, want = run_both(lambda b: lambda c: b.count_per_element(c, "ce"))
    check("count_per_element", sorted(got) == sorted(want))
    got = {
        k: sorted(v)
        for k, v in backend.group_by_key(SC.parallelize(kv), "g").collect()
    }
    check("group_by_key", got == {"a": [1, 3], "b": [2], "c": [4]})
    got = sorted(
        backend.filter_by_key(SC.parallelize(kv), ["a", "c"],
                              "fbk").collect())
    check("filter_by_key(list)", got == [("a", 1), ("a", 3), ("c", 4)])
    got = sorted(
        backend.filter_by_key(SC.parallelize(kv), SC.parallelize(["b"]),
                              "fbk2").collect())
    check("filter_by_key(rdd)", got == [("b", 2)])
    got = sorted(
        backend.flatten(
            (SC.parallelize(kv), SC.parallelize([("z", 9)])), "fl").collect())
    check("flatten", got == sorted(kv + [("z", 9)]))
    got = sorted(
        backend.sample_fixed_per_key(SC.parallelize(kv), 1,
                                     "sfpk").collect())
    check("sample_fixed_per_key",
          [k for k, _ in got] == ["a", "b", "c"] and all(
              len(v) == 1 for _, v in got))


def test_dp_engine_on_spark():
    backend = pipeline_backend.SparkRDDBackend(SC)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant, backend)
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 max_partitions_contributed=4,
                                 max_contributions_per_partition=20,
                                 min_value=0.0,
                                 max_value=5.0)
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    result = engine.aggregate(SC.parallelize(ROWS), params, extractors,
                              [f"pk{i}" for i in range(4)])
    accountant.compute_budgets()
    got = dict(result.collect())
    for pk, want in raw_counts().items():
        assert abs(got[pk].count - want) < 0.5, (pk, got[pk].count, want)
    check("DPEngine.aggregate on SparkRDDBackend", True)


def test_private_rdd():
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    private = private_spark.make_private(SC.parallelize(ROWS), accountant,
                                         lambda r: r[0])
    mapped = private.map(lambda r: (r[1], r[2]))
    count = mapped.count(
        pdp.CountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                        max_partitions_contributed=4,
                        max_contributions_per_partition=20,
                        partition_extractor=lambda r: r[0]),
        public_partitions=[f"pk{i}" for i in range(4)])
    sums = mapped.sum(
        pdp.SumParams(noise_kind=pdp.NoiseKind.LAPLACE,
                      max_partitions_contributed=4,
                      max_contributions_per_partition=20,
                      min_value=0.0,
                      max_value=5.0,
                      partition_extractor=lambda r: r[0],
                      value_extractor=lambda r: r[1]),
        public_partitions=[f"pk{i}" for i in range(4)])
    selected = private.select_partitions(
        pdp.SelectPartitionsParams(max_partitions_contributed=4),
        partition_extractor=lambda r: r[1])
    flat = private.flat_map(lambda r: [r[2], r[2]])
    pid_count = flat.privacy_id_count(
        pdp.PrivacyIdCountParams(noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=1,
                                 partition_extractor=lambda v: "all"),
        public_partitions=["all"])
    accountant.compute_budgets()
    got_counts = dict(count.collect())
    for pk, want in raw_counts().items():
        assert abs(got_counts[pk] - want) < 0.5, (pk, got_counts[pk])
    check("PrivateRDD count/sum", len(dict(sums.collect())) == 4)
    check("PrivateRDD select_partitions",
          set(selected.collect()) == set(raw_counts()))
    got_pid = dict(pid_count.collect())
    check("PrivateRDD flat_map + privacy_id_count",
          abs(got_pid["all"] - 30) < 0.5)


def test_private_rdd_mean_variance():
    import numpy as _np
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-6)
    private = private_spark.make_private(SC.parallelize(ROWS), accountant,
                                         lambda r: r[0])
    mapped = private.map(lambda r: (r[1], r[2]))
    mean = mapped.mean(
        pdp.MeanParams(noise_kind=pdp.NoiseKind.GAUSSIAN,
                       max_partitions_contributed=4,
                       max_contributions_per_partition=20,
                       min_value=0.0,
                       max_value=5.0,
                       partition_extractor=lambda r: r[0],
                       value_extractor=lambda r: r[1]),
        public_partitions=[f"pk{i}" for i in range(4)])
    var = mapped.variance(
        pdp.VarianceParams(noise_kind=pdp.NoiseKind.GAUSSIAN,
                           max_partitions_contributed=4,
                           max_contributions_per_partition=20,
                           min_value=0.0,
                           max_value=5.0,
                           partition_extractor=lambda r: r[0],
                           value_extractor=lambda r: r[1]),
        public_partitions=[f"pk{i}" for i in range(4)])
    accountant.compute_budgets()
    raw_vals = {}
    for _, pk, v in ROWS:
        raw_vals.setdefault(pk, []).append(v)
    got_mean = dict(mean.collect())
    got_var = dict(var.collect())
    check("PrivateRDD mean",
          all(abs(got_mean[pk] - _np.mean(vs)) < 0.05
              for pk, vs in raw_vals.items()))
    check("PrivateRDD variance",
          all(abs(got_var[pk] - _np.var(vs)) < 0.1
              for pk, vs in raw_vals.items()))


def test_utility_analysis_on_spark():
    from pipelinedp_tpu import analysis
    from pipelinedp_tpu.analysis import data_structures
    backend = pipeline_backend.SparkRDDBackend(SC)
    options = data_structures.UtilityAnalysisOptions(
        epsilon=10,
        delta=1e-5,
        aggregate_params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.GAUSSIAN,
            max_partitions_contributed=2,
            max_contributions_per_partition=5))
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    reports, per_partition = analysis.perform_utility_analysis(
        SC.parallelize(ROWS), backend, options, extractors)
    reports = sorted(reports.collect(), key=lambda r: r.configuration_index)
    check("utility analysis on SparkRDDBackend",
          len(reports) == 1 and
          reports[0].partitions_info.num_dataset_partitions == 4)
    check("per-partition output on SparkRDDBackend",
          len(per_partition.collect()) == 4)


def test_executor_serialization_boundary():
    """Closures ship through cloudpickle: unserializable ones fail, and
    executors operate on copies of captured driver objects."""
    import threading
    lock = threading.Lock()
    bad = SC.parallelize([1, 2, 3]).map(lambda x: (lock, x)[1])
    try:
        bad.collect()
        check("unserializable closure rejected at the executor boundary",
              False)
    except TypeError:
        check("unserializable closure rejected at the executor boundary",
              True)

    driver_side = []
    out = SC.parallelize([1, 2, 3]).map(
        lambda x: (driver_side.append(x), x)[1]).collect()
    check("executors mutate a shipped COPY, not the driver object",
          out == [1, 2, 3] and driver_side == [])


if __name__ == "__main__":
    test_backend_ops_match_local()
    test_dp_engine_on_spark()
    test_private_rdd()
    test_private_rdd_mean_variance()
    test_utility_analysis_on_spark()
    test_executor_serialization_boundary()
    print("SPARK_CHECKS_PASSED")
