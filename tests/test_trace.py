"""Tracing + metrics-registry coverage.

Three contracts:

  * REGISTRY discipline: every ``telemetry.record("...")`` literal in
    the source tree names a declared registry metric, and every declared
    counter is recorded somewhere — the registry and the code cannot
    drift apart in either direction. Since PR 7 this is enforced by
    staticcheck's ``registry-drift`` AST rule (the source-scraping grep
    this file used to carry is gone); the tests here pin the rule's
    verdict on the real tree and prove both drift directions on
    fixtures.
  * Exporter validity: a dumped trace is valid Chrome/Perfetto
    trace-event JSON (json.loads + the required keys on every event),
    and trace_summary's inclusive/exclusive accounting is coherent.
  * Disabled cost: with tracing off, span() must be a near-free bool
    check — the blocked-driver hot path takes two of them per block.
"""

import json
import time

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import input_validators, pipeline_backend, staticcheck
from pipelinedp_tpu.runtime import health as rt_health
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.runtime import trace


@pytest.fixture(autouse=True)
def _trace_epoch():
    """Each test runs in a fresh trace epoch and leaves tracing off."""
    telemetry.reset()
    yield
    trace.disable()
    telemetry.reset()


class TestRegistry:

    @pytest.mark.staticcheck
    def test_registry_and_source_agree_both_directions(self):
        """The analyzer's registry-drift rule over the REAL tree: no
        record() literal without a declaration, no declaration without a
        recording site."""
        tree = staticcheck.load_tree(staticcheck.default_paths())
        found = staticcheck.analyze(
            tree, only_rules=["registry-drift"]).active
        assert found == [], "\n".join(f.render() for f in found)

    @pytest.mark.staticcheck
    def test_recorded_but_undeclared_literal_is_caught(self):
        mods = [
            staticcheck.parse_source(
                "pipelinedp_tpu/runtime/telemetry.py",
                "def _counter(name, help_text):\n"
                "    return (name, 'counter', help_text)\n"
                "REGISTRY = dict(a=_counter('used_counter', 'h'))\n"),
            staticcheck.parse_source(
                "pipelinedp_tpu/fix_user.py",
                "from pipelinedp_tpu.runtime import telemetry\n"
                "def f():\n"
                "    telemetry.record('used_counter')\n"
                "    telemetry.record('undeclared_counter')\n"),
        ]
        found = staticcheck.analyze(
            mods, only_rules=["registry-drift"]).active
        assert len(found) == 1
        assert "undeclared_counter" in found[0].message
        assert found[0].file == "pipelinedp_tpu/fix_user.py"

    @pytest.mark.staticcheck
    def test_declared_but_unrecorded_counter_is_caught(self):
        mods = [
            staticcheck.parse_source(
                "pipelinedp_tpu/runtime/telemetry.py",
                "def _counter(name, help_text):\n"
                "    return (name, 'counter', help_text)\n"
                "REGISTRY = dict(\n"
                "    a=_counter('used_counter', 'h'),\n"
                "    b=_counter('ghost_counter', 'h'))\n"),
            staticcheck.parse_source(
                "pipelinedp_tpu/fix_user.py",
                "from pipelinedp_tpu.runtime import telemetry\n"
                "def f():\n"
                "    telemetry.record('used_counter')\n"),
        ]
        found = staticcheck.analyze(
            mods, only_rules=["registry-drift"]).active
        assert len(found) == 1
        assert "ghost_counter" in found[0].message
        assert found[0].file == "pipelinedp_tpu/runtime/telemetry.py"

    @pytest.mark.staticcheck
    def test_set_gauge_of_undeclared_name_is_caught(self):
        mods = [
            staticcheck.parse_source(
                "pipelinedp_tpu/runtime/telemetry.py",
                "def _gauge(name, help_text):\n"
                "    return (name, 'gauge', help_text)\n"
                "REGISTRY = dict(a=_gauge('used_gauge', 'h'))\n"),
            staticcheck.parse_source(
                "pipelinedp_tpu/fix_user.py",
                "from pipelinedp_tpu.runtime import telemetry\n"
                "def f():\n"
                "    telemetry.set_gauge('used_gauge', 1)\n"
                "    telemetry.set_gauge('undeclared_gauge', 2)\n"),
        ]
        found = staticcheck.analyze(
            mods, only_rules=["registry-drift"]).active
        assert len(found) == 1
        assert "undeclared_gauge" in found[0].message
        assert found[0].file == "pipelinedp_tpu/fix_user.py"

    @pytest.mark.staticcheck
    def test_declared_but_never_set_gauge_is_caught(self):
        mods = [
            staticcheck.parse_source(
                "pipelinedp_tpu/runtime/telemetry.py",
                "def _gauge(name, help_text):\n"
                "    return (name, 'gauge', help_text)\n"
                "REGISTRY = dict(\n"
                "    a=_gauge('used_gauge', 'h'),\n"
                "    b=_gauge('ghost_gauge', 'h'))\n"),
            staticcheck.parse_source(
                "pipelinedp_tpu/fix_user.py",
                "from pipelinedp_tpu.runtime import telemetry\n"
                "def f():\n"
                "    telemetry.set_gauge('used_gauge', 1)\n"),
        ]
        found = staticcheck.analyze(
            mods, only_rules=["registry-drift"]).active
        assert len(found) == 1
        assert "ghost_gauge" in found[0].message
        assert found[0].file == "pipelinedp_tpu/runtime/telemetry.py"

    @pytest.mark.staticcheck
    def test_kind_mismatch_is_caught_both_ways(self):
        mods = [
            staticcheck.parse_source(
                "pipelinedp_tpu/runtime/telemetry.py",
                "def _counter(name, help_text):\n"
                "    return (name, 'counter', help_text)\n"
                "def _gauge(name, help_text):\n"
                "    return (name, 'gauge', help_text)\n"
                "REGISTRY = dict(\n"
                "    a=_counter('a_counter', 'h'),\n"
                "    b=_gauge('a_gauge', 'h'))\n"),
            staticcheck.parse_source(
                "pipelinedp_tpu/fix_user.py",
                "from pipelinedp_tpu.runtime import telemetry\n"
                "def f():\n"
                "    telemetry.record('a_gauge')\n"
                "    telemetry.set_gauge('a_counter', 1)\n"),
        ]
        found = staticcheck.analyze(
            mods, only_rules=["registry-drift"]).active
        messages = "\n".join(f.message for f in found)
        assert "declared as a gauge" in messages
        assert "declared as a counter" in messages

    def test_registry_entries_are_complete(self):
        kinds = set()
        for name, metric in telemetry.REGISTRY.items():
            assert metric.name == name
            assert metric.kind in ("counter", "gauge")
            assert metric.help and isinstance(metric.help, str)
            kinds.add(metric.kind)
        # Both kinds are live in the registry (counters since PR 2,
        # gauges since the observability plane).
        assert kinds == {"counter", "gauge"}

    def test_record_rejects_undeclared_names(self):
        with pytest.raises(ValueError, match="not a declared metric"):
            telemetry.record("totally_made_up_counter")

    def test_record_accepts_declared_names_with_attrs(self):
        telemetry.record("block_retries", block=7)
        assert telemetry.snapshot()["block_retries"] == 1


class TestSnapshotSplit:

    def test_snapshot_is_flat_ints(self):
        telemetry.record("block_retries")
        telemetry.record_duration("phase_y", 0.25)
        snap = telemetry.snapshot()
        assert snap == {"block_retries": 1}
        assert all(isinstance(v, int) for v in snap.values())

    def test_full_snapshot_is_structured(self):
        telemetry.record("block_retries")
        telemetry.record_duration("phase_y", 0.25)
        full = telemetry.full_snapshot()
        assert set(full) == {"counters", "gauges", "timings",
                             "job_timings"}
        assert full["counters"] == {"block_retries": 1}
        assert full["timings"]["phase_y"]["count"] == 1

    def test_delta_never_sees_timings(self):
        before = telemetry.snapshot()
        telemetry.record_duration("phase_y", 1.0)
        assert telemetry.delta(before) == {}
        telemetry.record("block_retries", 2)
        assert telemetry.delta(before) == {"block_retries": 2}


class TestCoordinatedReset:

    def test_reset_clears_counters_timings_health_and_trace(self):
        trace.enable()
        telemetry.record("block_retries")
        telemetry.record_duration("phase_z", 0.5)
        with rt_health.job_scope("reset-job"):
            telemetry.record_duration("phase_z", 0.5)
        with trace.span("s"):
            pass
        assert telemetry.snapshot()
        assert telemetry.timing_snapshot()
        assert rt_health.snapshot_all()
        assert trace.trace_summary()["n_events"] > 0
        telemetry.reset()
        assert telemetry.snapshot() == {}
        assert telemetry.timing_snapshot() == {}
        assert telemetry.job_timing_snapshot() == {}
        assert rt_health.snapshot_all() == {}
        assert trace.trace_summary()["n_events"] == 0


class TestSpans:

    def test_nesting_inclusive_exclusive(self):
        trace.enable()
        with trace.span("outer"):
            time.sleep(0.02)
            with trace.span("inner"):
                time.sleep(0.03)
        s = trace.trace_summary()["spans"]
        assert s["outer"]["count"] == 1
        assert s["inner"]["count"] == 1
        # Inclusive covers the child; exclusive subtracts it.
        assert s["outer"]["inclusive_s"] >= s["inner"]["inclusive_s"]
        assert s["outer"]["exclusive_s"] <= s["outer"]["inclusive_s"]
        # Summary values are rounded to 6 decimals; three roundings can
        # disagree by a few microseconds.
        assert (s["outer"]["exclusive_s"] + s["inner"]["inclusive_s"]
                == pytest.approx(s["outer"]["inclusive_s"], abs=5e-6))
        # The self-times partition the root: generous sleep-based bounds.
        assert s["inner"]["inclusive_s"] >= 0.02
        assert s["outer"]["exclusive_s"] >= 0.01

    def test_span_attrs_and_set(self):
        trace.enable()
        with trace.span("fetch", block=3) as sp:
            sp.set(bytes=4096)
        events = trace.to_trace_events()["traceEvents"]
        span_ev = [e for e in events if e.get("name") == "fetch"][0]
        assert span_ev["args"]["block"] == 3
        assert span_ev["args"]["bytes"] == 4096
        assert trace.trace_summary()["transfer_bytes"] == 4096

    def test_job_scoping(self):
        trace.enable()
        with rt_health.job_scope("job-a"):
            with trace.span("work"):
                pass
        with trace.span("unscoped"):
            pass
        scoped = trace.trace_summary(job_id="job-a")["spans"]
        assert set(scoped) == {"work"}

    def test_instants_from_counters(self):
        trace.enable()
        telemetry.record("journal_replays", block=5)
        summary = trace.trace_summary()
        assert summary["instants"].get("journal_replays") == 1

    def test_buffer_limit_counts_drops(self):
        trace.enable(buffer_limit=10)
        for _ in range(25):
            trace.instant("tick")
        summary = trace.trace_summary()
        assert summary["n_events"] == 10
        assert summary["dropped_events"] == 15

    def test_disabled_records_nothing(self):
        with trace.span("ghost"):
            trace.instant("ghost_tick")
        trace.enable()
        assert trace.trace_summary()["n_events"] == 0


class TestDisabledOverhead:
    """Disabled tracing must add no measurable per-span overhead: the
    blocked drivers take two span() calls per block, and the acceptance
    bar is < 2% driver throughput regression with tracing off."""

    def test_disabled_span_is_near_free(self):
        assert not trace.enabled()
        n = 200_000
        start = time.perf_counter()
        for _ in range(n):
            with trace.span("hot"):
                pass
        elapsed = time.perf_counter() - start
        # ~100-300ns/span on this class of hardware; 5µs/span is two
        # orders of magnitude of headroom against CI noise while still
        # catching an accidental allocation/lock on the disabled path.
        assert elapsed / n < 5e-6, (
            f"disabled span() costs {elapsed / n * 1e9:.0f}ns — the "
            f"disabled path must stay a bool check")
        assert trace.trace_summary()["n_events"] == 0


class TestExporter:

    def test_dump_is_valid_chrome_trace_json(self, tmp_path):
        trace.enable()
        with trace.span("outer", rows=4):
            with trace.span("inner"):
                pass
            trace.instant("incident", block=1)
        path = trace.dump(str(tmp_path / "trace.json"))
        with open(path) as f:
            payload = json.load(f)
        assert set(payload) >= {"traceEvents", "displayTimeUnit"}
        events = payload["traceEvents"]
        assert isinstance(events, list) and len(events) == 4  # M + 2X + i
        for ev in events:
            assert {"name", "ph", "pid", "tid", "ts"} <= set(ev), ev
            assert ev["ph"] in ("X", "i", "M")
            if ev["ph"] == "X":
                assert ev["dur"] >= 0
            if ev["ph"] == "i":
                assert ev["s"] == "t"
        names = {e["name"] for e in events}
        assert {"outer", "inner", "incident"} <= names

    def test_dump_filters_by_job(self, tmp_path):
        trace.enable()
        with rt_health.job_scope("job-x"):
            with trace.span("mine"):
                pass
        with trace.span("theirs"):
            pass
        path = trace.dump(str(tmp_path / "trace.json"), job_id="job-x")
        with open(path) as f:
            names = {e["name"] for e in json.load(f)["traceEvents"]}
        assert "mine" in names and "theirs" not in names


class TestJitProbe:

    def test_compile_miss_and_hit_attribution(self):
        import jax
        import jax.numpy as jnp
        probed = trace.probe_jit("probe_target",
                                 jax.jit(lambda x: x * 2 + 1))
        trace.enable()
        x = jnp.ones(16)
        np.testing.assert_allclose(np.asarray(probed(x)), 3.0)
        probed(x)  # cache hit: no new compile
        stats = trace.compile_stats()
        assert stats["probe_target"]["misses"] == 1
        assert stats["probe_target"]["compile_s"] > 0
        probed(jnp.ones(32))  # new shape: second compile
        assert trace.compile_stats()["probe_target"]["misses"] == 2
        summary = trace.trace_summary()
        assert summary["spans"]["jit:probe_target"]["count"] == 3
        assert summary["instants"]["jit_compile:probe_target"] == 2
        assert telemetry.snapshot()["jit_cache_misses"] == 2

    def test_untraced_calls_skip_attribution(self):
        import jax
        import jax.numpy as jnp
        probed = trace.probe_jit("probe_quiet", jax.jit(lambda x: x + 1))
        probed(jnp.ones(8))
        assert trace.compile_stats() == {}


class TestBackendIntegration:

    def test_trace_knob_validation(self):
        with pytest.raises(ValueError, match="trace"):
            pipeline_backend.TPUBackend(trace="yes")
        with pytest.raises(ValueError, match="trace"):
            input_validators.validate_trace("/tmp/trace.json", "T")

    def test_backend_trace_enables_and_dumps(self, tmp_path):
        backend = pdp.TPUBackend(noise_seed=5, trace=True)
        assert trace.enabled()
        rng = np.random.default_rng(1)
        rows = list(
            zip(rng.integers(0, 40, 800).tolist(),
                rng.integers(0, 20, 800).tolist(),
                rng.uniform(0, 5, 800).tolist()))
        ex = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                partition_extractor=lambda r: r[1],
                                value_extractor=lambda r: r[2])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=4,
            max_contributions_per_partition=8,
            min_value=0.0,
            max_value=5.0)
        # High epsilon: partition selection keeps the dense partitions
        # with probability ~1, so the decode/post-process spans run.
        acc = pdp.NaiveBudgetAccountant(total_epsilon=100.0,
                                        total_delta=1e-6)
        engine = pdp.DPEngine(acc, backend)
        result = engine.aggregate(rows, params, ex)
        acc.compute_budgets()
        assert dict(result)
        summary = backend.trace_summary()
        # The stage spans of the fused path, plus ledger instants.
        for expected in ("graph_build", "encode", "dispatch", "drain",
                         "post_process"):
            assert expected in summary["spans"], (expected,
                                                  sorted(summary["spans"]))
        assert summary["instants"].get("budget_registrations", 0) >= 1
        path = backend.dump_trace(str(tmp_path / "engine_trace.json"))
        with open(path) as f:
            payload = json.load(f)
        assert len(payload["traceEvents"]) > 5

    def test_blocked_driver_spans_and_phase_partition(self):
        """A blocked run's spans decompose its wall time: per-block
        dispatch/drain spans exist and the sum of exclusive times
        reconciles (within 10%) with the driver's entry span."""
        import jax
        from pipelinedp_tpu import combiners, executor
        from pipelinedp_tpu.aggregate_params import MechanismType
        from pipelinedp_tpu.ops import selection_ops
        from pipelinedp_tpu.parallel import large_p

        P = 1 << 12
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=2,
            max_contributions_per_partition=3,
            min_value=0.0,
            max_value=5.0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, acc)
        budget = acc.request_budget(MechanismType.GENERIC)
        acc.compute_budgets()
        selection = selection_ops.selection_params_from_host(
            params.partition_selection_strategy, budget.eps, budget.delta,
            params.max_partitions_contributed, None)
        cfg = executor.make_kernel_config(params, compound, P,
                                          private_selection=True,
                                          selection_params=selection)
        stds = executor.compute_noise_stds(compound, params)
        scalars = executor.kernel_scalars(params)
        rng = np.random.default_rng(3)
        n = 4000
        pid = rng.integers(0, 200, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        values = rng.uniform(0, 5, n)
        valid = np.ones(n, bool)
        args = (pid, pk, values, valid, *scalars, np.asarray(stds),
                jax.random.PRNGKey(11), cfg)
        large_p.aggregate_blocked(*args, block_partitions=1 << 10)  # warm
        trace.enable()
        # Serial consume loop (overlap=False): the one-thread timeline
        # whose exclusive span times partition the root span by
        # construction. The overlapped drainer records the SAME spans
        # on its own thread — they overlap the dispatch timeline, so
        # only presence (not partition) is asserted for it below.
        large_p.aggregate_blocked(*args, block_partitions=1 << 10,
                                  overlap=False)
        spans = trace.trace_summary()["spans"]
        for expected in ("aggregate_blocked", "contribution_bounding",
                         "dispatch", "drain", "consume"):
            assert expected in spans, (expected, sorted(spans))
        assert spans["dispatch"]["count"] >= 2  # several blocks
        root = spans["aggregate_blocked"]["inclusive_s"]
        attributed = sum(s["exclusive_s"] for s in spans.values())
        assert abs(attributed - root) <= 0.1 * root + 1e-3, (
            attributed, root)
        trace.reset()
        large_p.aggregate_blocked(*args, block_partitions=1 << 10)
        spans_overlapped = trace.trace_summary()["spans"]
        for expected in ("aggregate_blocked", "contribution_bounding",
                         "dispatch", "drain", "consume"):
            assert expected in spans_overlapped, (
                expected, sorted(spans_overlapped))
