"""Multi-tenant service coverage (pipelinedp_tpu/service/).

The contracts under test:

  * **Bit-identity under concurrency** — two tenants submitting at the
    same time over ONE backend produce exactly the outputs their
    serial, service-less runs produce (per-job accountants, per-job
    noise seeds, per-job backend views; nothing shared but the mesh
    and the compile caches).
  * **Ledger of record** — per-tenant cumulative spend is the job's
    odometer trail: disjoint between tenants, bit-exactly equal to
    each job's ``BudgetAccountant.spent_epsilon()``, durable across a
    service restart through the CRC-verified journal.
  * **Admission control** — a tenant at its lifetime budget is refused
    BEFORE any mechanism registers; the memory-watermark shed and the
    queue-timeout shed raise typed AdmissionRejectedError with a
    retry-after and release their reservations.
  * **Compile-cache reuse** — the second tenant submitting an
    identical spec records 0 jit cache misses on its own job record.
  * **The reset guard** — telemetry.reset() warns and no-ops while any
    job_scope is live (a resident service always has some), so an
    epoch reset can no longer wipe a running job's state.
"""

import threading
import time

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.runtime import health as rt_health
from pipelinedp_tpu.runtime import observability as obs
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.runtime import trace
from pipelinedp_tpu.service import (
    AdmissionRejectedError,
    DPAggregationService,
    JobSpec,
    JobStatus,
    TenantBudgetExceededError,
    TenantLedger,
)
from pipelinedp_tpu.service import service as service_module

pytestmark = pytest.mark.service


@pytest.fixture(autouse=True)
def _service_epoch():
    telemetry.reset()
    yield
    trace.disable()
    telemetry.reset()


ROWS_A = [("u1", "A", 1.0), ("u1", "A", 2.0), ("u2", "A", 1.0),
          ("u2", "B", 3.0), ("u3", "A", 2.0), ("u3", "B", 1.0)]
ROWS_B = [("v1", "X", 4.0), ("v1", "Y", 1.0), ("v2", "X", 2.0),
          ("v2", "Y", 2.0), ("v3", "X", 1.0)]


def _params():
    return pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                               max_partitions_contributed=2,
                               max_contributions_per_partition=3,
                               min_value=0.0,
                               max_value=5.0)


def _spec(seed, public, epsilon=1.0):
    return JobSpec(params=_params(), epsilon=epsilon, delta=1e-6,
                   noise_seed=seed, public_partitions=public)


def _extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def _reference_run(spec, rows):
    """The serial, service-less run of the same spec: same noise seed,
    same budget, fresh accountant — the bit-identity baseline."""
    backend = pdp.TPUBackend(noise_seed=spec.noise_seed)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=spec.epsilon,
                                           total_delta=spec.delta)
    engine = pdp.DPEngine(accountant, backend)
    lazy = engine.aggregate(rows, spec.params, _extractors(),
                            spec.public_partitions)
    accountant.compute_budgets()
    return dict(lazy), accountant


class _SlowRows:
    """Row source whose iteration stalls — holds a worker busy so
    queue-timeout and stop() behavior become observable."""

    def __init__(self, rows, delay_s):
        self._rows = rows
        self._delay_s = delay_s

    def __iter__(self):
        time.sleep(self._delay_s)
        return iter(self._rows)


class _PoisonRows:
    """Row source that explodes mid-iteration — a job failure AFTER
    its mechanisms registered (graph build saw a valid collection)."""

    def __iter__(self):
        raise RuntimeError("injected source failure")


class _EmptyMsgPoison:
    """Row source whose failure carries an EMPTY message (str(e) == "")
    — the shape that used to crash the failure handler's log line."""

    def __iter__(self):
        raise ValueError()


class TestConcurrentBitIdentity:

    def test_two_tenants_concurrent_equal_serial(self):
        spec_a = _spec(seed=11, public=["A", "B"])
        spec_b = _spec(seed=23, public=["X", "Y"])
        want_a, acc_a = _reference_run(spec_a, ROWS_A)
        want_b, acc_b = _reference_run(spec_b, ROWS_B)
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=2,
                                  tenant_budget_epsilon=10.0) as svc:
            ha = svc.submit("tenant-a", spec_a, ROWS_A)
            hb = svc.submit("tenant-b", spec_b, ROWS_B)
            got_a = ha.result(timeout=120)
            got_b = hb.result(timeout=120)
            # Bit-identical to the serial runs: float equality, not
            # approx — same seeds, same kernel, same release.
            assert got_a == want_a
            assert got_b == want_b
            # Disjoint ledgers, each reconciling bit-exactly with its
            # job's accountant.
            led_a = svc.tenant_ledger("tenant-a")
            led_b = svc.tenant_ledger("tenant-b")
            assert led_a.job_spent_epsilon(ha.job_id) == \
                acc_a.spent_epsilon()
            assert led_b.job_spent_epsilon(hb.job_id) == \
                acc_b.spent_epsilon()
            assert led_a.job_spent_epsilon(hb.job_id) == 0.0
            assert led_b.job_spent_epsilon(ha.job_id) == 0.0
            assert svc.ledgers_reconciled()
            assert ha.spent_epsilon == acc_a.spent_epsilon()

    def test_select_partitions_job(self):
        params = pdp.SelectPartitionsParams(max_partitions_contributed=2)
        rows = [(f"u{i}", "P", 0.0) for i in range(200)] + \
               [(f"u{i}", "Q", 0.0) for i in range(200)]
        spec = JobSpec(params=params, epsilon=5.0, delta=1e-4,
                       noise_seed=3)
        with DPAggregationService(pdp.TPUBackend()) as svc:
            handle = svc.submit("tenant-s", spec, rows)
            kept = handle.result(timeout=120)
            assert set(kept) <= {"P", "Q"}
            assert len(kept) == 2  # 200 ids each: kept w.p. ~1
            assert handle.spent_epsilon == pytest.approx(5.0)
            assert svc.ledgers_reconciled()


class TestTenantBudget:

    def test_exhausted_tenant_rejected_before_any_registration(self):
        with DPAggregationService(pdp.TPUBackend(),
                                  tenant_budget_epsilon=1.0) as svc:
            first = svc.submit("tenant-x", _spec(7, ["A", "B"],
                                                 epsilon=0.8), ROWS_A)
            assert first.result(timeout=120) is not None
            before = telemetry.snapshot().get("budget_registrations", 0)
            mechanisms_before = obs.odometer_report()["mechanisms"]
            with pytest.raises(TenantBudgetExceededError) as exc:
                svc.submit("tenant-x", _spec(8, ["A", "B"], epsilon=0.5),
                           ROWS_A)
            assert exc.value.retry_after_s is None
            # Rejected before the job existed: zero new mechanisms,
            # zero new odometer records.
            assert telemetry.snapshot().get("budget_registrations",
                                            0) == before
            assert obs.odometer_report()["mechanisms"] == \
                mechanisms_before
            # A grant that still fits is admitted.
            ok = svc.submit("tenant-x", _spec(9, ["A", "B"],
                                              epsilon=0.2), ROWS_A)
            assert ok.result(timeout=120) is not None

    def test_reservations_count_against_concurrent_submissions(self):
        # One worker, lifetime 1.0: while the first 0.7 job is still
        # queued/running, a second 0.7 must already be refused.
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=1,
                                  tenant_budget_epsilon=1.0) as svc:
            slow = _SlowRows(ROWS_A, delay_s=0.3)
            h1 = svc.submit("tenant-r", _spec(1, ["A", "B"], epsilon=0.7),
                            slow)
            with pytest.raises(TenantBudgetExceededError):
                svc.submit("tenant-r", _spec(2, ["A", "B"], epsilon=0.7),
                           ROWS_A)
            assert h1.result(timeout=120) is not None

    def test_failed_before_registration_releases_grant(self):
        with DPAggregationService(pdp.TPUBackend(),
                                  tenant_budget_epsilon=1.0) as svc:
            bad = JobSpec(params=_params(), epsilon=0.9, delta=1e-6,
                          noise_seed=1, public_partitions=["A"])
            handle = svc.submit("tenant-f", bad, None)  # col=None fails
            with pytest.raises(Exception):
                handle.result(timeout=120)
            assert handle.status == JobStatus.FAILED
            ledger = svc.tenant_ledger("tenant-f")
            assert ledger.spent_epsilon() == 0.0
            assert ledger.reserved_epsilon() == 0.0

    def test_empty_message_failure_keeps_worker_alive(self):
        """Regression: a job failing with an empty exception message
        used to IndexError inside the failure handler's log formatting
        AFTER the ledger settled but BEFORE the handle failed — the
        worker thread died, result() blocked forever, and the pool
        permanently lost a worker."""
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=1,
                                  tenant_budget_epsilon=5.0) as svc:
            bad = svc.submit("tenant-w", _spec(1, ["A"]),
                             _EmptyMsgPoison())
            with pytest.raises(ValueError):
                bad.result(timeout=120)
            assert bad.status == JobStatus.FAILED
            # The single worker survived the crash: the next job on
            # the same worker still runs to completion.
            ok = svc.submit("tenant-w", _spec(2, ["A", "B"]), ROWS_A)
            assert ok.result(timeout=120) is not None
            assert svc.tenant_ledger("tenant-w").reserved_epsilon() == 0.0

    def test_failed_after_registration_forfeits_grant(self):
        with DPAggregationService(pdp.TPUBackend(),
                                  tenant_budget_epsilon=1.0) as svc:
            spec = _spec(1, ["A"], epsilon=0.9)
            handle = svc.submit("tenant-g", spec, _PoisonRows())
            with pytest.raises(RuntimeError, match="injected source"):
                handle.result(timeout=120)
            ledger = svc.tenant_ledger("tenant-g")
            # The full admission grant is conservatively charged: the
            # graph existed, so a release cannot be ruled out.
            assert ledger.spent_epsilon() == 0.9
            records = ledger.records()
            assert records[-1]["metric"] == "admission_grant_forfeit"


class TestLedgerPersistence:

    def test_ledger_survives_service_restart(self, tmp_path):
        ledger_dir = str(tmp_path)
        spec = _spec(5, ["A", "B"], epsilon=0.6)
        with DPAggregationService(pdp.TPUBackend(), ledger_dir,
                                  tenant_budget_epsilon=1.0) as svc:
            handle = svc.submit("tenant-p", spec, ROWS_A)
            handle.result(timeout=120)
            spent = handle.spent_epsilon
            assert spent == 0.6
        # A FRESH service over the same ledger directory reloads the
        # trail through the CRC-verified journal read path.
        with DPAggregationService(pdp.TPUBackend(), ledger_dir,
                                  tenant_budget_epsilon=1.0) as svc2:
            ledger = svc2.tenant_ledger("tenant-p")
            assert ledger.spent_epsilon() == spent  # bit-exact
            assert ledger.job_spent_epsilon(handle.job_id) == spent
            with pytest.raises(TenantBudgetExceededError):
                svc2.submit("tenant-p", _spec(6, ["A", "B"], epsilon=0.5),
                            ROWS_A)
            ok = svc2.submit("tenant-p", _spec(7, ["A", "B"],
                                               epsilon=0.3), ROWS_A)
            assert ok.result(timeout=120) is not None
            assert svc2.ledgers_reconciled()

    def test_restart_job_ids_never_collide_with_persisted(self, tmp_path):
        """Regression: a restarted service used to restart its job
        sequence at 1, so its first job reused a persisted job id and
        job_spent_epsilon()/reconciles() merged two runs' records."""
        ledger_dir = str(tmp_path)
        with DPAggregationService(pdp.TPUBackend(), ledger_dir,
                                  tenant_budget_epsilon=2.0) as svc:
            h1 = svc.submit("tenant-c", _spec(5, ["A", "B"], epsilon=0.6),
                            ROWS_A)
            h1.result(timeout=120)
        with DPAggregationService(pdp.TPUBackend(), ledger_dir,
                                  tenant_budget_epsilon=2.0) as svc2:
            # The FIRST submission after restart (nothing consumed the
            # would-be colliding sequence number first).
            h2 = svc2.submit("tenant-c",
                             _spec(6, ["A", "B"], epsilon=0.6), ROWS_A)
            h2.result(timeout=120)
            assert h2.job_id != h1.job_id
            ledger = svc2.tenant_ledger("tenant-c")
            # Per-job spends stay per-job — no cross-run merge.
            assert ledger.job_spent_epsilon(h1.job_id) == h1.spent_epsilon
            assert ledger.job_spent_epsilon(h2.job_id) == h2.spent_epsilon
            assert ledger.spent_epsilon() == \
                h1.spent_epsilon + h2.spent_epsilon
            assert svc2.ledgers_reconciled()

    def test_ledger_records_ride_the_odometer_format(self, tmp_path):
        from pipelinedp_tpu.runtime import journal as rt_journal
        journal = rt_journal.BlockJournal(str(tmp_path))
        ledger = TenantLedger("tenant-o", 2.0, journal)
        ledger.reserve("job-1", 1.0)
        ledger.charge("job-1", [{
            "seq": 0, "job_id": "job-1", "metric": "count",
            "mechanism_kind": "MechanismType.LAPLACE", "weight": 1.0,
            "sensitivity": 1.0, "count": 1, "process_index": 0,
            "eps": 1.0, "delta": 0.0,
        }])
        loaded = obs.load_odometer(
            rt_journal.BlockJournal(str(tmp_path)), "tenant-o")
        assert len(loaded) == 1
        assert loaded[0]["eps"] == 1.0
        assert loaded[0]["metric"] == "count"


class TestCompileCacheReuse:

    def test_second_identical_spec_zero_jit_misses(self):
        trace.enable()  # probe_jit only attributes with tracing on
        spec1 = _spec(seed=41, public=["A", "B"])
        spec2 = _spec(seed=42, public=["A", "B"])
        assert spec1.cache_key == spec2.cache_key
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=1) as svc:
            h1 = svc.submit("tenant-1", spec1, ROWS_A)
            h1.result(timeout=120)
            h2 = svc.submit("tenant-2", spec2, ROWS_A)
            h2.result(timeout=120)
            # Same spec, same row bucket -> the second tenant's job hit
            # every compiled entry point the first one built.
            assert h2.jit_cache_misses == 0
            reuse = svc.compile_reuse()[spec1.cache_key]
            assert reuse["jobs"] == 2
            assert reuse["jit_cache_misses"] == (h1.jit_cache_misses or 0)

    def test_distinct_specs_distinct_cache_keys(self):
        a = _spec(1, ["A"])
        b = JobSpec(params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=1.0), epsilon=1.0, delta=1e-6)
        assert a.cache_key != b.cache_key


class TestAdmissionControl:

    def test_watermark_shed_with_injected_squeeze(self, monkeypatch):
        monkeypatch.setattr(
            obs, "memory_watermark",
            lambda: {"live_bytes": 9_000, "peak_bytes": 9_000,
                     "source": "accounted"})
        with DPAggregationService(pdp.TPUBackend(),
                                  shed_watermark_fraction=0.5,
                                  memory_limit_bytes=10_000) as svc:
            before = telemetry.snapshot().get("service_jobs_shed", 0)
            with pytest.raises(AdmissionRejectedError) as exc:
                svc.submit("tenant-m", _spec(1, ["A"]), ROWS_A)
            assert exc.value.retry_after_s is not None
            assert not isinstance(exc.value, TenantBudgetExceededError)
            assert telemetry.snapshot()["service_jobs_shed"] == before + 1
            # Squeeze clears -> admission resumes.
            monkeypatch.setattr(
                obs, "memory_watermark",
                lambda: {"live_bytes": 100, "peak_bytes": 9_000,
                         "source": "accounted"})
            handle = svc.submit("tenant-m", _spec(1, ["A", "B"]), ROWS_A)
            assert handle.result(timeout=120) is not None

    def test_queue_timeout_sheds_and_releases_reservation(self):
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=1,
                                  tenant_budget_epsilon=2.0,
                                  queue_timeout_s=0.05) as svc:
            slow = _SlowRows(ROWS_A, delay_s=0.5)
            h1 = svc.submit("tenant-q", _spec(1, ["A", "B"]), slow)
            h2 = svc.submit("tenant-q", _spec(2, ["A", "B"]), ROWS_A)
            with pytest.raises(AdmissionRejectedError) as exc:
                h2.result(timeout=120)
            assert exc.value.retry_after_s == pytest.approx(0.05)
            assert h2.status == JobStatus.SHED
            assert h1.result(timeout=120) is not None
            ledger = svc.tenant_ledger("tenant-q")
            assert ledger.reserved_epsilon() == 0.0
            assert ledger.spent_epsilon() == h1.spent_epsilon

    def test_stop_cancels_queued_jobs_and_releases_grants(self):
        svc = DPAggregationService(pdp.TPUBackend(),
                                   max_concurrent_jobs=1,
                                   tenant_budget_epsilon=5.0)
        slow = _SlowRows(ROWS_A, delay_s=0.3)
        h1 = svc.submit("tenant-z", _spec(1, ["A", "B"]), slow)
        h2 = svc.submit("tenant-z", _spec(2, ["A", "B"]), ROWS_A)
        # Let the single worker pick h1 up; the stop sentinel preempts
        # everything still queued (h2), never a running job.
        deadline = time.monotonic() + 10
        while h1.status == JobStatus.QUEUED and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        svc.stop()
        assert h1.done() and h2.done()
        assert h1.status == JobStatus.DONE
        with pytest.raises(AdmissionRejectedError, match="stopped"):
            h2.result(timeout=1)
        assert svc.tenant_ledger("tenant-z").reserved_epsilon() == 0.0
        with pytest.raises(RuntimeError, match="stopped"):
            svc.submit("tenant-z", _spec(3, ["A"]), ROWS_A)

    def test_submit_racing_stop_releases_reservation(self, monkeypatch):
        """Regression: stop() landing between submit's admission checks
        and its enqueue used to leave the job in a queue no worker
        would ever read — the handle never completed and the tenant's
        reservation leaked. The enqueue now re-checks _stopped under
        the lock and refuses (releasing the grant) instead."""
        svc = DPAggregationService(pdp.TPUBackend(),
                                   tenant_budget_epsilon=1.0)
        orig_shed_check = svc._shed_check

        def shed_check_then_stop():
            orig_shed_check()
            svc.stop()  # the race, made deterministic

        monkeypatch.setattr(svc, "_shed_check", shed_check_then_stop)
        with pytest.raises(RuntimeError, match="stopped"):
            svc.submit("tenant-race", _spec(1, ["A"]), ROWS_A)
        assert svc.tenant_ledger("tenant-race").reserved_epsilon() == 0.0

    def test_priority_orders_the_queue(self):
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=1,
                                  queue_timeout_s=60.0) as svc:
            order = []
            slow = _SlowRows(ROWS_A, delay_s=0.2)
            h0 = svc.submit("t", _spec(1, ["A", "B"]), slow)
            # Queued while the worker is busy: the urgent (lower
            # priority value) job must run before the earlier lazy one.
            lazy_spec = _spec(2, ["A", "B"])
            lazy_spec.priority = 5
            urgent_spec = _spec(3, ["A", "B"])
            urgent_spec.priority = 1
            h_lazy = svc.submit("t", lazy_spec, _Recorder(order, "lazy"))
            h_urgent = svc.submit("t", urgent_spec,
                                  _Recorder(order, "urgent"))
            h0.result(timeout=120)
            h_lazy.result(timeout=120)
            h_urgent.result(timeout=120)
            assert order == ["urgent", "lazy"]


class _Recorder:
    """Row source that records when it is first iterated."""

    def __init__(self, order, name):
        self._order = order
        self._name = name

    def __iter__(self):
        self._order.append(self._name)
        return iter(ROWS_A)


class TestResidentGrowthBounds:
    """A resident service must not grow without bound: completed jobs
    leave the process-global odometer (their ledger is the record) and
    completed handles are evicted beyond a retention cap."""

    def test_completed_jobs_prune_their_odometer_records(self):
        with DPAggregationService(pdp.TPUBackend()) as svc:
            svc.submit("tenant-1", _spec(1, ["A", "B"]),
                       ROWS_A).result(timeout=120)
            svc.submit("tenant-2", _spec(2, ["A", "B"]),
                       ROWS_A).result(timeout=120)
            # Both jobs' trails moved to their tenant ledgers of
            # record; the global trail holds nothing for them.
            assert obs.odometer_report()["mechanisms"] == 0
            assert svc.ledgers_reconciled()
            assert svc.tenant_ledger("tenant-1").records()

    def test_failed_jobs_prune_their_odometer_records(self):
        with DPAggregationService(pdp.TPUBackend(),
                                  tenant_budget_epsilon=2.0) as svc:
            handle = svc.submit("tenant-p", _spec(1, ["A"], epsilon=0.5),
                                _PoisonRows())
            with pytest.raises(RuntimeError):
                handle.result(timeout=120)
            assert obs.odometer_report()["mechanisms"] == 0

    def test_handle_retention_is_bounded(self, monkeypatch):
        monkeypatch.setattr(service_module, "_MAX_RETAINED_HANDLES", 3)
        with DPAggregationService(pdp.TPUBackend()) as svc:
            for i in range(6):
                svc.submit("tenant-h", _spec(i + 1, ["A", "B"]),
                           ROWS_A).result(timeout=120)
            retained = svc.handles()
            assert len(retained) == 3
            # Newest completed jobs are the ones kept.
            assert all(h.status == JobStatus.DONE for h in retained)
            assert svc.ledgers_reconciled()
            # The ledger keeps the FULL history regardless of handle
            # eviction.
            ledger = svc.tenant_ledger("tenant-h")
            assert len(ledger.snapshot()["jobs"]) == 6


class TestServiceMetrics:

    def test_service_counters_export_through_strict_parser(self):
        with DPAggregationService(pdp.TPUBackend()) as svc:
            handle = svc.submit("tenant-e", _spec(1, ["A", "B"]), ROWS_A)
            handle.result(timeout=120)
        parsed = obs.parse_prometheus(obs.render_prometheus())
        assert parsed["pdp_service_jobs_queued"]["samples"][""] >= 1.0
        assert parsed["pdp_service_jobs_admitted"]["samples"][""] >= 1.0
        assert parsed["pdp_service_jobs_shed"]["samples"][""] == 0.0
        assert parsed["pdp_service_active_jobs"]["type"] == "gauge"
        assert parsed["pdp_service_active_jobs"]["samples"][""] == 0.0
        assert parsed["pdp_service_queue_depth"]["samples"][""] == 0.0

    def test_stats_rollup(self):
        with DPAggregationService(pdp.TPUBackend()) as svc:
            svc.submit("tenant-e", _spec(1, ["A", "B"]),
                       ROWS_A).result(timeout=120)
            stats = svc.stats()
            assert stats["jobs_admitted"] >= 1
            assert stats["jobs_by_status"][JobStatus.DONE] == 1
            assert stats["ledgers_reconciled"]
            assert "tenant-e" in stats["ledgers"]


class TestValidation:

    def test_bad_knobs_rejected(self):
        backend = pdp.TPUBackend()
        with pytest.raises(ValueError, match="max_concurrent_jobs"):
            DPAggregationService(backend, max_concurrent_jobs=0)
        with pytest.raises(ValueError, match="tenant_budget_epsilon"):
            DPAggregationService(backend, tenant_budget_epsilon=-1.0)
        with pytest.raises(ValueError, match="queue_timeout_s"):
            DPAggregationService(backend, queue_timeout_s=0)
        with pytest.raises(ValueError, match="shed_watermark_fraction"):
            DPAggregationService(backend, shed_watermark_fraction=1.5)
        with pytest.raises(ValueError, match="TPUBackend"):
            DPAggregationService(pdp.LocalBackend())

    def test_path_unsafe_tenant_id_rejected(self):
        with DPAggregationService(pdp.TPUBackend()) as svc:
            with pytest.raises(ValueError, match="path"):
                svc.submit("ten/ant", _spec(1, ["A"]), ROWS_A)

    def test_bad_spec_rejected(self):
        with DPAggregationService(pdp.TPUBackend()) as svc:
            with pytest.raises(ValueError, match="JobSpec"):
                svc.submit("tenant", _params(), ROWS_A)
            with pytest.raises(ValueError, match="epsilon"):
                svc.submit("tenant", _spec(1, ["A"], epsilon=-1.0),
                           ROWS_A)


class TestResetGuard:

    def test_reset_refuses_while_job_scope_active(self):
        """The satellite regression: a process-wide epoch reset during
        a live job would wipe its health/odometer state — the guard
        warns and no-ops instead."""
        started = threading.Event()
        release = threading.Event()

        def hold():
            with rt_health.job_scope("live-job"):
                telemetry.record("block_retries")
                started.set()
                release.wait(20)

        worker = threading.Thread(target=hold)
        worker.start()
        try:
            assert started.wait(10)
            assert rt_health.active_job_scopes() == 1
            telemetry.reset()  # guard: no-op while the scope is live
            assert telemetry.snapshot().get("block_retries") == 1
            assert rt_health.snapshot_all().get("live-job") is not None
            telemetry.reset(force=True)  # explicit override still works
            assert telemetry.snapshot() == {}
        finally:
            release.set()
            worker.join(timeout=20)
        assert rt_health.active_job_scopes() == 0
        telemetry.reset()  # no scopes left: the plain reset works again
        assert telemetry.snapshot() == {}
