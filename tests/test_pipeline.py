"""Tests for the device-resident streaming executor
(pipelinedp_tpu/runtime/pipeline.py) and its integration through
ingest.stream_encode_columns, the ChunkSource engine entry and the
TPUBackend pipeline knobs.

The load-bearing invariant: pipelined execution is BIT-IDENTICAL to
serial execution — same vocabularies, same pad_rows buffers, same noise
keys, same outputs, zero duplicate budget registrations — at every
tested pipeline depth, including under injected faults and journaled
resume.
"""

import threading
import time

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import executor, ingest, staticcheck
from pipelinedp_tpu.runtime import BlockJournal, Watchdog
from pipelinedp_tpu.runtime import faults as rt_faults
from pipelinedp_tpu.runtime import pipeline as rt_pipeline
from pipelinedp_tpu.runtime import telemetry as rt_telemetry
from pipelinedp_tpu.runtime import watchdog as rt_watchdog
from pipelinedp_tpu.runtime.watchdog import BlockTimeoutError

pytestmark = pytest.mark.pipeline

HUGE_EPS = 1e7


@pytest.fixture(autouse=True)
def _clean_telemetry():
    rt_telemetry.reset()
    yield
    rt_telemetry.reset()


# ---------------------------------------------------------------------------
# map_overlapped: ordering, backpressure, error propagation
# ---------------------------------------------------------------------------


class TestMapOverlapped:

    def test_preserves_input_order_under_racing_workers(self):
        # Later items finish first (decreasing sleeps); order must hold.
        def slow_square(x):
            time.sleep(0.02 * (8 - x) / 8)
            return x * x

        out = list(
            rt_pipeline.map_overlapped(range(8), slow_square,
                                       encode_threads=4, depth=8))
        assert out == [x * x for x in range(8)]

    def test_backpressure_bounds_in_flight_window(self):
        depth = 3
        in_flight = []
        lock = threading.Lock()
        peak = [0]

        def tracked(x):
            with lock:
                in_flight.append(x)
                peak[0] = max(peak[0], len(in_flight))
            time.sleep(0.01)
            with lock:
                in_flight.remove(x)
            return x

        consumed = []
        for x in rt_pipeline.map_overlapped(range(20), tracked,
                                            encode_threads=4,
                                            depth=depth):
            time.sleep(0.005)  # slow consumer -> producer must stall
            consumed.append(x)
        assert consumed == list(range(20))
        # The semaphore bounds submitted-but-unconsumed items at `depth`;
        # concurrently RUNNING workers can never exceed that.
        assert peak[0] <= depth

    def test_worker_exception_surfaces_as_original_type(self):
        def boom(x):
            if x == 3:
                raise RuntimeError("encode worker crashed")
            return x

        out = []
        with pytest.raises(RuntimeError, match="encode worker crashed"):
            for x in rt_pipeline.map_overlapped(range(6), boom,
                                                encode_threads=2,
                                                depth=4):
                out.append(x)
        assert out == [0, 1, 2]  # everything before the crash delivered

    def test_producer_exception_surfaces(self):
        def chunks():
            yield 1
            yield 2
            raise ValueError("bad input file")

        out = []
        with pytest.raises(ValueError, match="bad input file"):
            for x in rt_pipeline.map_overlapped(chunks(), lambda v: v,
                                                encode_threads=1,
                                                depth=4):
                out.append(x)
        assert out == [1, 2]

    def test_empty_iterable(self):
        assert list(
            rt_pipeline.map_overlapped((), lambda v: v,
                                       encode_threads=1)) == []

    def test_counts_chunks(self):
        before = rt_telemetry.snapshot().get("pipeline_chunks", 0)
        list(rt_pipeline.map_overlapped(range(5), lambda v: v,
                                        encode_threads=2))
        delta = rt_telemetry.snapshot().get("pipeline_chunks", 0) - before
        assert delta == 5

    @pytest.mark.parametrize("bad", [0, -1, 1.5, True])
    def test_rejects_bad_window(self, bad):
        with pytest.raises(ValueError):
            list(
                rt_pipeline.map_overlapped((), lambda v: v,
                                           encode_threads=1, depth=bad))


# ---------------------------------------------------------------------------
# DeviceRowAccumulator: pad_rows bit-identity in both modes
# ---------------------------------------------------------------------------


def _chunk_arrays(n, seed=0, vector=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, 50, n).astype(np.int32)
    pk = rng.integers(0, 9, n).astype(np.int32)
    shape = (n, vector) if vector else (n,)
    values = rng.uniform(0, 5, shape)
    return pid, pk, values


class TestDeviceRowAccumulator:

    @pytest.mark.parametrize("donate", [False, True])
    @pytest.mark.parametrize("sizes", [
        (700, 700, 700, 700, 700),  # uniform chunks
        (1000, 20, 3000),  # growth jumps + tiny tail
        (5,),  # single sub-bucket chunk
    ])
    def test_matches_pad_rows_exactly(self, donate, sizes):
        from pipelinedp_tpu import columnar
        chunks = [_chunk_arrays(n, seed=i) for i, n in enumerate(sizes)]
        pid_all = np.concatenate([c[0] for c in chunks])
        pk_all = np.concatenate([c[1] for c in chunks])
        values_all = np.concatenate([c[2] for c in chunks])
        encoded = columnar.EncodedData(pid=pid_all, pk=pk_all,
                                       values=values_all,
                                       partition_vocab=list(range(9)),
                                       n_privacy_ids=50)
        want = [np.asarray(a) for a in executor.pad_rows(encoded)[:3]]

        acc = rt_pipeline.DeviceRowAccumulator(donate=donate)
        for i, (pid, pk, values) in enumerate(chunks):
            n = len(pid)
            if acc.donating:
                pid, pk, values = ingest._pad_chunk_rows(
                    pid, pk, values, executor.row_bucket(n))
            acc.append(pid, pk, values, n, chunk=i)
        got = [np.asarray(a) for a in acc.finalize()]
        assert acc.n_rows == sum(sizes)
        for g, w in zip(got, want):
            assert g.shape == w.shape
            np.testing.assert_array_equal(g, w)

    @pytest.mark.parametrize("donate", [False, True])
    def test_vector_values(self, donate):
        chunks = [_chunk_arrays(n, seed=i, vector=3)
                  for i, n in enumerate((40, 500))]
        acc = rt_pipeline.DeviceRowAccumulator(donate=donate)
        for i, (pid, pk, values) in enumerate(chunks):
            n = len(pid)
            if acc.donating:
                pid, pk, values = ingest._pad_chunk_rows(
                    pid, pk, values, executor.row_bucket(n))
            acc.append(pid, pk, values, n, chunk=i)
        pid_d, pk_d, values_d = acc.finalize()
        cap = executor.row_bucket(540)
        assert values_d.shape == (cap, 3)
        np.testing.assert_array_equal(
            np.asarray(values_d)[:40], chunks[0][2])
        # Pad tail rows carry the pad_rows pad values.
        assert not np.asarray(pk_d)[540:].max() >= 0
        assert np.asarray(values_d)[540:].sum() == 0.0

    def test_empty_stream_finalizes_none(self):
        assert rt_pipeline.DeviceRowAccumulator(donate=False).finalize() \
            is None


# ---------------------------------------------------------------------------
# Pipelined stream_encode_columns == serial (vocabulary + buffers)
# ---------------------------------------------------------------------------


def _string_chunks(n=4000, chunk=700, seed=2, n_users=300, n_parts=40):
    rng = np.random.default_rng(seed)
    pid = np.char.add("u", rng.integers(0, n_users, n).astype(str))
    pk = np.char.add("m", rng.integers(0, n_parts, n).astype(str))
    values = rng.uniform(0, 5, n)

    def gen():
        for i in range(0, n, chunk):
            yield pid[i:i + chunk], pk[i:i + chunk], values[i:i + chunk]

    return gen


class TestStreamEncodePipelined:

    @pytest.mark.parametrize("depth", [1, 2, 8])
    def test_bit_identical_to_serial_pad_rows(self, depth):
        gen = _string_chunks()
        serial = ingest.stream_encode_columns(gen())
        piped = ingest.stream_encode_columns(gen(), encode_threads=2,
                                             pipeline_depth=depth)
        want = executor.pad_rows(serial)
        for w, g in zip(want, (piped.pid, piped.pk, piped.values)):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
        assert list(serial.partition_vocab) == list(piped.partition_vocab)
        assert serial.n_privacy_ids == piped.n_privacy_ids

    def test_public_partitions(self):
        gen = _string_chunks()
        public = ["m0", "m1", "m_empty"]
        serial = ingest.stream_encode_columns(gen(),
                                              public_partitions=public)
        piped = ingest.stream_encode_columns(gen(),
                                             public_partitions=public,
                                             encode_threads=2)
        want = executor.pad_rows(serial)
        for w, g in zip(want, (piped.pid, piped.pk, piped.values)):
            np.testing.assert_array_equal(np.asarray(w), np.asarray(g))
        assert piped.public_encoded
        assert list(piped.partition_vocab) == public

    def test_empty_stream(self):
        encoded = ingest.stream_encode_columns(iter(()), encode_threads=2)
        assert encoded.n_rows == 0
        assert encoded.n_partitions == 0

    def test_nonfinite_error_surfaces_from_worker(self):
        def chunks():
            yield ["a", "b"], ["x", "y"], [1.0, np.nan]

        with pytest.raises(ValueError, match="non-finite"):
            ingest.stream_encode_columns(chunks(), encode_threads=2)

    def test_nonfinite_drop_marks_rows_invalid(self):
        def chunks():
            yield ["a", "b", "c"], ["x", "y", "z"], [1.0, np.inf, 2.0]

        encoded = ingest.stream_encode_columns(chunks(), nonfinite="drop",
                                               encode_threads=2)
        valid = np.asarray(encoded.valid)
        assert valid[:3].tolist() == [True, False, True]


# ---------------------------------------------------------------------------
# Engine-level bit-identity: ChunkSource vs serial, dense + blocked routes
# ---------------------------------------------------------------------------


def _engine_spec():
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                          pdp.Metrics.SUM],
                                 max_partitions_contributed=25,
                                 max_contributions_per_partition=16,
                                 min_value=0.0,
                                 max_value=5.0)
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    return params, extractors


def _run_engine(col, params, extractors, **backend_knobs):
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-5)
    engine = pdp.DPEngine(accountant,
                          pdp.TPUBackend(noise_seed=11, **backend_knobs))
    result = engine.aggregate(col, params, extractors)
    accountant.compute_budgets()
    out = dict(result)
    return out, accountant.mechanism_count


def _assert_identical(a, b):
    assert set(a) == set(b)
    for key in a:
        assert a[key].count == b[key].count, key
        assert a[key].sum == b[key].sum, key


class TestEngineBitIdentity:

    @pytest.mark.parametrize("depth", [1, 2, 8])
    def test_dense_route(self, depth):
        gen = _string_chunks()
        params, extractors = _engine_spec()
        serial, m_serial = _run_engine(ingest.stream_encode_columns(gen()),
                                       params, extractors)
        assert serial  # kept partitions exist at huge eps
        piped, m_piped = _run_engine(pdp.ChunkSource(gen()), params,
                                     extractors, encode_threads=2,
                                     pipeline_depth=depth)
        # Same noise (seeded), same selection, same ledger size: the
        # pipelined release IS the serial release.
        assert m_serial == m_piped
        _assert_identical(serial, piped)

    def test_blocked_route(self):
        gen = _string_chunks()
        params, extractors = _engine_spec()
        serial, _ = _run_engine(ingest.stream_encode_columns(gen()),
                                params, extractors,
                                large_partition_threshold=16)
        piped, _ = _run_engine(pdp.ChunkSource(gen()), params, extractors,
                               encode_threads=2,
                               large_partition_threshold=16)
        assert serial
        _assert_identical(serial, piped)

    def test_select_partitions_route(self):
        gen = _string_chunks()
        sel_params = pdp.SelectPartitionsParams(
            max_partitions_contributed=8)
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1])

        def run(col, **knobs):
            accountant = pdp.NaiveBudgetAccountant(
                total_epsilon=HUGE_EPS, total_delta=1e-5)
            engine = pdp.DPEngine(accountant,
                                  pdp.TPUBackend(noise_seed=3, **knobs))
            result = engine.select_partitions(col, sel_params, extractors)
            accountant.compute_budgets()
            return sorted(result)

        serial = run(ingest.stream_encode_columns(gen()))
        piped = run(pdp.ChunkSource(gen()), encode_threads=2)
        assert serial and serial == piped

    def test_single_thread_pipeline_matches(self):
        # encode_threads=1 is the minimal pipeline (one worker +
        # consumer overlap) — still bit-identical.
        gen = _string_chunks()
        params, extractors = _engine_spec()
        serial, _ = _run_engine(ingest.stream_encode_columns(gen()),
                                params, extractors)
        piped, _ = _run_engine(pdp.ChunkSource(gen()), params, extractors,
                               encode_threads=1, pipeline_depth=1)
        _assert_identical(serial, piped)


# ---------------------------------------------------------------------------
# Fault injection: encode crash, OOM mid-pipeline, stalled-queue watchdog
# ---------------------------------------------------------------------------


class TestPipelineFaults:

    def test_encode_thread_crash_surfaces_and_ledger_is_clean(self):
        params, extractors = _engine_spec()
        crash_after = [2]

        def chunks():
            for i, chunk in enumerate(_string_chunks()()):
                if i == crash_after[0]:
                    raise RuntimeError("simulated parser crash")
                yield chunk

        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant,
                              pdp.TPUBackend(noise_seed=11,
                                             encode_threads=2))
        result = engine.aggregate(pdp.ChunkSource(chunks()), params,
                                  extractors)
        accountant.compute_budgets()
        before = accountant.mechanism_count
        with pytest.raises(RuntimeError, match="simulated parser crash"):
            list(result)
        # The failed execution never touched the ledger; a rerun under
        # the same seed replays the identical release.
        assert accountant.mechanism_count == before
        crash_after[0] = 10**9
        serial, _ = _run_engine(
            ingest.stream_encode_columns(_string_chunks()()), params,
            extractors)
        retry, _ = _run_engine(pdp.ChunkSource(chunks()), params,
                               extractors, encode_threads=2)
        _assert_identical(serial, retry)

    def test_oom_mid_pipeline_aborts_then_clean_rerun_is_identical(self):
        gen = _string_chunks()
        params, extractors = _engine_spec()
        schedule = rt_faults.FaultSchedule(
            [rt_faults.Fault("oom", block=2)])
        with rt_faults.inject(schedule):
            with pytest.raises(rt_faults.InjectedOOMError):
                ingest.stream_encode_columns(gen(), encode_threads=2)
        assert schedule.pending() == 0
        serial, _ = _run_engine(ingest.stream_encode_columns(gen()),
                                params, extractors)
        rerun, _ = _run_engine(pdp.ChunkSource(gen()), params, extractors,
                               encode_threads=2)
        _assert_identical(serial, rerun)

    @pytest.mark.hard_timeout(60)
    def test_watchdog_times_out_stalled_queue(self):
        stall = threading.Event()

        def stalled_chunks():
            yield from _string_chunks(n=700, chunk=700)()
            # Producer wedges: the staging queue starves and the
            # consumer's pipeline_wait guard must expire.
            stall.wait(timeout=30.0)

        wd = Watchdog(timeout_s=0.5)
        try:
            with rt_watchdog.activate(wd):
                with pytest.raises(BlockTimeoutError,
                                   match="pipeline_wait"):
                    ingest.stream_encode_columns(stalled_chunks(),
                                                 encode_threads=1)
        finally:
            stall.set()
            wd.close()
        assert rt_telemetry.snapshot().get("watchdog_timeouts", 0) >= 1

    @pytest.mark.hard_timeout(60)
    def test_backend_timeout_knob_reaches_chunk_source_ingest(self):
        stall = threading.Event()
        params, extractors = _engine_spec()

        def stalled_chunks():
            yield from _string_chunks(n=700, chunk=700)()
            stall.wait(timeout=30.0)

        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(
            accountant,
            pdp.TPUBackend(noise_seed=11, encode_threads=1,
                           timeout_s=0.5))
        result = engine.aggregate(pdp.ChunkSource(stalled_chunks()),
                                  params, extractors)
        accountant.compute_budgets()
        try:
            with pytest.raises(BlockTimeoutError):
                list(result)
        finally:
            stall.set()

    def test_journaled_blocked_route_with_retry_matches_serial(self,
                                                               tmp_path):
        gen = _string_chunks()
        params, extractors = _engine_spec()
        serial, _ = _run_engine(ingest.stream_encode_columns(gen()),
                                params, extractors,
                                large_partition_threshold=16)
        # Pipelined ingest + journaled blocked execution + one killed
        # block dispatch: the retry re-derives the same fold_in key, the
        # journal records consumed blocks, and the output is still the
        # serial release bit for bit.
        schedule = rt_faults.FaultSchedule(
            [rt_faults.Fault("dispatch", block=0)])
        with rt_faults.inject(schedule):
            faulted, _ = _run_engine(
                pdp.ChunkSource(gen()), params, extractors,
                encode_threads=2, large_partition_threshold=16,
                journal=BlockJournal(str(tmp_path)), job_id="pipe-job")
        assert schedule.pending() == 0
        _assert_identical(serial, faulted)
        counters = rt_telemetry.snapshot()
        assert counters.get("block_retries", 0) >= 1
        # Resume against the same journal: every block replays, output
        # identical again.
        resumed, _ = _run_engine(
            pdp.ChunkSource(gen()), params, extractors, encode_threads=2,
            large_partition_threshold=16,
            journal=BlockJournal(str(tmp_path)), job_id="pipe-job")
        _assert_identical(serial, resumed)
        assert rt_telemetry.snapshot().get("journal_replays", 0) >= 1


# ---------------------------------------------------------------------------
# Knob validation + staticcheck coverage
# ---------------------------------------------------------------------------


class TestKnobs:

    @pytest.mark.parametrize("bad", [0, -1, 1.5, "8", True])
    def test_backend_rejects_bad_pipeline_depth(self, bad):
        with pytest.raises(ValueError, match="pipeline_depth"):
            pdp.TPUBackend(pipeline_depth=bad)

    @pytest.mark.parametrize("bad", [-1, 1.5, "2", True])
    def test_backend_rejects_bad_encode_threads(self, bad):
        with pytest.raises(ValueError, match="encode_threads"):
            pdp.TPUBackend(encode_threads=bad)

    def test_backend_accepts_valid_knobs(self):
        backend = pdp.TPUBackend(pipeline_depth=4, encode_threads=0)
        assert backend.pipeline_depth == 4
        assert backend.encode_threads == 0

    def test_chunk_source_rejects_bad_nonfinite(self):
        with pytest.raises(ValueError, match="nonfinite"):
            pdp.ChunkSource((), nonfinite="ignore")

    def test_encode_threads_zero_still_streams_serially(self):
        gen = _string_chunks()
        params, extractors = _engine_spec()
        serial, _ = _run_engine(ingest.stream_encode_columns(gen()),
                                params, extractors)
        piped, _ = _run_engine(pdp.ChunkSource(gen()), params, extractors,
                               encode_threads=0)
        _assert_identical(serial, piped)


class TestStaticcheckCoverage:
    """The host-transfer rule covers runtime/pipeline.py: staging-stage
    device fetches must route through mesh.host_fetch."""

    def test_rule_flags_transfers_in_runtime_pipeline(self):
        mod = staticcheck.parse_source(
            "pipelinedp_tpu/runtime/pipeline.py",
            "import numpy as np\n"
            "def drain(arr):\n"
            "    return np.asarray(arr)\n")
        findings = staticcheck.analyze(
            [mod], only_rules=["host-transfer"]).active
        assert any(f.rule_id == "host-transfer" for f in findings)

    def test_other_runtime_modules_stay_uncovered(self):
        mod = staticcheck.parse_source(
            "pipelinedp_tpu/runtime/journal.py",
            "import numpy as np\n"
            "def load(arr):\n"
            "    return np.asarray(arr)\n")
        assert staticcheck.analyze(
            [mod], only_rules=["host-transfer"]).active == []

    def test_real_tree_is_clean(self):
        tree = staticcheck.load_tree(staticcheck.default_paths())
        analysis = staticcheck.analyze(tree,
                                       only_rules=["host-transfer"])
        pipeline_findings = [
            f for f in analysis.active
            if f.file == "pipelinedp_tpu/runtime/pipeline.py"
        ]
        assert pipeline_findings == []
