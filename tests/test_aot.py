"""Single-dispatch warm path coverage (PR 14).

The contracts under test:

  * **Bit-identity** — the fused release kernels (one program: bounding
    → stats → selection → noise → kept-first compaction), the
    compute/drain overlap (drainer-thread consume) and the AOT
    executable cache are OPTIMIZATIONS: every knob combination releases
    exactly the bytes the unfused / serial / traced path releases,
    across the dense, meshed (1/4/8 devices) and blocked routes, with
    equal budget-ledger mechanism counts.
  * **AOT cache keying** — a distinct spec or row bucket is a miss; an
    identical (spec, shape) is a hit; values never enter the key. A
    second identical-spec service job records 0 aot_cache_misses on
    ITS OWN health record (the cross-tenant zero-retrace proof).
  * **Journal semantics under overlap** — a journaled run consumed on
    the drainer thread writes the same record keys as the serial
    consume loop, and a resume replays them bit-identically.
  * **Async-drain symmetry** — the journaled blocked/sharded consume
    paths run under reshard.forbid_row_fetches: the batched
    copy_to_host_async drain transfers O(kept), never rows.
"""

import numpy as np
import pytest

import jax

import pipelinedp_tpu as pdp
from pipelinedp_tpu import executor
from pipelinedp_tpu.parallel import make_mesh
from pipelinedp_tpu.runtime import aot as rt_aot
from pipelinedp_tpu.runtime import faults as rt_faults
from pipelinedp_tpu.runtime import health as rt_health
from pipelinedp_tpu.runtime import journal as rt_journal
from pipelinedp_tpu.runtime import pipeline as rt_pipeline
from pipelinedp_tpu.runtime import telemetry as rt_telemetry

pytestmark = pytest.mark.aot


@pytest.fixture(autouse=True)
def _aot_epoch():
    rt_aot.enable(False)
    yield
    rt_aot.enable(False)


def _rows(n=3000, n_ids=500, n_parts=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(int(rng.integers(0, n_ids)), int(rng.integers(0, n_parts)),
             float(rng.uniform(0, 5))) for _ in range(n)]


def _exact_rows(n_ids=600, n_parts=12):
    """Integer-valued rows whose contribution bounds (l0=2, linf=3 — the
    _params() bounds) are exactly met: bounding drops nothing, integer
    sums are exact in f64, so engine outputs are a pure function of the
    row multiset — independent of mesh geometry (the multihost identity
    recipe). ONE unmeshed baseline therefore serves every mesh size,
    and equality across geometries is itself part of the assertion."""
    rows = []
    for u in range(n_ids):
        for pk in ((u * 7) % n_parts, (u * 7 + 1) % n_parts):
            for r in range(3):
                rows.append((u, pk, float((u * 3 + pk + r) % 6)))
    return rows


_BASE_CACHE = {}


def _cached(key, fn):
    if key not in _BASE_CACHE:
        _BASE_CACHE[key] = fn()
    return _BASE_CACHE[key]


def _params():
    return pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                               noise_kind=pdp.NoiseKind.LAPLACE,
                               max_partitions_contributed=2,
                               max_contributions_per_partition=3,
                               min_value=0.0,
                               max_value=5.0)


def _extractors():
    return pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                              partition_extractor=lambda r: r[1],
                              value_extractor=lambda r: r[2])


def _run_engine(rows, **backend_kwargs):
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant,
                          pdp.TPUBackend(noise_seed=13, **backend_kwargs))
    result = engine.aggregate(rows, _params(), _extractors())
    accountant.compute_budgets()
    out = sorted((k, tuple(v)) for k, v in result)
    return out, accountant.mechanism_count


def _run_select(rows, **backend_kwargs):
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    engine = pdp.DPEngine(accountant,
                          pdp.TPUBackend(noise_seed=13, **backend_kwargs))
    result = engine.select_partitions(
        rows, pdp.SelectPartitionsParams(max_partitions_contributed=2),
        pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                           partition_extractor=lambda r: r[1]))
    accountant.compute_budgets()
    return sorted(result), accountant.mechanism_count


class TestBitIdentity:
    """Fused/unfused, overlapped/serial and AOT/traced release the same
    bytes on every route."""

    def test_dense_engine(self):
        rows = _rows()
        base, n_base = _run_engine(rows, fused_release=False)
        assert base  # a vacuous comparison proves nothing
        for kwargs in (dict(fused_release=True),
                       dict(fused_release=True, aot=True),
                       dict(fused_release=False, aot=True)):
            got, n = _run_engine(rows, **kwargs)
            assert got == base, kwargs
            assert n == n_base

    @pytest.mark.parametrize("n_devices", [1, 4, 8])
    def test_meshed_engine(self, n_devices):
        # Exactly-met bounds: the UNMESHED unfused run is the bitwise
        # baseline for every geometry (computed once, shared across
        # the mesh params) — the fused meshed release must equal it at
        # 1, 4 AND 8 devices, which asserts both fused-vs-unfused and
        # cross-geometry identity in one run per mesh.
        rows = _exact_rows()
        base, n_base = _cached(
            "meshed_base", lambda: _run_engine(rows, fused_release=False))
        assert base
        mesh = make_mesh(n_devices=n_devices)
        # AOT executes the same executable jit would dispatch; the
        # 8-device point covers the AOT meshed route.
        kwargs = dict(aot=True) if n_devices == 8 else {}
        fused, n_f = _run_engine(rows, mesh=mesh, fused_release=True,
                                 **kwargs)
        assert fused == base
        assert n_base == n_f

    @pytest.mark.parametrize("mesh_devices", [None, 4])
    def test_blocked_overlap_vs_serial(self, mesh_devices):
        # Exactly-met bounds again: block noise keys are geometry-
        # independent (fold_in(final_key, b)), so the unmeshed SERIAL
        # consume run is the bitwise baseline for the meshed overlapped
        # route too — one baseline, shared across the params.
        rows = _exact_rows()
        kw = dict(large_partition_threshold=4, block_partitions=2)
        serial, n_s = _cached(
            "blocked_base",
            lambda: _run_engine(rows, overlap_drain=False, **kw))
        assert serial
        mesh = (make_mesh(n_devices=mesh_devices)
                if mesh_devices else None)
        # aot=True on the overlapped run: one run covers both the
        # drainer-thread consume and the AOT-dispatched block kernels
        # against the serial traced baseline.
        overlapped, n_o = _run_engine(rows, mesh=mesh, overlap_drain=True,
                                      aot=True, **kw)
        assert overlapped == serial
        assert n_s == n_o

    @pytest.mark.parametrize("n_devices", [None, 8])
    def test_select_routes(self, n_devices):
        # Exact bounds: L0 sampling drops no pairs, counts are integer
        # psums — selection decisions are geometry-independent, so the
        # unmeshed unfused run baselines the mesh-8 routes too.
        rows = _exact_rows()
        mesh = make_mesh(n_devices=n_devices) if n_devices else None
        base, _ = _cached(
            "select_base",
            lambda: _run_select(rows, fused_release=False))
        assert base
        fused, _ = _run_select(rows, mesh=mesh, fused_release=True,
                               aot=True)
        blocked, _ = _run_select(rows, mesh=mesh,
                                 large_partition_threshold=4,
                                 block_partitions=3,
                                 overlap_drain=True)
        # The serial-consume blocked comparison runs on the cheap
        # unmeshed param only (the drivers share _dispatch_blocks).
        if n_devices is None:
            blocked_serial, _ = _run_select(rows, mesh=mesh,
                                            large_partition_threshold=4,
                                            block_partitions=3,
                                            overlap_drain=False)
            assert blocked_serial == blocked
        assert fused == base
        assert blocked == base

    def test_chunk_source_depths(self):
        """The streamed (batched-append) route at pipeline depths 1/8
        equals the serial row run — the append batching and the fused
        release change dispatch counts, never bytes."""
        rows = _rows(n=2500)
        base, n_base = _run_engine(rows, fused_release=False,
                                   overlap_drain=False)

        def chunks():
            for i in range(0, len(rows), 300):
                chunk = rows[i:i + 300]
                yield (np.array([r[0] for r in chunk]),
                       np.array([r[1] for r in chunk]),
                       np.array([r[2] for r in chunk]))

        for depth in (1, 8):
            got, n = _run_engine(pdp.ChunkSource(chunks()), aot=True,
                                 pipeline_depth=depth, encode_threads=2)
            assert got == base, depth
            assert n == n_base


class TestExecutableCache:

    def test_key_correctness_spec_shape_and_values(self):
        """Distinct spec → miss; distinct row bucket → miss; identical
        (spec, shape) with different VALUES → hit."""
        cache = rt_aot.global_cache()
        cache.clear()
        rt_aot.enable(True)
        n, P = 256, 8
        rng = np.random.default_rng(0)

        def call(linf=3, n_rows=n, seed=1):
            params = _params()
            accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                                   total_delta=1e-6)
            from pipelinedp_tpu import combiners
            compound = combiners.create_compound_combiner(
                params, accountant)
            accountant.compute_budgets()
            cfg = executor.make_kernel_config(
                params, compound, P, private_selection=False,
                selection_params=None)
            import dataclasses
            cfg = dataclasses.replace(cfg, linf=linf)
            stds = executor.compute_noise_stds(compound, params)
            import jax.numpy as jnp
            pid = jnp.asarray(rng.integers(0, 50, n_rows), jnp.int32)
            pk = jnp.asarray(rng.integers(0, P, n_rows), jnp.int32)
            values = jnp.asarray(rng.uniform(0, 5, n_rows))
            valid = jnp.ones(n_rows, bool)
            out = executor.aggregate_release_kernel(
                pid, pk, values, valid, 0.0, 5.0, 0.0, 0.0, 2.5,
                jnp.asarray(stds), jax.random.PRNGKey(seed), cfg)
            jax.block_until_ready(out[0])

        before = rt_telemetry.snapshot()
        call(linf=3)
        call(linf=3, seed=9)  # same spec+shape, different values/key
        d1 = rt_telemetry.delta(before)
        assert d1.get("aot_cache_misses", 0) == 1
        assert d1.get("aot_cache_hits", 0) == 1

        before = rt_telemetry.snapshot()
        call(linf=2)  # distinct spec fingerprint
        call(n_rows=n * 2)  # distinct row bucket
        d2 = rt_telemetry.delta(before)
        assert d2.get("aot_cache_misses", 0) == 2
        assert d2.get("aot_cache_hits", 0) == 0

        stats = cache.stats()
        assert stats["entries"] >= 3
        assert stats["per_entry"]["aggregate_release_kernel"]["misses"] \
            >= 3

    def test_disabled_is_traced_path(self):
        before = rt_telemetry.snapshot()
        _run_engine(_rows(n=400))  # aot knob off
        delta = rt_telemetry.delta(before)
        assert delta.get("aot_cache_misses", 0) == 0
        assert delta.get("aot_cache_hits", 0) == 0

    def test_nested_trace_falls_back_to_jit(self):
        """An aot_probe'd entry called INSIDE another jit trace inlines
        through the traced path (tracers cannot feed an executable)."""
        rt_aot.enable(True)
        calls = {}

        @jax.jit
        def inner(x):
            return x + 1

        wrapped = rt_aot.aot_probe("test_inner", inner)

        @jax.jit
        def outer(x):
            return wrapped(x) * 2

        out = outer(np.arange(4.0))
        np.testing.assert_array_equal(np.asarray(out),
                                      (np.arange(4.0) + 1) * 2)
        del calls

    def test_fingerprint_distinguishes_dtype_and_shape(self):
        import jax.numpy as jnp
        a = {"x": jnp.zeros(4, jnp.int32)}
        b = {"x": jnp.zeros(4, jnp.float32)}
        c = {"x": jnp.zeros(8, jnp.int32)}
        d = {"x": jnp.ones(4, jnp.int32)}  # values don't key
        fa, fb, fc, fd = (rt_aot.fingerprint(v) for v in (a, b, c, d))
        assert fa != fb and fa != fc
        assert fa == fd

    def test_activation_is_thread_scoped(self):
        import threading
        assert not rt_aot.enabled()
        seen = {}

        def worker():
            seen["worker"] = rt_aot.enabled()

        with rt_aot.activate(True):
            assert rt_aot.enabled()
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert not rt_aot.enabled()
        assert seen["worker"] is False  # no cross-thread leak


class TestOverlapSemantics:

    def test_journal_keys_identical_overlap_vs_serial(self, tmp_path):
        rows = _rows()
        j_serial = rt_journal.BlockJournal(str(tmp_path / "serial"))
        j_overlap = rt_journal.BlockJournal(str(tmp_path / "overlap"))
        kw = dict(large_partition_threshold=4, block_partitions=2)
        a, _ = _run_engine(rows, journal=j_serial, job_id="j",
                           overlap_drain=False, **kw)
        b, _ = _run_engine(rows, journal=j_overlap, job_id="j",
                           overlap_drain=True, **kw)
        assert a == b
        assert sorted(j_serial.keys("j")) == sorted(j_overlap.keys("j"))

    def test_resume_replays_overlapped_records(self, tmp_path):
        rows = _rows()
        journal = rt_journal.BlockJournal(str(tmp_path / "j"))
        kw = dict(large_partition_threshold=4, block_partitions=2,
                  journal=journal, job_id="resume-job",
                  overlap_drain=True)
        before = rt_telemetry.snapshot()
        first, n_first = _run_engine(rows, **kw)
        assert rt_telemetry.delta(before).get("journal_replays", 0) == 0
        before = rt_telemetry.snapshot()
        second, n_second = _run_engine(rows, **kw)
        replays = rt_telemetry.delta(before).get("journal_replays", 0)
        block_keys = [k for k in journal.keys("resume-job")
                      if not k.startswith("__")]  # minus the odometer
        assert replays == len(block_keys)
        assert replays > 0
        assert second == first
        assert n_second == n_first  # no duplicate registrations

    @pytest.mark.faults
    def test_transient_consume_fault_under_overlap(self):
        rows = _rows()
        sched = rt_faults.FaultSchedule([
            rt_faults.Fault("consume", block=1),
        ])
        base, n_base = _run_engine(rows, large_partition_threshold=4,
                                   block_partitions=2)
        before = rt_telemetry.snapshot()
        with rt_faults.inject(sched):
            got, n = _run_engine(rows, large_partition_threshold=4,
                                 block_partitions=2, overlap_drain=True)
        delta = rt_telemetry.delta(before)
        assert delta.get("injected_faults", 0) == 1
        assert delta.get("block_retries", 0) >= 1
        assert got == base  # same fold_in key on the retried block
        assert n == n_base

    def test_async_drain_under_forbid_row_fetches(self, tmp_path):
        """Journaled meshed blocked run over device-resident inputs with
        the transfer guard armed: the batched async drain moves O(kept)
        journal records, never rows."""
        from pipelinedp_tpu.parallel import reshard
        rows = _rows(n=1500)
        journal = rt_journal.BlockJournal(str(tmp_path / "j"))

        def chunks():
            for i in range(0, len(rows), 500):
                chunk = rows[i:i + 500]
                yield (np.array([r[0] for r in chunk]),
                       np.array([r[1] for r in chunk]),
                       np.array([r[2] for r in chunk]))

        mesh = make_mesh(n_devices=4)
        kw = dict(mesh=mesh, large_partition_threshold=4,
                  block_partitions=2)
        base, _ = _run_engine(pdp.ChunkSource(chunks()), **kw)
        assert base
        with reshard.forbid_row_fetches():
            got, _ = _run_engine(pdp.ChunkSource(chunks()),
                                 journal=journal, job_id="guarded",
                                 aot=True, overlap_drain=True, **kw)
        assert got == base


class TestServiceReuse:

    def test_second_identical_spec_job_zero_aot_retraces(self):
        from pipelinedp_tpu.service import DPAggregationService, JobSpec
        rt_telemetry.reset()
        rows = [("u%d" % (i % 40), "P%d" % (i % 4), 1.0 + i % 3)
                for i in range(400)]
        spec = lambda seed: JobSpec(params=_params(), epsilon=1.0,
                                    delta=1e-6, noise_seed=seed,
                                    data_extractors=_extractors(),
                                    public_partitions=["P0", "P1", "P2",
                                                       "P3"])
        with DPAggregationService(pdp.TPUBackend(aot=True),
                                  max_concurrent_jobs=1) as svc:
            h1 = svc.submit("tenant-a", spec(3), rows)
            h1.result(timeout=120)
            h2 = svc.submit("tenant-b", spec(4), rows)
            h2.result(timeout=120)
            reuse = svc.compile_reuse()
        (key, stats), = reuse.items()
        assert stats["jobs"] == 2
        second = rt_health.for_job(
            h2.job_id).snapshot()["counters"].get("aot_cache_misses", 0)
        assert second == 0, (
            f"second identical-spec job retraced {second} AOT entries")
        assert stats["aot_cache_hits"] >= 1


class TestAppendBatching:

    @pytest.mark.parametrize("donate", [False, True])
    def test_batched_matches_pad_rows(self, donate):
        from pipelinedp_tpu import columnar
        rng = np.random.default_rng(3)
        sizes = (700, 20, 3000, 5)
        chunks = []
        for i, n in enumerate(sizes):
            chunks.append((rng.integers(0, 50, n).astype(np.int32),
                           rng.integers(0, 9, n).astype(np.int32),
                           rng.uniform(0, 5, n)))
        encoded = columnar.EncodedData(
            pid=np.concatenate([c[0] for c in chunks]),
            pk=np.concatenate([c[1] for c in chunks]),
            values=np.concatenate([c[2] for c in chunks]),
            partition_vocab=list(range(9)), n_privacy_ids=50)
        want = [np.asarray(a) for a in executor.pad_rows(encoded)[:3]]
        acc = rt_pipeline.DeviceRowAccumulator(donate=donate,
                                               batch_rows=1024)
        for i, (pid, pk, values) in enumerate(chunks):
            acc.append(pid, pk, values, len(pid), chunk=i)
        got = [np.asarray(a) for a in acc.finalize()]
        assert acc.n_rows == sum(sizes)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)

    def test_batching_reduces_append_dispatches(self):
        from pipelinedp_tpu.runtime import trace as rt_trace
        rng = np.random.default_rng(5)
        chunks = [(rng.integers(0, 50, 200).astype(np.int32),
                   rng.integers(0, 9, 200).astype(np.int32),
                   rng.uniform(0, 5, 200)) for _ in range(30)]

        def n_appends(batch_rows):
            rt_trace.reset()
            with rt_trace.scoped():
                acc = rt_pipeline.DeviceRowAccumulator(
                    donate=False, batch_rows=batch_rows)
                for i, (pid, pk, values) in enumerate(chunks):
                    acc.append(pid, pk, values, len(pid), chunk=i)
                acc.finalize()
                spans = rt_trace.trace_summary()["spans"]
            rt_trace.reset()
            return spans.get("pipeline_append", {}).get("count", 0)

        per_chunk = n_appends(0)
        batched = n_appends(2000)
        assert per_chunk == 30
        assert batched <= (30 * 200) // 2000 + 1
