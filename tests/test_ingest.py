"""Tests for the chunked overlapped ingest pipeline (pipelinedp_tpu.ingest)
and the Netflix-format chunked parser."""

import os
import sys

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import columnar, ingest

sys.path.insert(0,
                os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
from examples.movie_view_ratings import netflix_format  # noqa: E402

HUGE_EPS = 1e7


class TestChunkedVocabEncoder:

    def test_matches_global_factorize(self):
        rng = np.random.default_rng(0)
        raw = np.char.add("k", rng.integers(0, 500, 10_000).astype(str))
        expected_codes, expected_vocab = columnar.factorize(raw)
        enc = ingest.ChunkedVocabEncoder()
        got = np.concatenate([
            enc.encode(raw[i:i + 1234]) for i in range(0, len(raw), 1234)
        ])
        np.testing.assert_array_equal(got, expected_codes)
        assert list(enc.vocabulary) == list(expected_vocab)
        assert len(enc) == len(expected_vocab)

    def test_int_keys_and_single_chunk(self):
        raw = np.array([5, 5, 7, 5, 9, 7])
        enc = ingest.ChunkedVocabEncoder()
        codes = enc.encode(raw)
        np.testing.assert_array_equal(codes, [0, 0, 1, 0, 2, 1])
        assert list(enc.vocabulary) == [5, 7, 9]

    def test_composite_tuple_keys(self):
        # Tuple keys must stay single object elements (not explode into a
        # 2-D array) and encode consistently across chunks.
        chunk1 = [("a", 1), ("b", 2), ("a", 1)]
        chunk2 = [("b", 2), ("c", 3), ("a", 1)]
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(chunk1)
        c2 = enc.encode(chunk2)
        np.testing.assert_array_equal(c1, [0, 1, 0])
        np.testing.assert_array_equal(c2, [1, 2, 0])
        assert list(enc.vocabulary) == [("a", 1), ("b", 2), ("c", 3)]

    @pytest.mark.parametrize("dtype", ["str", "int"])
    def test_fallback_matches_global_factorize_first_occurrence(
            self, monkeypatch, dtype):
        # With pandas masked out the chunk-local factorize can yield
        # SORTED uniques (np.unique branch); the encoder must still assign
        # global codes in first-occurrence order of the concatenation.
        rng = np.random.default_rng(1)
        ints = rng.integers(0, 500, 10_000)
        raw = (np.char.add("k", ints.astype(str)).astype(object)
               if dtype == "str" else ints)
        expected_codes, expected_vocab = columnar.factorize(
            columnar._as_key_array(raw))  # pandas path: first-occurrence
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        got = np.concatenate([
            enc.encode(raw[i:i + 1234]) for i in range(0, len(raw), 1234)
        ])
        np.testing.assert_array_equal(got, expected_codes)
        assert list(enc.vocabulary) == list(expected_vocab)

    def test_fallback_unorderable_keys_spill_to_dict(self, monkeypatch):
        # A chunk mixing unorderable key types mid-stream must spill to
        # the dict path without invalidating already-assigned codes.
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(np.array(["x", "y", "x"], dtype=object))
        c2 = enc.encode(
            np.array(["y", ("tup", 1), 3, "z"], dtype=object))
        np.testing.assert_array_equal(c1, [0, 1, 0])
        np.testing.assert_array_equal(c2, [1, 2, 3, 4])
        assert list(enc.vocabulary) == ["x", "y", ("tup", 1), 3, "z"]
        # Codes keep accumulating on the dict path.
        c3 = enc.encode(np.array([3, "w"], dtype=object))
        np.testing.assert_array_equal(c3, [3, 5])


class TestNetflixChunkedParse:

    @pytest.mark.parametrize("chunk_bytes", [64, 1000, 1 << 20])
    def test_chunks_concat_equals_whole_parse(self, tmp_path, chunk_bytes):
        path = str(tmp_path / "views.txt")
        netflix_format.generate_file(path, 3000, n_users=50, n_movies=40,
                                     seed=3)
        users, movies, ratings = netflix_format.parse_file_columns(path)
        chunks = list(netflix_format.parse_file_chunks(path, chunk_bytes))
        assert len(chunks) >= (2 if chunk_bytes < 1000 else 1)
        np.testing.assert_array_equal(
            np.concatenate([c[0] for c in chunks]), users)
        np.testing.assert_array_equal(
            np.concatenate([c[1] for c in chunks]), movies)
        np.testing.assert_array_equal(
            np.concatenate([c[2] for c in chunks]), ratings)

    def test_generated_file_roundtrip(self, tmp_path):
        path = str(tmp_path / "views.txt")
        netflix_format.generate_file(path, 500, n_users=20, n_movies=10,
                                     seed=1)
        users, movies, ratings = netflix_format.parse_file_columns(path)
        assert len(users) == 500
        assert movies.min() >= 1 and movies.max() <= 10
        assert set(np.unique(ratings)) <= {1, 2, 3, 4, 5}

    def test_headerless_file_rejected(self, tmp_path):
        path = str(tmp_path / "bad.txt")
        with open(path, "w") as f:
            f.write("1,5,2023-01-01\n")
        with pytest.raises(ValueError, match="header"):
            list(netflix_format.parse_file_chunks(path))


class TestStreamEncodeEngine:

    @staticmethod
    def _chunks(pid, pk, values, size):
        for i in range(0, len(pid), size):
            yield pid[i:i + size], pk[i:i + size], values[i:i + size]

    def _data(self):
        rng = np.random.default_rng(7)
        pid = np.char.add("u", rng.integers(0, 80, 4000).astype(str))
        pk = np.char.add("m", rng.integers(0, 25, 4000).astype(str))
        values = rng.uniform(0, 5, 4000)
        return pid, pk, values

    def _aggregate(self, col, public=None, extractors=None):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=25,
                                     max_contributions_per_partition=16,
                                     min_value=0.0,
                                     max_value=5.0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                        total_delta=1e-5)
        engine = pdp.DPEngine(acc, pdp.TPUBackend(noise_seed=11))
        if extractors is None:
            extractors = pdp.DataExtractors(
                privacy_id_extractor=lambda r: r[0],
                partition_extractor=lambda r: r[1],
                value_extractor=lambda r: r[2])
        result = engine.aggregate(col, params, extractors, public)
        acc.compute_budgets()
        return dict(result)

    def test_streamed_equals_row_input(self):
        pid, pk, values = self._data()
        encoded = ingest.stream_encode_columns(
            self._chunks(pid, pk, values, 700))
        streamed = self._aggregate(encoded)
        rows = list(zip(pid, pk, values))
        direct = self._aggregate(rows)
        assert set(streamed) == set(direct)
        for key in direct:
            assert streamed[key].count == pytest.approx(direct[key].count,
                                                        abs=0.05)
            assert streamed[key].sum == pytest.approx(direct[key].sum,
                                                      abs=0.1)

    def test_streamed_public_partitions(self):
        pid, pk, values = self._data()
        public = ["m0", "m1", "m_empty"]
        encoded = ingest.stream_encode_columns(
            self._chunks(pid, pk, values, 900), public_partitions=public)
        result = self._aggregate(encoded, public=public)
        assert set(result) == set(public)
        direct = self._aggregate(list(zip(pid, pk, values)), public=public)
        for key in public:
            assert result[key].count == pytest.approx(direct[key].count,
                                                      abs=0.05)

    def test_public_partition_mismatch_raises(self):
        pid, pk, values = self._data()
        encoded = ingest.stream_encode_columns(
            self._chunks(pid, pk, values, 900), public_partitions=["m0"])
        with pytest.raises(ValueError, match="same public partitions"):
            self._aggregate(encoded, public=["m0", "m1"])

    def test_empty_chunk_iter(self):
        encoded = ingest.stream_encode_columns(iter(()))
        assert encoded.n_rows == 0
        assert encoded.n_partitions == 0

    def test_file_to_result_end_to_end(self, tmp_path):
        path = str(tmp_path / "views.txt")
        netflix_format.generate_file(path, 4000, n_users=60, n_movies=30,
                                     seed=5)
        chunk_iter = ((u, m, r.astype(np.float32)) for u, m, r in
                      netflix_format.parse_file_chunks(path, 2048))
        encoded = ingest.stream_encode_columns(chunk_iter)
        result = self._aggregate(encoded)
        users, movies, ratings = netflix_format.parse_file_columns(path)
        direct = self._aggregate(list(zip(users, movies, ratings)))
        assert set(result) == set(direct)
        for key in direct:
            assert result[key].count == pytest.approx(direct[key].count,
                                                      abs=0.05)
            assert result[key].sum == pytest.approx(direct[key].sum,
                                                    abs=0.1)


class TestPreEncodedGuards:

    def _encoded(self, public=None):
        rng = np.random.default_rng(3)
        pid = np.char.add("u", rng.integers(0, 50, 2000).astype(str))
        pk = np.char.add("m", rng.integers(0, 12, 2000).astype(str))
        values = rng.uniform(0, 5, 2000)
        return ingest.stream_encode_columns(
            iter([(pid, pk, values)]), public_partitions=public)

    def test_public_encoded_without_public_raises(self):
        encoded = self._encoded(public=["m0", "m1"])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=2)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                        total_delta=1e-5)
        engine = pdp.DPEngine(acc, pdp.TPUBackend(noise_seed=1))
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        result = engine.aggregate(encoded, params, ext)
        acc.compute_budgets()
        with pytest.raises(ValueError, match="public-partition vocabulary"):
            list(result)

    def test_select_partitions_does_not_destroy_values(self):
        encoded = self._encoded()
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                        total_delta=1e-5)
        engine = pdp.DPEngine(acc, pdp.TPUBackend(noise_seed=1))
        sel = engine.select_partitions(
            encoded, pdp.SelectPartitionsParams(max_partitions_contributed=12),
            ext)
        agg = engine.aggregate(
            encoded,
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=12,
                                max_contributions_per_partition=64,
                                min_value=0.0,
                                max_value=5.0), ext)
        acc.compute_budgets()
        assert len(list(sel)) == 12
        agg = dict(agg)
        # values column must have survived select_partitions: sums nonzero.
        assert encoded.values.shape == (2000,)
        assert sum(v.sum for v in agg.values()) > 100

    def test_device_resident_blocked_route(self):
        encoded = self._encoded()
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=12,
                                     max_contributions_per_partition=64,
                                     min_value=0.0,
                                     max_value=5.0)

        def run(backend):
            acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                            total_delta=1e-5)
            engine = pdp.DPEngine(acc, backend)
            result = engine.aggregate(encoded, params, ext)
            acc.compute_budgets()
            return dict(result)

        blocked = run(pdp.TPUBackend(noise_seed=2,
                                     large_partition_threshold=4))
        dense = run(pdp.TPUBackend(noise_seed=2,
                                   large_partition_threshold=None))
        assert set(blocked) == set(dense)
        for k in dense:
            assert blocked[k].count == pytest.approx(dense[k].count,
                                                     abs=0.1)


def test_generate_file_zero_rows(tmp_path):
    path = str(tmp_path / "empty.txt")
    netflix_format.generate_file(path, 0)
    assert open(path).read() == ""
