"""Tests for the chunked overlapped ingest pipeline (pipelinedp_tpu.ingest)
and the Netflix-format chunked parser."""

import os
import sys

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import columnar, ingest

sys.path.insert(0,
                os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
from examples.movie_view_ratings import netflix_format  # noqa: E402

HUGE_EPS = 1e7


class TestChunkedVocabEncoder:

    def test_matches_global_factorize(self):
        rng = np.random.default_rng(0)
        raw = np.char.add("k", rng.integers(0, 500, 10_000).astype(str))
        expected_codes, expected_vocab = columnar.factorize(raw)
        enc = ingest.ChunkedVocabEncoder()
        got = np.concatenate([
            enc.encode(raw[i:i + 1234]) for i in range(0, len(raw), 1234)
        ])
        np.testing.assert_array_equal(got, expected_codes)
        assert list(enc.vocabulary) == list(expected_vocab)
        assert len(enc) == len(expected_vocab)

    def test_int_keys_and_single_chunk(self):
        raw = np.array([5, 5, 7, 5, 9, 7])
        enc = ingest.ChunkedVocabEncoder()
        codes = enc.encode(raw)
        np.testing.assert_array_equal(codes, [0, 0, 1, 0, 2, 1])
        assert list(enc.vocabulary) == [5, 7, 9]

    def test_composite_tuple_keys(self):
        # Tuple keys must stay single object elements (not explode into a
        # 2-D array) and encode consistently across chunks.
        chunk1 = [("a", 1), ("b", 2), ("a", 1)]
        chunk2 = [("b", 2), ("c", 3), ("a", 1)]
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(chunk1)
        c2 = enc.encode(chunk2)
        np.testing.assert_array_equal(c1, [0, 1, 0])
        np.testing.assert_array_equal(c2, [1, 2, 0])
        assert list(enc.vocabulary) == [("a", 1), ("b", 2), ("c", 3)]

    @pytest.mark.parametrize("dtype", ["str", "int"])
    def test_fallback_matches_global_factorize_first_occurrence(
            self, monkeypatch, dtype):
        # With pandas masked out the chunk-local factorize can yield
        # SORTED uniques (np.unique branch); the encoder must still assign
        # global codes in first-occurrence order of the concatenation.
        rng = np.random.default_rng(1)
        ints = rng.integers(0, 500, 10_000)
        raw = (np.char.add("k", ints.astype(str)).astype(object)
               if dtype == "str" else ints)
        expected_codes, expected_vocab = columnar.factorize(
            columnar._as_key_array(raw))  # pandas path: first-occurrence
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        got = np.concatenate([
            enc.encode(raw[i:i + 1234]) for i in range(0, len(raw), 1234)
        ])
        np.testing.assert_array_equal(got, expected_codes)
        assert list(enc.vocabulary) == list(expected_vocab)

    def test_fallback_dtype_widening(self, monkeypatch):
        # A later chunk with a wider string dtype must widen the stored
        # vocabulary, not truncate the new keys into it (np.insert would
        # silently cast 'hello' to 'he' in a '<U2' vocab).
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(np.array(["ab", "cd"]))
        c2 = enc.encode(np.array(["hello", "ab"]))
        c3 = enc.encode(np.array(["hello", "cd"]))
        np.testing.assert_array_equal(c1, [0, 1])
        np.testing.assert_array_equal(c2, [2, 0])
        np.testing.assert_array_equal(c3, [2, 1])
        assert list(enc.vocabulary) == ["ab", "cd", "hello"]
        # Numeric widening: float keys against an int vocab must not be
        # floored into it.
        enc2 = ingest.ChunkedVocabEncoder()
        enc2.encode(np.array([1, 2]))
        c = enc2.encode(np.array([1.5, 1.0]))
        np.testing.assert_array_equal(c, [2, 0])
        assert list(enc2.vocabulary) == [1.0, 2.0, 1.5]

    def test_fallback_nan_keys_unify(self, monkeypatch):
        # All NaN keys share one code across chunks (pandas
        # use_na_sentinel=False semantics), and the NaN key never enters
        # the sorted vocab where it would corrupt binary searches.
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(np.array([1.0, np.nan, 2.0]))
        c2 = enc.encode(np.array([np.nan, 1.0, 3.0]))
        np.testing.assert_array_equal(c1, [0, 1, 2])
        np.testing.assert_array_equal(c2, [1, 0, 3])
        vocab = enc.vocabulary
        assert len(vocab) == 4
        assert vocab[0] == 1.0 and np.isnan(vocab[1]) and vocab[2] == 2.0
        assert vocab[3] == 3.0
        # Keys larger than everything must still be found after NaN
        # appeared (NaN inside the sorted array would break the search).
        c3 = enc.encode(np.array([99.0, np.nan, 3.0]))
        np.testing.assert_array_equal(c3, [4, 1, 3])
        c4 = enc.encode(np.array([99.0]))
        np.testing.assert_array_equal(c4, [4])

    def test_fallback_mixed_number_string_chunks_spill(self, monkeypatch):
        # numpy silently PROMOTES numbers to strings instead of raising;
        # the encoder must detect the kind mismatch and spill to the dict
        # path where 1.5 and '1.5' stay distinct keys (pandas semantics).
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(np.array(["ab", "cd"]))
        c2 = enc.encode(np.array([1.5, 2.5]))
        c3 = enc.encode(np.array([1.5, "1.5", "ab"], dtype=object))
        np.testing.assert_array_equal(c1, [0, 1])
        np.testing.assert_array_equal(c2, [2, 3])
        np.testing.assert_array_equal(c3, [2, 4, 0])
        assert list(enc.vocabulary) == ["ab", "cd", 1.5, 2.5, "1.5"]

    def test_fallback_nan_survives_dict_spill(self, monkeypatch):
        # The NaN code must keep matching after a spill to the dict path
        # (every float('nan') object is distinct under ==).
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(np.array([1.0, np.nan]))
        c2 = enc.encode(np.array(["x", 2, np.nan], dtype=object))  # spills
        c3 = enc.encode(np.array([np.nan, 1.0]))
        np.testing.assert_array_equal(c1, [0, 1])
        np.testing.assert_array_equal(c2, [2, 3, 1])
        np.testing.assert_array_equal(c3, [1, 0])
        vocab = enc.vocabulary
        assert vocab[0] == 1.0 and np.isnan(vocab[1])

    def test_fallback_nan_with_string_vocab(self, monkeypatch):
        # A float NaN key alongside string keys: the vocabulary must hold
        # a REAL NaN (object dtype), not the string 'nan'.
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        enc.encode(np.array(["a", "b"], dtype=object))
        enc.encode(np.array([np.nan], dtype=object))
        vocab = enc.vocabulary
        assert list(vocab[:2]) == ["a", "b"]
        assert isinstance(vocab[2], float) and np.isnan(vocab[2])
        # An int vocab with NaN promotes to float64, not to a string.
        enc2 = ingest.ChunkedVocabEncoder()
        enc2.encode(np.array([7, 9]))
        enc2.encode(np.array([np.nan]))
        vocab2 = enc2.vocabulary
        assert vocab2.dtype.kind in "fO"
        assert vocab2[0] == 7 and np.isnan(vocab2[2])

    def test_fallback_unorderable_keys_spill_to_dict(self, monkeypatch):
        # A chunk mixing unorderable key types mid-stream must spill to
        # the dict path without invalidating already-assigned codes.
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        enc = ingest.ChunkedVocabEncoder()
        c1 = enc.encode(np.array(["x", "y", "x"], dtype=object))
        c2 = enc.encode(
            np.array(["y", ("tup", 1), 3, "z"], dtype=object))
        np.testing.assert_array_equal(c1, [0, 1, 0])
        np.testing.assert_array_equal(c2, [1, 2, 3, 4])
        assert list(enc.vocabulary) == ["x", "y", ("tup", 1), 3, "z"]
        # Codes keep accumulating on the dict path.
        c3 = enc.encode(np.array([3, "w"], dtype=object))
        np.testing.assert_array_equal(c3, [3, 5])


class TestNetflixChunkedParse:

    @pytest.mark.parametrize("chunk_bytes", [64, 1000, 1 << 20])
    def test_chunks_concat_equals_whole_parse(self, tmp_path, chunk_bytes):
        path = str(tmp_path / "views.txt")
        netflix_format.generate_file(path, 3000, n_users=50, n_movies=40,
                                     seed=3)
        users, movies, ratings = netflix_format.parse_file_columns(path)
        chunks = list(netflix_format.parse_file_chunks(path, chunk_bytes))
        assert len(chunks) >= (2 if chunk_bytes < 1000 else 1)
        np.testing.assert_array_equal(
            np.concatenate([c[0] for c in chunks]), users)
        np.testing.assert_array_equal(
            np.concatenate([c[1] for c in chunks]), movies)
        np.testing.assert_array_equal(
            np.concatenate([c[2] for c in chunks]), ratings)

    @pytest.mark.parametrize("n_shards", [1, 2, 3, 7])
    def test_byte_range_shards_cover_file_exactly(self, tmp_path, n_shards):
        # Host-shard semantics: a shard owns every movie section whose
        # header starts in its byte range; concatenating a contiguous
        # cover equals the whole-file parse, each line exactly once.
        path = str(tmp_path / "views.txt")
        netflix_format.generate_file(path, 5000, n_users=80, n_movies=60,
                                     seed=9)
        users, movies, ratings = netflix_format.parse_file_columns(path)
        size = os.path.getsize(path)
        per = -(-size // n_shards)
        got_u, got_m, got_r = [], [], []
        for h in range(n_shards):
            for u, m, r in netflix_format.parse_file_chunks(
                    path, chunk_bytes=997,
                    byte_range=(h * per, min((h + 1) * per, size))):
                got_u.append(u)
                got_m.append(m)
                got_r.append(r)
        np.testing.assert_array_equal(np.concatenate(got_u), users)
        np.testing.assert_array_equal(np.concatenate(got_m), movies)
        np.testing.assert_array_equal(np.concatenate(got_r), ratings)

    def test_byte_range_shards_crlf_file(self, tmp_path):
        # CRLF files: the binary byte accounting of the sharded reader
        # must line up with the binary header-probe offsets (text-mode
        # newline translation would undercount by one byte per line).
        path = str(tmp_path / "views_crlf.txt")
        lf = str(tmp_path / "views_lf.txt")
        netflix_format.generate_file(lf, 3000, n_users=50, n_movies=40,
                                     seed=3)
        with open(lf, "rb") as f:
            data = f.read()
        with open(path, "wb") as f:
            f.write(data.replace(b"\n", b"\r\n"))
        users, movies, ratings = netflix_format.parse_file_columns(lf)
        size = os.path.getsize(path)
        per = -(-size // 3)
        got_u, got_m = [], []
        for h in range(3):
            for u, m, _ in netflix_format.parse_file_chunks(
                    path, chunk_bytes=997,
                    byte_range=(h * per, min((h + 1) * per, size))):
                got_u.append(u)
                got_m.append(m)
        np.testing.assert_array_equal(np.concatenate(got_u), users)
        np.testing.assert_array_equal(np.concatenate(got_m), movies)

    def test_byte_range_shard_without_headers_is_empty(self, tmp_path):
        # A byte range holding only rating lines of an earlier section
        # yields nothing (and must not raise the no-header error).
        path = str(tmp_path / "views.txt")
        with open(path, "w") as f:
            f.write("7:\n" + "".join(f"{u},3,2020-01-01\n"
                                     for u in range(200)))
        mid = os.path.getsize(path) // 2
        out = list(
            netflix_format.parse_file_chunks(path, byte_range=(mid,
                                                               mid + 10)))
        assert out == []
        # And the owning shard (containing the header) reads to EOF.
        total = sum(
            len(u) for u, _, _ in netflix_format.parse_file_chunks(
                path, byte_range=(0, mid)))
        assert total == 200

    def test_generated_file_roundtrip(self, tmp_path):
        path = str(tmp_path / "views.txt")
        netflix_format.generate_file(path, 500, n_users=20, n_movies=10,
                                     seed=1)
        users, movies, ratings = netflix_format.parse_file_columns(path)
        assert len(users) == 500
        assert movies.min() >= 1 and movies.max() <= 10
        assert set(np.unique(ratings)) <= {1, 2, 3, 4, 5}

    def test_headerless_file_rejected(self, tmp_path):
        path = str(tmp_path / "bad.txt")
        with open(path, "w") as f:
            f.write("1,5,2023-01-01\n")
        with pytest.raises(ValueError, match="header"):
            list(netflix_format.parse_file_chunks(path))


class TestStreamEncodeEngine:

    @staticmethod
    def _chunks(pid, pk, values, size):
        for i in range(0, len(pid), size):
            yield pid[i:i + size], pk[i:i + size], values[i:i + size]

    def _data(self):
        rng = np.random.default_rng(7)
        pid = np.char.add("u", rng.integers(0, 80, 4000).astype(str))
        pk = np.char.add("m", rng.integers(0, 25, 4000).astype(str))
        values = rng.uniform(0, 5, 4000)
        return pid, pk, values

    def _aggregate(self, col, public=None, extractors=None):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=25,
                                     max_contributions_per_partition=16,
                                     min_value=0.0,
                                     max_value=5.0)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                        total_delta=1e-5)
        engine = pdp.DPEngine(acc, pdp.TPUBackend(noise_seed=11))
        if extractors is None:
            extractors = pdp.DataExtractors(
                privacy_id_extractor=lambda r: r[0],
                partition_extractor=lambda r: r[1],
                value_extractor=lambda r: r[2])
        result = engine.aggregate(col, params, extractors, public)
        acc.compute_budgets()
        return dict(result)

    def test_streamed_equals_row_input(self):
        pid, pk, values = self._data()
        encoded = ingest.stream_encode_columns(
            self._chunks(pid, pk, values, 700))
        streamed = self._aggregate(encoded)
        rows = list(zip(pid, pk, values))
        direct = self._aggregate(rows)
        assert set(streamed) == set(direct)
        for key in direct:
            assert streamed[key].count == pytest.approx(direct[key].count,
                                                        abs=0.05)
            assert streamed[key].sum == pytest.approx(direct[key].sum,
                                                      abs=0.1)

    def test_streamed_public_partitions(self):
        pid, pk, values = self._data()
        public = ["m0", "m1", "m_empty"]
        encoded = ingest.stream_encode_columns(
            self._chunks(pid, pk, values, 900), public_partitions=public)
        result = self._aggregate(encoded, public=public)
        assert set(result) == set(public)
        direct = self._aggregate(list(zip(pid, pk, values)), public=public)
        for key in public:
            assert result[key].count == pytest.approx(direct[key].count,
                                                      abs=0.05)

    def test_public_partition_mismatch_raises(self):
        pid, pk, values = self._data()
        encoded = ingest.stream_encode_columns(
            self._chunks(pid, pk, values, 900), public_partitions=["m0"])
        with pytest.raises(ValueError, match="same public partitions"):
            self._aggregate(encoded, public=["m0", "m1"])

    def test_empty_chunk_iter(self):
        encoded = ingest.stream_encode_columns(iter(()))
        assert encoded.n_rows == 0
        assert encoded.n_partitions == 0

    def test_file_to_result_end_to_end(self, tmp_path):
        path = str(tmp_path / "views.txt")
        netflix_format.generate_file(path, 4000, n_users=60, n_movies=30,
                                     seed=5)
        chunk_iter = ((u, m, r.astype(np.float32)) for u, m, r in
                      netflix_format.parse_file_chunks(path, 2048))
        encoded = ingest.stream_encode_columns(chunk_iter)
        result = self._aggregate(encoded)
        users, movies, ratings = netflix_format.parse_file_columns(path)
        direct = self._aggregate(list(zip(users, movies, ratings)))
        assert set(result) == set(direct)
        for key in direct:
            assert result[key].count == pytest.approx(direct[key].count,
                                                      abs=0.05)
            assert result[key].sum == pytest.approx(direct[key].sum,
                                                    abs=0.1)


class TestPreEncodedGuards:

    def _encoded(self, public=None):
        rng = np.random.default_rng(3)
        pid = np.char.add("u", rng.integers(0, 50, 2000).astype(str))
        pk = np.char.add("m", rng.integers(0, 12, 2000).astype(str))
        values = rng.uniform(0, 5, 2000)
        return ingest.stream_encode_columns(
            iter([(pid, pk, values)]), public_partitions=public)

    def test_public_encoded_without_public_raises(self):
        encoded = self._encoded(public=["m0", "m1"])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=2)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                        total_delta=1e-5)
        engine = pdp.DPEngine(acc, pdp.TPUBackend(noise_seed=1))
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        result = engine.aggregate(encoded, params, ext)
        acc.compute_budgets()
        with pytest.raises(ValueError, match="public-partition vocabulary"):
            list(result)

    def test_select_partitions_does_not_destroy_values(self):
        encoded = self._encoded()
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                        total_delta=1e-5)
        engine = pdp.DPEngine(acc, pdp.TPUBackend(noise_seed=1))
        sel = engine.select_partitions(
            encoded, pdp.SelectPartitionsParams(max_partitions_contributed=12),
            ext)
        agg = engine.aggregate(
            encoded,
            pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                max_partitions_contributed=12,
                                max_contributions_per_partition=64,
                                min_value=0.0,
                                max_value=5.0), ext)
        acc.compute_budgets()
        assert len(list(sel)) == 12
        agg = dict(agg)
        # values column must have survived select_partitions: sums nonzero.
        assert encoded.values.shape == (2000,)
        assert sum(v.sum for v in agg.values()) > 100

    def test_device_resident_blocked_route(self):
        encoded = self._encoded()
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=12,
                                     max_contributions_per_partition=64,
                                     min_value=0.0,
                                     max_value=5.0)

        def run(backend):
            acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                            total_delta=1e-5)
            engine = pdp.DPEngine(acc, backend)
            result = engine.aggregate(encoded, params, ext)
            acc.compute_budgets()
            return dict(result)

        blocked = run(pdp.TPUBackend(noise_seed=2,
                                     large_partition_threshold=4))
        dense = run(pdp.TPUBackend(noise_seed=2,
                                   large_partition_threshold=None))
        assert set(blocked) == set(dense)
        for k in dense:
            assert blocked[k].count == pytest.approx(dense[k].count,
                                                     abs=0.1)


def test_generate_file_zero_rows(tmp_path):
    path = str(tmp_path / "empty.txt")
    netflix_format.generate_file(path, 0)
    assert open(path).read() == ""


class TestMultiHostIngest:
    """Host-sharded ingest: encode_shard + vocabulary merge + remap."""

    @staticmethod
    def _raw(n=6000, seed=7):
        rng = np.random.default_rng(seed)
        pids = np.char.add("u", rng.integers(0, 500, n).astype(str))
        pks = np.char.add("pk", rng.integers(0, 60, n).astype(str))
        vals = rng.uniform(0, 5, n)
        return pids, pks, vals

    def _shard_chunks(self, pids, pks, vals, h, n_hosts, chunk=517):
        n = len(pids)
        per = -(-n // n_hosts)
        lo, hi = h * per, min((h + 1) * per, n)
        return [(pids[i:min(i + chunk, hi)], pks[i:min(i + chunk, hi)],
                 vals[i:min(i + chunk, hi)]) for i in range(lo, hi, chunk)]

    def test_merge_matches_single_process_factorize(self):
        pids, pks, vals = self._raw()
        n_hosts = 3
        shards = [
            ingest.encode_shard(self._shard_chunks(pids, pks, vals, h,
                                                   n_hosts))
            for h in range(n_hosts)
        ]
        merged = ingest.merge_shards(shards)
        expected = columnar.encode_columns(pids, pks, vals)
        np.testing.assert_array_equal(np.asarray(merged.pid), expected.pid)
        np.testing.assert_array_equal(np.asarray(merged.pk), expected.pk)
        assert list(merged.partition_vocab) == list(
            expected.partition_vocab)
        assert merged.n_privacy_ids == expected.n_privacy_ids
        np.testing.assert_allclose(np.asarray(merged.values),
                                   vals.astype(np.float32), rtol=1e-6)

    def test_merge_public_partitions(self):
        pids, pks, vals = self._raw(2000)
        public = [f"pk{i}" for i in range(40)]
        shards = [
            ingest.encode_shard(self._shard_chunks(pids, pks, vals, h, 2),
                                public_partitions=public)
            for h in range(2)
        ]
        merged = ingest.merge_shards(shards, public_partitions=public)
        expected = columnar.encode_columns(pids, pks, vals,
                                           public_partitions=public)
        np.testing.assert_array_equal(np.asarray(merged.pk), expected.pk)
        assert merged.public_encoded

    def test_merge_public_mismatch_raises(self):
        pids, pks, vals = self._raw(200)
        shard = ingest.encode_shard(self._shard_chunks(pids, pks, vals, 0, 1),
                                    public_partitions=["pk1"])
        with pytest.raises(ValueError, match="public"):
            ingest.merge_shards([shard])
        # Reverse direction: privately-encoded shard + public merge must
        # also raise (the pk codes index the wrong vocabulary).
        shard_priv = ingest.encode_shard(
            self._shard_chunks(pids, pks, vals, 0, 1))
        with pytest.raises(ValueError, match="without public"):
            ingest.merge_shards([shard_priv], public_partitions=["pk1"])

    def test_merge_fallback_no_pandas(self, monkeypatch):
        monkeypatch.setattr(ingest, "_pd", None)
        monkeypatch.setattr(columnar, "_pd", None)
        pids, pks, vals = self._raw(3000)
        pids = pids.astype(object)
        pks = pks.astype(object)
        shards = [
            ingest.encode_shard(self._shard_chunks(pids, pks, vals, h, 3))
            for h in range(3)
        ]
        merged = ingest.merge_shards(shards)
        monkeypatch.undo()
        expected = columnar.encode_columns(pids, pks, vals)
        np.testing.assert_array_equal(np.asarray(merged.pid), expected.pid)
        np.testing.assert_array_equal(np.asarray(merged.pk), expected.pk)

    def test_n_process_dryrun_and_engine(self, tmp_path):
        # REAL process isolation: each "host" encodes its shard in a
        # separate python process (no shared encoder state), the parent
        # merges and runs the engine — codes must equal the single-process
        # factorize and the DP result must match the row-input path.
        import pickle
        import subprocess

        pids, pks, vals = self._raw(4000)
        n_hosts = 3
        worker = tmp_path / "worker.py"
        worker.write_text(
            "import os, pickle, sys\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "sys.path.insert(0, %r)\n"
            "import numpy as np\n"
            "from pipelinedp_tpu import ingest\n"
            "with open(sys.argv[1], 'rb') as f:\n"
            "    chunks = pickle.load(f)\n"
            "shard = ingest.encode_shard(chunks)\n"
            "with open(sys.argv[2], 'wb') as f:\n"
            "    pickle.dump(shard, f)\n" %
            os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
        shards = []
        for h in range(n_hosts):
            inp, out = tmp_path / f"in{h}.pkl", tmp_path / f"out{h}.pkl"
            with open(inp, "wb") as f:
                pickle.dump(self._shard_chunks(pids, pks, vals, h, n_hosts),
                            f)
            subprocess.run([sys.executable, str(worker), str(inp), str(out)],
                           check=True, timeout=300)
            with open(out, "rb") as f:
                shards.append(pickle.load(f))
        merged = ingest.merge_shards(shards)
        expected = columnar.encode_columns(pids, pks, vals)
        np.testing.assert_array_equal(np.asarray(merged.pid), expected.pid)
        np.testing.assert_array_equal(np.asarray(merged.pk), expected.pk)

        rows = list(zip(pids, pks, vals))
        ex = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                partition_extractor=lambda r: r[1],
                                value_extractor=lambda r: float(r[2]))
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=60,
            max_contributions_per_partition=30,
            min_value=0.0,
            max_value=5.0)

        def agg(data):
            acc = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                            total_delta=1e-6)
            engine = pdp.DPEngine(acc, pdp.TPUBackend(noise_seed=5))
            result = engine.aggregate(data, params, ex)
            acc.compute_budgets()
            return {k: round(v.count, 2) for k, v in dict(result).items()}

        assert agg(merged) == agg(rows)


class TestChunkedEncoderProperty:
    """Hypothesis: the no-pandas fallback encoder matches a global pandas
    factorize for ANY chunking over mixed key types (strings, ints,
    floats, NaN, tuples) — the contract every round-5 edge fix defends."""

    KEY_POOL = [
        "a", "bb", "ccc", "hello", "zz9", 1, 2, 37, 1.5, 2.5, 2.0,
        float("nan"), ("t", 1), ("t", 2)
    ]

    def test_random_chunkings_match_global_factorize(self):
        pytest.importorskip(
            "hypothesis",
            reason="property test needs hypothesis (absent in some images)")
        from hypothesis import given, settings, strategies as st

        @settings(max_examples=60, deadline=None)
        @given(
            st.lists(st.sampled_from(self.KEY_POOL), min_size=1,
                     max_size=60), st.integers(1, 7))
        def run(keys, chunk):
            arr = columnar._as_key_array(keys)
            expected, expected_vocab = columnar.factorize(arr)  # pandas
            saved = ingest._pd, columnar._pd
            ingest._pd = columnar._pd = None
            try:
                enc = ingest.ChunkedVocabEncoder()
                got = np.concatenate([
                    enc.encode(keys[i:i + chunk])
                    for i in range(0, len(keys), chunk)
                ])
                vocab = enc.vocabulary
            finally:
                ingest._pd, columnar._pd = saved
            np.testing.assert_array_equal(got, expected)
            assert len(vocab) == len(expected_vocab)
            for a, b in zip(vocab, expected_vocab):
                if isinstance(a, float) and np.isnan(a):
                    assert isinstance(b, float) and np.isnan(b)
                else:
                    assert a == b, (a, b)

        run()


class TestNonFiniteValueValidation:
    """NaN/Inf in the VALUE column survives jnp.clip and silently poisons
    sums; the ingest/columnar boundary must reject (default) or
    drop-with-warning."""

    def _cols(self):
        pids = np.array(["u1", "u2", "u3", "u4"])
        pks = np.array(["a", "a", "b", "b"])
        vals = np.array([1.0, np.nan, np.inf, 2.0])
        return pids, pks, vals

    def test_encode_columns_rejects_by_default(self):
        pids, pks, vals = self._cols()
        with pytest.raises(ValueError, match="non-finite"):
            columnar.encode_columns(pids, pks, vals)

    def test_encode_columns_drop_policy_invalidates_rows(self, caplog):
        pids, pks, vals = self._cols()
        with caplog.at_level("WARNING"):
            encoded = columnar.encode_columns(pids, pks, vals,
                                              nonfinite="drop")
        assert "dropping 2" in caplog.text
        np.testing.assert_array_equal(encoded.valid,
                                      [True, False, False, True])
        assert np.isfinite(encoded.values).all()

    def test_vector_values_any_bad_coordinate_drops_row(self):
        pids = np.array(["u1", "u2"])
        pks = np.array(["a", "b"])
        vals = np.array([[1.0, np.nan], [2.0, 3.0]])
        with pytest.raises(ValueError, match="non-finite"):
            columnar.encode_columns(pids, pks, vals)
        encoded = columnar.encode_columns(pids, pks, vals, nonfinite="drop")
        np.testing.assert_array_equal(encoded.valid, [False, True])

    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError, match="error|drop"):
            columnar.nonfinite_value_rows(np.array([1.0]), policy="ignore")

    def test_integer_values_never_checked(self):
        assert columnar.nonfinite_value_rows(np.array([1, 2, 3])) is None

    def test_stream_encode_rejects_and_drops(self):
        chunks = lambda: iter([(np.array(["u1", "u2"]), np.array(["a", "b"]),
                                np.array([1.0, np.inf]))])
        with pytest.raises(ValueError, match="non-finite"):
            ingest.stream_encode_columns(chunks())
        encoded = ingest.stream_encode_columns(chunks(), nonfinite="drop")
        np.testing.assert_array_equal(np.asarray(encoded.valid),
                                      [True, False])
        assert np.isfinite(np.asarray(encoded.values)).all()

    def test_encode_shard_rejects_and_drops(self):
        chunks = lambda: iter([(np.array(["u1", "u2"]), np.array(["a", "b"]),
                                np.array([np.nan, 5.0]))])
        with pytest.raises(ValueError, match="non-finite"):
            ingest.encode_shard(chunks())
        shard = ingest.encode_shard(chunks(), nonfinite="drop")
        np.testing.assert_array_equal(shard.pk, [-1, 1])
        assert np.isfinite(shard.values).all()

    def test_dropped_rows_do_not_poison_engine_results(self):
        # End to end: a poisoned row dropped at ingest leaves the other
        # partitions' noise-free sums intact.
        pids = np.array(["u%d" % (i % 30) for i in range(300)])
        pks = np.array(["p%d" % (i % 3) for i in range(300)])
        vals = np.ones(300)
        vals[7] = np.nan
        encoded = columnar.encode_columns(pids, pks, vals, nonfinite="drop")
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=3,
            max_contributions_per_partition=10,
            min_value=0.0,
            max_value=5.0)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, pdp.TPUBackend(noise_seed=3))
        result = engine.aggregate(encoded, params, extractors)
        accountant.compute_budgets()
        out = dict(result)
        assert len(out) == 3
        for pk, metrics in out.items():
            assert np.isfinite(metrics.sum)
            expected = 100.0 - (1.0 if pk == "p1" else 0.0)
            assert metrics.sum == pytest.approx(expected, abs=0.1)
