"""Tests for native DP numerics.

Statistical-distribution tests follow the reference pattern
(/root/reference/tests/dp_computations_test.py:99-177): large-sample noise
draws checked for mean/std within multi-sigma confidence deltas.
"""

import math

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dp_computations as dp
from pipelinedp_tpu.budget_accounting import MechanismSpec
from pipelinedp_tpu.aggregate_params import MechanismType

N_SAMPLES = 200_000


@pytest.fixture(autouse=True)
def _seed():
    dp.seed_mechanism_rng(12345)
    yield
    dp.seed_mechanism_rng(None)


class TestSensitivityCalculus:

    def test_l1_l2(self):
        assert dp.compute_l1_sensitivity(4, 2.5) == 10
        assert dp.compute_l2_sensitivity(4, 2.5) == 5

    def test_middle_and_squares(self):
        assert dp.compute_middle(-1, 3) == 1
        assert dp.compute_squares_interval(-2, 1) == (0, 4)
        assert dp.compute_squares_interval(1, 2) == (1, 4)

    def test_sensitivities_consistency(self):
        s = dp.Sensitivities(l0=4, linf=2)
        assert s.l1 == 8
        assert s.l2 == 4
        with pytest.raises(ValueError, match="L1"):
            dp.Sensitivities(l0=4, linf=2, l1=5)
        with pytest.raises(ValueError, match="positive"):
            dp.Sensitivities(l0=-1, linf=2)
        with pytest.raises(ValueError, match="both"):
            dp.Sensitivities(l0=4)

    def test_per_metric_sensitivities(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=2,
                                     min_value=-1,
                                     max_value=4)
        assert dp.compute_sensitivities_for_count(params).l1 == 6
        assert dp.compute_sensitivities_for_privacy_id_count(params).l1 == 3
        assert dp.compute_sensitivities_for_sum(params).linf == 8
        # normalized sum: (4 - -1)/2 * 2 = 5
        assert dp.compute_sensitivities_for_normalized_sum(params).linf == 5


class TestAnalyticGaussian:

    @pytest.mark.parametrize("eps,delta,sens", [(1.0, 1e-6, 1.0),
                                                (0.1, 1e-10, 3.0),
                                                (5.0, 1e-5, 0.5),
                                                (10.0, 1e-12, 1.0)])
    def test_calibration_is_tight(self, eps, delta, sens):
        sigma = dp.gaussian_sigma(eps, delta, sens)
        assert dp.gaussian_delta(sigma, eps, sens) <= delta * (1 + 1e-6)
        # Slightly smaller sigma must violate delta (tightness).
        assert dp.gaussian_delta(sigma * 0.999, eps, sens) > delta

    def test_beats_classic_bound(self):
        # The analytic mechanism is never worse than the classic
        # sqrt(2 ln(1.25/delta))/eps calibration (for eps<=1).
        eps, delta = 0.5, 1e-6
        classic = math.sqrt(2 * math.log(1.25 / delta)) / eps
        assert dp.gaussian_sigma(eps, delta, 1.0) <= classic

    def test_invalid_delta(self):
        with pytest.raises(ValueError):
            dp.gaussian_sigma(1.0, 0, 1.0)


class TestNoiseDistributions:

    def test_laplace_mechanism_distribution(self):
        mech = dp.LaplaceMechanism.create_from_epsilon(2.0, 4.0)  # b = 2
        samples = np.array([mech.add_noise(10.0) for _ in range(N_SAMPLES)])
        b = 2.0
        assert samples.mean() == pytest.approx(10.0,
                                               abs=5 * b * math.sqrt(2) /
                                               math.sqrt(N_SAMPLES))
        assert samples.std() == pytest.approx(b * math.sqrt(2), rel=0.02)
        assert mech.std == pytest.approx(b * math.sqrt(2))

    def test_gaussian_mechanism_distribution(self):
        mech = dp.GaussianMechanism.create_from_epsilon_delta(1.0, 1e-6, 1.0)
        sigma = mech.std
        samples = np.array([mech.add_noise(0.0) for _ in range(N_SAMPLES)])
        assert samples.mean() == pytest.approx(0.0,
                                               abs=5 * sigma /
                                               math.sqrt(N_SAMPLES))
        assert samples.std() == pytest.approx(sigma, rel=0.02)
        # ~68%/95% mass within 1/2 sigma.
        within1 = np.mean(np.abs(samples) < sigma)
        assert within1 == pytest.approx(0.6827, abs=0.01)

    def test_create_from_std_deviation(self):
        lap = dp.LaplaceMechanism.create_from_std_deviation(2.0, 3.0)
        assert lap.std == pytest.approx(2.0 * 3.0)
        gauss = dp.GaussianMechanism.create_from_std_deviation(2.0, 3.0)
        assert gauss.std == pytest.approx(6.0)


class TestBudgetSplit:

    def test_equally_split_budget(self):
        budgets = dp.equally_split_budget(1.0, 1e-6, 3)
        assert len(budgets) == 3
        assert sum(b[0] for b in budgets) == pytest.approx(1.0)
        assert sum(b[1] for b in budgets) == pytest.approx(1e-6)
        with pytest.raises(ValueError):
            dp.equally_split_budget(1.0, 0, 0)


class TestMeanAndVariance:

    def _huge_eps_params(self, **kwargs):
        defaults = dict(eps=1e6,
                        delta=1e-8,
                        min_value=0.0,
                        max_value=10.0,
                        min_sum_per_partition=None,
                        max_sum_per_partition=None,
                        max_partitions_contributed=1,
                        max_contributions_per_partition=3,
                        noise_kind=pdp.NoiseKind.LAPLACE)
        defaults.update(kwargs)
        return dp.ScalarNoiseParams(**defaults)

    def test_mean_mechanism_huge_eps(self):
        spec_count = MechanismSpec(MechanismType.LAPLACE)
        spec_count.set_eps_delta(1e6, 0)
        spec_sum = MechanismSpec(MechanismType.LAPLACE)
        spec_sum.set_eps_delta(1e6, 0)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=3,
                                     min_value=0.0,
                                     max_value=10.0)
        mech = dp.create_mean_mechanism(
            5.0, spec_count, dp.compute_sensitivities_for_count(params),
            spec_sum, dp.compute_sensitivities_for_normalized_sum(params))
        # values [4, 6, 8]: count=3, normalized_sum = (4-5)+(6-5)+(8-5)=3
        dp_count, dp_sum, dp_mean = mech.compute_mean(3, 3.0)
        assert dp_count == pytest.approx(3, abs=1e-2)
        assert dp_mean == pytest.approx(6.0, abs=1e-2)
        assert dp_sum == pytest.approx(18.0, abs=0.1)

    def test_compute_dp_var_huge_eps(self):
        params = self._huge_eps_params()
        values = np.array([2.0, 4.0, 6.0])
        middle = 5.0
        normalized = values - middle
        count, nsum, nsum2 = 3, normalized.sum(), (normalized**2).sum()
        dp_count, dp_sum, dp_mean, dp_var = dp.compute_dp_var(
            count, nsum, nsum2, params)
        assert dp_count == pytest.approx(3, abs=1e-2)
        assert dp_mean == pytest.approx(4.0, abs=1e-2)
        assert dp_var == pytest.approx(values.var(), abs=0.1)

    def test_noise_std_predictors(self):
        params = self._huge_eps_params(eps=1.0,
                                       min_sum_per_partition=0.0,
                                       max_sum_per_partition=2.0,
                                       min_value=None,
                                       max_value=None)
        count_std = dp.compute_dp_count_noise_std(params)
        assert count_std == pytest.approx(3 / 1.0 * math.sqrt(2))
        sum_std = dp.compute_dp_sum_noise_std(params)
        assert sum_std == pytest.approx(2 / 1.0 * math.sqrt(2))


class TestVectorNoise:

    def test_clip_linf(self):
        vec = np.array([-5.0, 0.5, 3.0])
        clipped = dp._clip_vector(vec, 1.0, pdp.NormKind.Linf)
        np.testing.assert_allclose(clipped, [-1.0, 0.5, 1.0])

    def test_clip_l2(self):
        vec = np.array([3.0, 4.0])
        clipped = dp._clip_vector(vec, 1.0, pdp.NormKind.L2)
        np.testing.assert_allclose(clipped, [0.6, 0.8])

    def test_add_noise_vector_huge_eps(self):
        params = dp.AdditiveVectorNoiseParams(
            eps_per_coordinate=1e6,
            delta_per_coordinate=0,
            max_norm=10.0,
            l0_sensitivity=1,
            linf_sensitivity=1,
            norm_kind=pdp.NormKind.Linf,
            noise_kind=pdp.NoiseKind.LAPLACE)
        noised = dp.add_noise_vector(np.array([1.0, 2.0]), params)
        np.testing.assert_allclose(noised, [1.0, 2.0], atol=1e-2)


class TestExponentialMechanism:

    class _Scoring(dp.ExponentialMechanism.ScoringFunction):

        def score(self, k):
            return float(k)

        @property
        def global_sensitivity(self):
            return 1.0

        @property
        def is_monotonic(self):
            return True

    def test_probabilities(self):
        mech = dp.ExponentialMechanism(self._Scoring())
        probs = mech._calculate_probabilities(1.0, [0, 1, 2])
        assert probs[2] > probs[1] > probs[0]
        assert probs.sum() == pytest.approx(1.0)
        # Closed form: p_i ∝ e^i
        expected = np.exp([0, 1, 2]) / np.exp([0, 1, 2]).sum()
        np.testing.assert_allclose(probs, expected, rtol=1e-12)

    def test_apply_returns_input_element(self):
        mech = dp.ExponentialMechanism(self._Scoring())
        assert mech.apply(10.0, [1, 2, 50]) in (1, 2, 50)
