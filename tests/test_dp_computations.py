"""Tests for native DP numerics.

Statistical-distribution tests follow the reference pattern
(/root/reference/tests/dp_computations_test.py:99-177): large-sample noise
draws checked for mean/std within multi-sigma confidence deltas.
"""

import math

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dp_computations as dp
from pipelinedp_tpu.budget_accounting import MechanismSpec
from pipelinedp_tpu.aggregate_params import MechanismType

N_SAMPLES = 200_000


@pytest.fixture(autouse=True)
def _seed():
    dp.seed_mechanism_rng(12345)
    yield
    dp.seed_mechanism_rng(None)


class TestSensitivityCalculus:

    def test_l1_l2(self):
        assert dp.compute_l1_sensitivity(4, 2.5) == 10
        assert dp.compute_l2_sensitivity(4, 2.5) == 5

    def test_middle_and_squares(self):
        assert dp.compute_middle(-1, 3) == 1
        assert dp.compute_squares_interval(-2, 1) == (0, 4)
        assert dp.compute_squares_interval(1, 2) == (1, 4)

    def test_sensitivities_consistency(self):
        s = dp.Sensitivities(l0=4, linf=2)
        assert s.l1 == 8
        assert s.l2 == 4
        with pytest.raises(ValueError, match="L1"):
            dp.Sensitivities(l0=4, linf=2, l1=5)
        with pytest.raises(ValueError, match="positive"):
            dp.Sensitivities(l0=-1, linf=2)
        with pytest.raises(ValueError, match="both"):
            dp.Sensitivities(l0=4)

    def test_per_metric_sensitivities(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=2,
                                     min_value=-1,
                                     max_value=4)
        assert dp.compute_sensitivities_for_count(params).l1 == 6
        assert dp.compute_sensitivities_for_privacy_id_count(params).l1 == 3
        assert dp.compute_sensitivities_for_sum(params).linf == 8
        # normalized sum: (4 - -1)/2 * 2 = 5
        assert dp.compute_sensitivities_for_normalized_sum(params).linf == 5


class TestAnalyticGaussian:

    @pytest.mark.parametrize("eps,delta,sens", [(1.0, 1e-6, 1.0),
                                                (0.1, 1e-10, 3.0),
                                                (5.0, 1e-5, 0.5),
                                                (10.0, 1e-12, 1.0)])
    def test_calibration_is_tight(self, eps, delta, sens):
        sigma = dp.gaussian_sigma(eps, delta, sens)
        assert dp.gaussian_delta(sigma, eps, sens) <= delta * (1 + 1e-6)
        # Slightly smaller sigma must violate delta (tightness).
        assert dp.gaussian_delta(sigma * 0.999, eps, sens) > delta

    def test_beats_classic_bound(self):
        # The analytic mechanism is never worse than the classic
        # sqrt(2 ln(1.25/delta))/eps calibration (for eps<=1).
        eps, delta = 0.5, 1e-6
        classic = math.sqrt(2 * math.log(1.25 / delta)) / eps
        assert dp.gaussian_sigma(eps, delta, 1.0) <= classic

    def test_invalid_delta(self):
        with pytest.raises(ValueError):
            dp.gaussian_sigma(1.0, 0, 1.0)


class TestNoiseDistributions:

    def test_laplace_mechanism_distribution(self):
        mech = dp.LaplaceMechanism.create_from_epsilon(2.0, 4.0)  # b = 2
        samples = np.array([mech.add_noise(10.0) for _ in range(N_SAMPLES)])
        b = 2.0
        assert samples.mean() == pytest.approx(10.0,
                                               abs=5 * b * math.sqrt(2) /
                                               math.sqrt(N_SAMPLES))
        assert samples.std() == pytest.approx(b * math.sqrt(2), rel=0.02)
        assert mech.std == pytest.approx(b * math.sqrt(2))

    def test_gaussian_mechanism_distribution(self):
        mech = dp.GaussianMechanism.create_from_epsilon_delta(1.0, 1e-6, 1.0)
        sigma = mech.std
        samples = np.array([mech.add_noise(0.0) for _ in range(N_SAMPLES)])
        assert samples.mean() == pytest.approx(0.0,
                                               abs=5 * sigma /
                                               math.sqrt(N_SAMPLES))
        assert samples.std() == pytest.approx(sigma, rel=0.02)
        # ~68%/95% mass within 1/2 sigma.
        within1 = np.mean(np.abs(samples) < sigma)
        assert within1 == pytest.approx(0.6827, abs=0.01)

    def test_create_from_std_deviation(self):
        lap = dp.LaplaceMechanism.create_from_std_deviation(2.0, 3.0)
        assert lap.std == pytest.approx(2.0 * 3.0)
        gauss = dp.GaussianMechanism.create_from_std_deviation(2.0, 3.0)
        assert gauss.std == pytest.approx(6.0)


class TestBudgetSplit:

    def test_equally_split_budget(self):
        budgets = dp.equally_split_budget(1.0, 1e-6, 3)
        assert len(budgets) == 3
        assert sum(b[0] for b in budgets) == pytest.approx(1.0)
        assert sum(b[1] for b in budgets) == pytest.approx(1e-6)
        with pytest.raises(ValueError):
            dp.equally_split_budget(1.0, 0, 0)


class TestMeanAndVariance:

    def _huge_eps_params(self, **kwargs):
        defaults = dict(eps=1e6,
                        delta=1e-8,
                        min_value=0.0,
                        max_value=10.0,
                        min_sum_per_partition=None,
                        max_sum_per_partition=None,
                        max_partitions_contributed=1,
                        max_contributions_per_partition=3,
                        noise_kind=pdp.NoiseKind.LAPLACE)
        defaults.update(kwargs)
        return dp.ScalarNoiseParams(**defaults)

    def test_mean_mechanism_huge_eps(self):
        spec_count = MechanismSpec(MechanismType.LAPLACE)
        spec_count.set_eps_delta(1e6, 0)
        spec_sum = MechanismSpec(MechanismType.LAPLACE)
        spec_sum.set_eps_delta(1e6, 0)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=3,
                                     min_value=0.0,
                                     max_value=10.0)
        mech = dp.create_mean_mechanism(
            5.0, spec_count, dp.compute_sensitivities_for_count(params),
            spec_sum, dp.compute_sensitivities_for_normalized_sum(params))
        # values [4, 6, 8]: count=3, normalized_sum = (4-5)+(6-5)+(8-5)=3
        dp_count, dp_sum, dp_mean = mech.compute_mean(3, 3.0)
        assert dp_count == pytest.approx(3, abs=1e-2)
        assert dp_mean == pytest.approx(6.0, abs=1e-2)
        assert dp_sum == pytest.approx(18.0, abs=0.1)

    def test_compute_dp_var_huge_eps(self):
        params = self._huge_eps_params()
        values = np.array([2.0, 4.0, 6.0])
        middle = 5.0
        normalized = values - middle
        count, nsum, nsum2 = 3, normalized.sum(), (normalized**2).sum()
        dp_count, dp_sum, dp_mean, dp_var = dp.compute_dp_var(
            count, nsum, nsum2, params)
        assert dp_count == pytest.approx(3, abs=1e-2)
        assert dp_mean == pytest.approx(4.0, abs=1e-2)
        assert dp_var == pytest.approx(values.var(), abs=0.1)

    def test_noise_std_predictors(self):
        params = self._huge_eps_params(eps=1.0,
                                       min_sum_per_partition=0.0,
                                       max_sum_per_partition=2.0,
                                       min_value=None,
                                       max_value=None)
        count_std = dp.compute_dp_count_noise_std(params)
        assert count_std == pytest.approx(3 / 1.0 * math.sqrt(2))
        sum_std = dp.compute_dp_sum_noise_std(params)
        assert sum_std == pytest.approx(2 / 1.0 * math.sqrt(2))


class TestVectorNoise:

    def test_clip_linf(self):
        vec = np.array([-5.0, 0.5, 3.0])
        clipped = dp._clip_vector(vec, 1.0, pdp.NormKind.Linf)
        np.testing.assert_allclose(clipped, [-1.0, 0.5, 1.0])

    def test_clip_l2(self):
        vec = np.array([3.0, 4.0])
        clipped = dp._clip_vector(vec, 1.0, pdp.NormKind.L2)
        np.testing.assert_allclose(clipped, [0.6, 0.8])

    def test_add_noise_vector_huge_eps(self):
        params = dp.AdditiveVectorNoiseParams(
            eps_per_coordinate=1e6,
            delta_per_coordinate=0,
            max_norm=10.0,
            l0_sensitivity=1,
            linf_sensitivity=1,
            norm_kind=pdp.NormKind.Linf,
            noise_kind=pdp.NoiseKind.LAPLACE)
        noised = dp.add_noise_vector(np.array([1.0, 2.0]), params)
        np.testing.assert_allclose(noised, [1.0, 2.0], atol=1e-2)


class TestExponentialMechanism:

    class _Scoring(dp.ExponentialMechanism.ScoringFunction):

        def score(self, k):
            return float(k)

        @property
        def global_sensitivity(self):
            return 1.0

        @property
        def is_monotonic(self):
            return True

    def test_probabilities(self):
        mech = dp.ExponentialMechanism(self._Scoring())
        probs = mech._calculate_probabilities(1.0, [0, 1, 2])
        assert probs[2] > probs[1] > probs[0]
        assert probs.sum() == pytest.approx(1.0)
        # Closed form: p_i ∝ e^i
        expected = np.exp([0, 1, 2]) / np.exp([0, 1, 2]).sum()
        np.testing.assert_allclose(probs, expected, rtol=1e-12)

    def test_apply_returns_input_element(self):
        mech = dp.ExponentialMechanism(self._Scoring())
        assert mech.apply(10.0, [1, 2, 50]) in (1, 2, 50)


class TestPerMetricSensitivitiesMaxContributions:
    """max_contributions (total-bound) sensitivity derivations
    (reference dp_computations.py:719-761 max_contributions branches)."""

    def _params(self, metrics, **kw):
        return pdp.AggregateParams(metrics=metrics,
                                   noise_kind=pdp.NoiseKind.GAUSSIAN,
                                   max_contributions=6,
                                   **kw)

    def test_count(self):
        s = dp.compute_sensitivities_for_count(
            self._params([pdp.Metrics.COUNT]))
        assert (s.l1, s.l2) == (6, 6)
        assert s.l0 is None and s.linf is None

    def test_privacy_id_count(self):
        s = dp.compute_sensitivities_for_privacy_id_count(
            self._params([pdp.Metrics.PRIVACY_ID_COUNT]))
        assert s.l1 == 6
        assert s.l2 == pytest.approx(math.sqrt(6))

    def test_sum(self):
        s = dp.compute_sensitivities_for_sum(
            self._params([pdp.Metrics.SUM], min_value=-2.0, max_value=1.0))
        # max_abs_value = 2, times max_contributions = 6.
        assert s.l1 == s.l2 == pytest.approx(12.0)

    def test_normalized_sum(self):
        s = dp.compute_sensitivities_for_normalized_sum(
            self._params([pdp.Metrics.MEAN], min_value=0.0, max_value=10.0))
        # (max-min)/2 = 5, times max_contributions = 6.
        assert s.l1 == s.l2 == pytest.approx(30.0)


class TestPerMetricSensitivitiesSumRegimes:

    def test_sum_per_partition_bounds(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     noise_kind=pdp.NoiseKind.LAPLACE,
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1,
                                     min_sum_per_partition=-4.0,
                                     max_sum_per_partition=2.0)
        s = dp.compute_sensitivities_for_sum(params)
        # Linf = max(|-4|, |2|) = 4, independent of contributions count.
        assert (s.l0, s.linf) == (3, 4.0)
        assert s.l1 == pytest.approx(12.0)
        assert s.l2 == pytest.approx(math.sqrt(3) * 4.0)

    def test_sum_value_bounds(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     noise_kind=pdp.NoiseKind.LAPLACE,
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=5,
                                     min_value=-1.0,
                                     max_value=3.0)
        s = dp.compute_sensitivities_for_sum(params)
        assert (s.l0, s.linf) == (2, 15.0)  # 3 * 5


class TestMechanismFactories:
    """create_additive_mechanism dispatch over spec state
    (reference dp_computations.py:622-659)."""

    def test_laplace_from_epsilon(self):
        spec = MechanismSpec(MechanismType.LAPLACE)
        spec.set_eps_delta(0.5, None)
        mech = dp.create_additive_mechanism(spec, dp.Sensitivities(l0=2,
                                                                   linf=3))
        assert isinstance(mech, dp.LaplaceMechanism)
        assert mech.noise_parameter == pytest.approx(6 / 0.5)  # l1/eps
        assert mech.std == pytest.approx(math.sqrt(2) * 12.0)
        assert mech.sensitivity == 6

    def test_laplace_from_stddev(self):
        spec = MechanismSpec(MechanismType.LAPLACE)
        spec.set_noise_standard_deviation(3.0)  # normalized by l1
        mech = dp.create_additive_mechanism(spec, dp.Sensitivities(l1=2.0))
        assert isinstance(mech, dp.LaplaceMechanism)
        # b = normalized_stddev/sqrt(2); eps = 1/b (per-unit-sensitivity).
        assert mech.epsilon == pytest.approx(math.sqrt(2) / 3.0)

    def test_laplace_requires_l1(self):
        spec = MechanismSpec(MechanismType.LAPLACE)
        spec.set_eps_delta(1.0, None)
        with pytest.raises(ValueError, match="L1"):
            dp.create_additive_mechanism(spec, dp.Sensitivities(l2=1.0))

    def test_gaussian_from_epsilon_delta(self):
        spec = MechanismSpec(MechanismType.GAUSSIAN)
        spec.set_eps_delta(1.0, 1e-6)
        mech = dp.create_additive_mechanism(spec, dp.Sensitivities(l0=4,
                                                                   linf=1))
        assert isinstance(mech, dp.GaussianMechanism)
        assert mech.sensitivity == pytest.approx(2.0)  # sqrt(4)*1
        # Analytic sigma satisfies the (eps, delta) constraint tightly.
        assert dp.gaussian_delta(mech.std, 1.0, 2.0) <= 1e-6 * (1 + 1e-6)

    def test_gaussian_from_stddev(self):
        spec = MechanismSpec(MechanismType.GAUSSIAN)
        spec.set_noise_standard_deviation(1.5)
        mech = dp.create_additive_mechanism(spec, dp.Sensitivities(l2=2.0))
        assert mech.std == pytest.approx(3.0)  # normalized 1.5 * l2 2.0

    def test_gaussian_requires_l2(self):
        spec = MechanismSpec(MechanismType.GAUSSIAN)
        spec.set_eps_delta(1.0, 1e-6)
        with pytest.raises(ValueError, match="L2"):
            dp.create_additive_mechanism(spec, dp.Sensitivities(l1=1.0))

    def test_describe_strings(self):
        lap = dp.LaplaceMechanism.create_from_epsilon(2.0, 3.0)
        assert "Laplace mechanism" in lap.describe()
        assert "eps=2.0" in lap.describe()
        gau = dp.GaussianMechanism.create_from_epsilon_delta(1.0, 1e-6, 1.0)
        assert "Gaussian mechanism" in gau.describe()
        assert "delta=1e-06" in gau.describe()


class TestMeanMechanismEdgeCases:

    def _mech(self, count_std=0.0, sum_std=0.0):

        class _Fixed(dp.AdditiveMechanism):
            """Deterministic mechanism: adds a constant 'noise' offset."""

            def __init__(self, offset):
                self._offset = offset

            def add_noise(self, value):
                return float(value) + self._offset

            @property
            def noise_kind(self):
                return pdp.NoiseKind.LAPLACE

            @property
            def noise_parameter(self):
                return 0.0

            @property
            def std(self):
                return 0.0

            @property
            def sensitivity(self):
                return 1.0

            def describe(self):
                return "fixed"

        return dp.MeanMechanism(5.0, _Fixed(count_std), _Fixed(sum_std))

    def test_negative_dp_count_clamped_in_denominator(self):
        # DP count can come out negative; the denominator clamps at 1 so the
        # mean stays finite (reference MeanMechanism semantics).
        mech = self._mech(count_std=-10.0)  # count 2 -> dp_count -8
        dp_count, dp_sum, dp_mean = mech.compute_mean(2, 4.0)
        assert dp_count == -8.0
        assert dp_mean == pytest.approx(5.0 + 4.0 / 1.0)
        assert dp_sum == pytest.approx(dp_mean * dp_count)

    def test_zero_noise_recovers_exact_mean(self):
        mech = self._mech()
        # values [4, 6, 8] around middle 5: normalized_sum = 3.
        dp_count, dp_sum, dp_mean = mech.compute_mean(3, 3.0)
        assert (dp_count, dp_mean) == (3.0, 6.0)
        assert dp_sum == pytest.approx(18.0)

    def test_describe_narrates_both_mechanisms(self):
        text = self._mech().describe()
        assert "normalized_sum" in text
        assert "'count'" in text


class TestComputeDpVarEdgeCases:

    def test_equal_min_max_returns_min_value_mean(self):
        params = dp.ScalarNoiseParams(eps=1e6,
                                      delta=1e-8,
                                      min_value=7.0,
                                      max_value=7.0,
                                      min_sum_per_partition=None,
                                      max_sum_per_partition=None,
                                      max_partitions_contributed=1,
                                      max_contributions_per_partition=1,
                                      noise_kind=pdp.NoiseKind.GAUSSIAN)
        dp_count, dp_sum, dp_mean, dp_var = dp.compute_dp_var(
            4, 0.0, 0.0, params)
        # All values pinned at 7: mean = middle + 0 = 7, variance ~ 0.
        assert dp_count == pytest.approx(4, abs=1e-2)
        assert dp_mean == pytest.approx(7.0, abs=1e-2)
        assert dp_var == pytest.approx(0.0, abs=1e-2)


class TestExponentialMechanismSelection:

    class _TableScore(dp.ExponentialMechanism.ScoringFunction):

        def __init__(self, table, monotonic=True):
            self._table = table
            self._monotonic = monotonic

        def score(self, k):
            return self._table[k]

        @property
        def global_sensitivity(self):
            return 1.0

        @property
        def is_monotonic(self):
            return self._monotonic

    def test_dominant_score_always_chosen(self):
        table = {"a": 0.0, "b": 1000.0, "c": 1.0}
        mech = dp.ExponentialMechanism(self._TableScore(table))
        assert all(
            mech.apply(10.0, list(table)) == "b" for _ in range(50))

    def test_constant_scores_reach_all_elements(self):
        table = {k: 1.0 for k in "abcd"}
        mech = dp.ExponentialMechanism(self._TableScore(table))
        seen = {mech.apply(1.0, list(table)) for _ in range(400)}
        assert seen == set("abcd")

    def test_non_monotonic_halves_the_exponent(self):
        table = {"a": 0.0, "b": 1.0}
        mono = dp.ExponentialMechanism(self._TableScore(table, True))
        non_mono = dp.ExponentialMechanism(self._TableScore(table, False))
        p_mono = mono._calculate_probabilities(2.0, ["a", "b"])
        p_non = non_mono._calculate_probabilities(2.0, ["a", "b"])
        # softmax(score * eps / sens) vs softmax(score * eps / (2 sens)).
        assert p_mono[1] == pytest.approx(math.exp(2) / (1 + math.exp(2)))
        assert p_non[1] == pytest.approx(math.e / (1 + math.e))

    def test_precomputed_scores_used_when_given(self):
        table = {"a": 0.0, "b": 0.0}
        mech = dp.ExponentialMechanism(self._TableScore(table))
        # Override with vectorized scores making "a" dominant.
        chosen = {
            mech.apply(10.0, ["a", "b"], scores=np.array([1000.0, 0.0]))
            for _ in range(20)
        }
        assert chosen == {"a"}
