"""Hang detection and deadline recovery: the watchdog monitor, the
timeout -> same-key retry -> capacity-degradation ladder, the health
state machine, and the runtime-knob validators (pipelinedp_tpu/runtime/
watchdog.py + health.py).

Every hang here is injected (faults.Fault("hang", ...)) and doubly
bounded: the watchdog deadline cancels it, and the fault's own `delay`
hard cap fires even if the watchdog never does — plus the conftest
hard_timeout guard interrupts the whole test if BOTH fail, so a watchdog
bug cannot hang tier-1.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, executor, input_validators, runtime
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.ops import selection_ops
from pipelinedp_tpu.parallel import large_p, make_mesh
from pipelinedp_tpu.runtime import faults
from pipelinedp_tpu.runtime import health as health_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.runtime import watchdog as watchdog_lib

pytestmark = [pytest.mark.faults, pytest.mark.hard_timeout(120)]

FAST = retry_lib.RetryPolicy(max_retries=3, base_delay=0.0, max_delay=0.0)


def _spec(P, eps=1.0, l0=4, linf=8):
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=l0,
                                 max_contributions_per_partition=linf,
                                 min_value=0.0,
                                 max_value=5.0)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    budget = accountant.request_budget(MechanismType.GENERIC)
    accountant.compute_budgets()
    selection = selection_ops.selection_params_from_host(
        params.partition_selection_strategy, budget.eps, budget.delta, l0,
        None)
    cfg = executor.make_kernel_config(params, compound, P,
                                      private_selection=True,
                                      selection_params=selection)
    stds = executor.compute_noise_stds(compound, params)
    return cfg, stds, executor.kernel_scalars(params)


def _data(n=20_000, n_ids=500, P=1000, seed=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n_ids, n).astype(np.int32)
    pk = rng.integers(0, P, n).astype(np.int32)
    values = rng.uniform(0, 5, n)
    return pid, pk, values, np.ones(n, bool)


class TestWatchdogUnit:

    def test_expiry_sets_cancel_and_counts(self):
        wd = watchdog_lib.Watchdog(timeout_s=0.05, poll_interval_s=0.01)
        before = telemetry.snapshot()
        with pytest.raises(watchdog_lib.BlockTimeoutError):
            with wd.guard("dispatch", 7) as g:
                assert g.cancel.wait(2.0), "monitor never cancelled"
                g.raise_if_expired()
        assert telemetry.delta(before).get("watchdog_timeouts") == 1

    def test_resolved_timeout_precedence(self):
        wd = watchdog_lib.Watchdog(timeout_s=None, multiplier=4.0,
                                   min_timeout_s=0.1)
        # No profile, no timeout: no deadline.
        assert wd.resolved_timeout("dispatch") == float("inf")
        wd.seed_profile(1.0)
        assert wd.resolved_timeout("dispatch") == pytest.approx(4.0)
        # Per-phase observation beats the "*" seed when larger.
        wd.observe("dispatch", 2.0)
        assert wd.resolved_timeout("dispatch") == pytest.approx(8.0)
        # The floor applies to tiny profiled times.
        wd2 = watchdog_lib.Watchdog(multiplier=4.0, min_timeout_s=0.5)
        wd2.seed_profile(1e-6)
        assert wd2.resolved_timeout("drain") == pytest.approx(0.5)
        # Explicit per-call and watchdog-wide timeouts win.
        assert wd.resolved_timeout("dispatch", 0.3) == pytest.approx(0.3)
        wd3 = watchdog_lib.Watchdog(timeout_s=2.5)
        wd3.seed_profile(100.0)
        assert wd3.resolved_timeout("dispatch") == pytest.approx(2.5)

    def test_late_completion_kept_and_counted(self):
        wd = watchdog_lib.Watchdog(timeout_s=0.03, poll_interval_s=0.01)
        before = telemetry.snapshot()
        with wd.guard("drain", 0) as g:
            g.cancel.wait(2.0)  # deadline expired mid-operation...
        # ...but the operation completed: no raise, counted as late.
        delta = telemetry.delta(before)
        assert delta.get("watchdog_timeouts") == 1
        assert delta.get("watchdog_late_completions") == 1

    def test_invalid_timeouts_rejected(self):
        for bad in (0, -1, float("nan"), float("inf")):
            with pytest.raises(ValueError, match="timeout_s"):
                watchdog_lib.Watchdog(timeout_s=bad)
        with pytest.raises(ValueError, match="multiplier"):
            watchdog_lib.Watchdog(multiplier=0)

    def test_guard_without_active_watchdog_is_noop(self):
        with watchdog_lib.guard("dispatch", 0):
            assert watchdog_lib.current_token() is None


class TestRuntimeKnobValidation:

    def test_backend_rejects_bad_knobs(self):
        with pytest.raises(ValueError, match="timeout_s"):
            pdp.TPUBackend(timeout_s=-1)
        with pytest.raises(ValueError, match="non-empty"):
            pdp.TPUBackend(job_id="  ")
        with pytest.raises(ValueError, match="path"):
            pdp.TPUBackend(job_id="../steal")
        with pytest.raises(ValueError, match="max_retries"):
            pdp.TPUBackend(retry=retry_lib.RetryPolicy(max_retries=-1))
        # Valid knobs construct fine.
        pdp.TPUBackend(timeout_s=30.0, job_id="job-1", retry=FAST)

    def test_driver_rejects_bad_knobs(self):
        P = 64
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data(n=100, P=P)
        args = (pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
                np.asarray(stds), jax.random.PRNGKey(0), cfg)
        with pytest.raises(ValueError, match="timeout_s"):
            large_p.aggregate_blocked(*args, timeout_s=0)
        with pytest.raises(ValueError, match="path"):
            large_p.aggregate_blocked(*args, job_id="a/b")
        with pytest.raises(ValueError, match="max_retries"):
            large_p.aggregate_blocked(
                *args, retry=retry_lib.RetryPolicy(max_retries=-2))

    def test_validator_messages_are_actionable(self):
        with pytest.raises(ValueError, match="None to disable"):
            input_validators.validate_timeout_s(-3, "T")
        with pytest.raises(ValueError, match="file-name"):
            input_validators.validate_job_id("x" * 500, "T")


class TestTelemetryTiming:

    def test_min_max_sum_count(self):
        telemetry.record_duration("phase_x", 0.5)
        telemetry.record_duration("phase_x", 1.5)
        snap = telemetry.full_snapshot()["timings"]["phase_x"]
        assert snap["count"] == 2
        assert snap["min"] == pytest.approx(0.5)
        assert snap["max"] == pytest.approx(1.5)
        assert snap["sum"] == pytest.approx(2.0)
        # delta() stays integer-counter-only even across timing updates.
        before = telemetry.snapshot()
        telemetry.record_duration("phase_x", 1.0)
        assert telemetry.delta(before) == {}


class TestHealthStateMachine:

    def test_escalation_and_recovery(self):
        h = health_lib.JobHealth("t-job")
        assert h.state is health_lib.HealthState.HEALTHY
        h.observe_counter("block_retries", 1)
        assert h.state is health_lib.HealthState.DEGRADED
        h.note_timeout("dispatch", 3)
        assert h.state is health_lib.HealthState.STALLED
        h.note_recovered()
        assert h.state is health_lib.HealthState.DEGRADED
        h.note_failed(RuntimeError("boom"))
        assert h.state is health_lib.HealthState.FAILED
        # FAILED ignores further escalation...
        h.observe_counter("watchdog_timeouts", 1)
        assert h.state is health_lib.HealthState.FAILED
        # ...until a later run of the job completes (journaled resume).
        h.note_complete()
        assert h.state is health_lib.HealthState.DEGRADED
        snap = h.snapshot()
        assert snap["state"] == "DEGRADED"
        assert snap["counters"]["block_retries"] == 1
        assert snap["last_error"] == "RuntimeError: boom"

    def test_job_scope_tracks_and_completes(self):
        with health_lib.job_scope("scope-job") as h:
            telemetry.record("block_retries")
        assert h.snapshot()["counters"]["block_retries"] >= 1
        assert h.snapshot()["completed_runs"] == 1
        assert h.state is health_lib.HealthState.DEGRADED

    def test_job_scope_records_failure(self):
        with pytest.raises(RuntimeError):
            with health_lib.job_scope("fail-job"):
                raise RuntimeError("kaput")
        h = health_lib.for_job("fail-job")
        assert h.state is health_lib.HealthState.FAILED
        assert "kaput" in h.snapshot()["last_error"]


class TestHangRecovery:
    """A hang on a dispatch and on a drain each recovers within the
    deadline and yields bit-identical outputs (same fold_in key)."""

    def _run(self, **kwargs):
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data(P=P)
        return large_p.aggregate_blocked(pid, pk, values, valid, min_v,
                                         max_v, min_s, max_s, mid,
                                         np.asarray(stds),
                                         jax.random.PRNGKey(7), cfg,
                                         block_partitions=128, retry=FAST,
                                         **kwargs)

    def test_dispatch_and_drain_hangs_bit_identical(self):
        base_kept, base_out = self._run()
        before = telemetry.snapshot()
        sched = faults.FaultSchedule([
            faults.Fault("hang", block=1, delay=60, point="dispatch"),
            faults.Fault("hang", block=3, delay=60, point="drain"),
        ])
        with faults.inject(sched):
            kept, out = self._run(timeout_s=1.0, job_id="hang-job")
        assert sched.pending() == 0
        np.testing.assert_array_equal(base_kept, kept)
        for name in base_out:
            np.testing.assert_array_equal(base_out[name], out[name],
                                          err_msg=name)
        delta = telemetry.delta(before)
        # The 60s injected hangs were cancelled BY THE DEADLINE (well
        # under the hard_timeout guard), then retried same-key.
        assert delta.get("watchdog_timeouts", 0) >= 2
        assert delta.get("block_timeouts", 0) >= 2
        assert delta.get("block_retries", 0) >= 2
        snap = health_lib.for_job("hang-job").snapshot()
        assert snap["state"] == "DEGRADED"  # recovered, didn't run clean

    def test_hang_without_watchdog_hits_hard_cap(self):
        base_kept, base_out = self._run()
        sched = faults.FaultSchedule(
            [faults.Fault("hang", block=2, delay=0.2)])
        t0 = time.monotonic()
        with faults.inject(sched):
            kept, out = self._run()
        assert time.monotonic() - t0 < 30  # the cap, not the default 30s
        np.testing.assert_array_equal(base_kept, kept)
        for name in base_out:
            np.testing.assert_array_equal(base_out[name], out[name],
                                          err_msg=name)

    def test_hang_exhausts_retries_then_raises_without_journal_geometry(
            self):
        # With retries exhausted the timeout escalates to re-planning;
        # at block_partitions=16 the capacity floor stops the halving and
        # the BlockOOMError (cause: timeout) propagates.
        sched = faults.FaultSchedule(
            [faults.Fault("hang", delay=0.05, times=64)])
        with faults.inject(sched):
            with pytest.raises(retry_lib.BlockOOMError):
                P = 1000
                cfg, stds, scalars = _spec(P)
                pid, pk, values, valid = _data(P=P)
                large_p.aggregate_blocked(pid, pk, values, valid,
                                          *scalars, np.asarray(stds),
                                          jax.random.PRNGKey(7), cfg,
                                          block_partitions=16, retry=FAST)


class TestTimeoutDegradation:
    """Repeated timeouts on one block degrade exactly like OOM: capacity
    halves, the remaining range re-plans, results match the fault-free
    run (key-independent noise-free data, as in TestOOMDegradation)."""

    DENSE = ((np.arange(12) * 77 + 5) % 1000).astype(np.int64)

    def _run_noise_free(self, **kwargs):
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P, eps=30,
                                                             linf=64)
        n_per = 120
        pid = (np.repeat(np.arange(n_per), len(self.DENSE)) * 1003 +
               np.tile(np.arange(len(self.DENSE)), n_per)).astype(np.int32)
        pk = np.tile(self.DENSE, n_per).astype(np.int32)
        rng = np.random.default_rng(4)
        values = rng.uniform(0, 5, len(pk))
        pid = np.concatenate([pid, 900_000 + np.arange(5, dtype=np.int32)])
        pk = np.concatenate(
            [pk, ((np.arange(5) * 311 + 9) % P).astype(np.int32)])
        values = np.concatenate([values, np.ones(5)])
        valid = np.ones(len(pid), bool)
        return large_p.aggregate_blocked(pid, pk, values, valid, min_v,
                                         max_v, min_s, max_s, mid,
                                         np.zeros_like(np.asarray(stds)),
                                         jax.random.PRNGKey(5), cfg,
                                         block_partitions=128, retry=FAST,
                                         **kwargs)

    def test_repeated_timeouts_degrade_like_oom(self):
        base_kept, base_out = self._run_noise_free()
        before = telemetry.snapshot()
        with faults.inject(
                faults.FaultSchedule([
                    faults.Fault("hang", block=3,
                                 times=FAST.max_retries + 1, delay=0.1,
                                 point="dispatch")
                ])):
            kept, out = self._run_noise_free(job_id="timeout-degrade")
        np.testing.assert_array_equal(base_kept, kept)
        np.testing.assert_allclose(base_out["count"], out["count"],
                                   atol=1e-9)
        np.testing.assert_allclose(base_out["sum"], out["sum"], rtol=1e-6)
        delta = telemetry.delta(before)
        assert delta.get("block_oom_degradations") == 1
        assert delta.get("block_timeouts", 0) >= FAST.max_retries


class TestCollectiveDeadline:
    """A hang on the device-reshard collective falls back to the host LPT
    permutation exactly like a collective failure."""

    def test_collective_hang_falls_back_to_host(self):
        mesh = make_mesh(n_devices=8)
        P = 1 << 12
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P, eps=30,
                                                             linf=64)
        stds = np.zeros_like(np.asarray(stds))
        dense = (np.arange(12) * 331 + 17) % P
        n_per = 120
        pid = (np.repeat(np.arange(n_per), len(dense)) * 1003 +
               np.tile(np.arange(len(dense)), n_per)).astype(np.int32)
        pk = np.tile(dense, n_per).astype(np.int32)
        rng = np.random.default_rng(3)
        values = rng.uniform(0, 5, len(pk))
        valid = np.ones(len(pid), bool)
        key = jax.random.PRNGKey(11)
        base_kept, base_out = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=1 << 9)
        dev = (jnp.asarray(pid), jnp.asarray(pk), jnp.asarray(values),
               jnp.asarray(valid))
        before = telemetry.snapshot()
        with faults.inject(
                faults.FaultSchedule(
                    [faults.Fault("hang", point="collective", delay=0.3)])):
            kept, out = large_p.aggregate_blocked_sharded(
                mesh, *dev, min_v, max_v, min_s, max_s, mid, stds, key,
                cfg, block_partitions=1 << 9, retry=FAST, timeout_s=20.0,
                job_id="coll-hang")
        np.testing.assert_array_equal(base_kept, kept)
        np.testing.assert_allclose(base_out["count"], out["count"],
                                   atol=1e-9)
        np.testing.assert_allclose(base_out["sum"], out["sum"], rtol=1e-6,
                                   atol=1e-6)
        assert telemetry.delta(before).get("reshard_host_fallbacks") == 1
        assert health_lib.for_job("coll-hang").snapshot()["counters"].get(
            "reshard_host_fallbacks") == 1


class TestBackendHealth:
    """TPUBackend(timeout_s=...) threads the watchdog through the engine,
    and TPUBackend.health() answers for the jobs it ran."""

    def _aggregate(self, backend, rows):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=8,
            min_value=0.0,
            max_value=5.0)
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1],
            value_extractor=lambda r: r[2])
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, backend)
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        registered = accountant.mechanism_count
        out = dict(result)
        assert accountant.mechanism_count == registered
        return out, registered

    def test_engine_hang_recovers_ledger_stable_health_reports(self):
        rng = np.random.default_rng(1)
        rows = list(
            zip(rng.integers(0, 300, 8000).tolist(),
                rng.integers(0, 3000, 8000).tolist(),
                rng.uniform(0, 5, 8000).tolist()))
        make = lambda **kw: pdp.TPUBackend(noise_seed=13,
                                           large_partition_threshold=1 << 10,
                                           block_partitions=1 << 10,
                                           retry=FAST,
                                           **kw)
        base, n_base = self._aggregate(make(), rows)
        backend = make(timeout_s=5.0, job_id="engine-hang")
        sched = faults.FaultSchedule(
            [faults.Fault("hang", block=0, delay=0.3, point="dispatch")])
        with faults.inject(sched):
            faulted, n_faulted = self._aggregate(backend, rows)
        assert sched.pending() == 0
        assert n_base == n_faulted  # zero duplicate registrations
        assert base == faulted
        snaps = backend.health()
        assert "engine-hang" in snaps
        snap = snaps["engine-hang"]
        assert snap["state"] == "DEGRADED"
        assert snap["counters"].get("block_retries", 0) >= 1

    def test_clean_run_reports_healthy(self):
        rng = np.random.default_rng(2)
        rows = list(
            zip(rng.integers(0, 100, 2000).tolist(),
                rng.integers(0, 2000, 2000).tolist(),
                rng.uniform(0, 5, 2000).tolist()))
        backend = pdp.TPUBackend(noise_seed=13,
                                 large_partition_threshold=1 << 10,
                                 block_partitions=1 << 10,
                                 job_id="clean-run")
        self._aggregate(backend, rows)
        snap = backend.health()["clean-run"]
        assert snap["state"] == "HEALTHY"
        assert snap["completed_runs"] >= 1
        assert snap["journal_quarantined"] == 0
