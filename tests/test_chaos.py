"""Chaos campaigns: randomized composed-fault schedules with invariant
checking, schedule minimization, and storage-fault hardening.

The contracts under test:

  * **Generator determinism** — ChaosCampaign(seed).schedules_for(t) is
    a pure function of (seed, t) through a private random.Random: two
    fresh campaign instances produce identical schedules, the
    process-global RNG is never touched, and the pinned tier-1 campaign
    (seed=3, 20 trials, intensity=0.6) covers the FULL fault-kind
    vocabulary.
  * **The campaign gate** — the pinned 20-trial campaign runs composed
    overlapping faults through the service + driver workload and every
    universal invariant holds: exactly-once completion, bit-exact
    ledger reconciliation over the whole campaign history, results
    bit-identical to fault-free baselines, counters consistent with the
    firings.
  * **The checker catches real bugs** — mutation tests: a double-charge
    planted in the completion map fails the disk audit; a duplicated
    completion across trials fails the exactly-once gate (and bumps
    ``chaos_invariant_failures``).
  * **The minimizer** — a planted two-fault bug buried in a six-fault
    composed schedule shrinks to exactly those two faults at their
    weakest strength, and the emitted FaultSchedule literal is runnable
    and still reproduces.
  * **Storage-fault hardening** — ENOSPC / failed fsync / EIO at the
    journal and ledger seams fail CLOSED: disk_full never retries a
    hopeless write, a failed fsync gets exactly one fresh-fd rewrite
    (fsyncgate — never re-fsync the same fd), an unreadable record
    quarantines instead of replaying, and the service converts a sick
    store into a typed shed with retry_after_s — reservation released,
    zero odometer records, never a lost job or a wedged worker.
  * **Deadline / cancel / retry budget** — submit(deadline_s=) and
    JobHandle.cancel() settle CANCELLED with a typed JobCancelledError
    and charge nothing; RetryPolicy.max_total_retries caps a job's
    TOTAL transient retries across every seam with a typed exhaustion.
"""

import os
import random

import numpy as np
import pytest

import jax

import pipelinedp_tpu as pdp
from pipelinedp_tpu import pipeline_backend
from pipelinedp_tpu.runtime import chaos
from pipelinedp_tpu.runtime import drill as drill_lib
from pipelinedp_tpu.runtime import faults
from pipelinedp_tpu.runtime import journal as journal_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.parallel import make_mesh
from pipelinedp_tpu.service import (AdmissionRejectedError,
                                    DPAggregationService,
                                    JobCancelledError, JobSpec, JobStatus)

from test_elastic import _blocked_agg_runner

pytestmark = pytest.mark.chaos

# The pinned tier-1 campaign: seed 3 at intensity 0.6 covers every kind
# in the vocabulary across its 20 trials (pinned by
# test_pinned_campaign_covers_full_vocabulary below — pick a new seed if
# the sampler changes).
SEED, TRIALS, INTENSITY = 3, 20, 0.6


def _pinned_campaign() -> chaos.ChaosCampaign:
    return chaos.ChaosCampaign(seed=SEED, trials=TRIALS,
                               intensity=INTENSITY)


def _small_spec(noise_seed=29):
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT],
        max_partitions_contributed=1,
        max_contributions_per_partition=1,
        min_value=0.0, max_value=1.0)
    ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                             partition_extractor=lambda r: r[1],
                             value_extractor=lambda r: r[2])
    return JobSpec(params=params, epsilon=1.0, delta=1e-6,
                   data_extractors=ext, noise_seed=noise_seed,
                   public_partitions=["A"])


_ROWS = [("u1", "A", 1.0), ("u2", "A", 1.0)]


class TestGenerator:

    def test_schedules_replay_bit_exact_across_instances(self):
        """(seed, trial) alone reconstructs any trial: two fresh
        campaign objects agree on every Fault of every schedule."""
        a, b = _pinned_campaign(), _pinned_campaign()
        for t in range(TRIALS):
            assert a.schedules_for(t) == b.schedules_for(t)

    def test_generator_never_touches_the_global_rng(self):
        state = random.getstate()
        list(_pinned_campaign())
        assert random.getstate() == state

    def test_distinct_seeds_sample_distinct_schedules(self):
        other = chaos.ChaosCampaign(seed=SEED + 1, trials=TRIALS,
                                    intensity=INTENSITY)
        mine = _pinned_campaign()
        assert any(mine.schedules_for(t) != other.schedules_for(t)
                   for t in range(TRIALS))

    def test_pinned_campaign_covers_full_vocabulary(self):
        """The tier-1 campaign is not a partial probe: every fault kind
        — including all three storage kinds — appears in some trial."""
        kinds = set()
        for sched in _pinned_campaign():
            kinds.update(f.kind for f in sched.service + sched.driver)
        assert kinds == set(chaos.ALL_KINDS)

    def test_kind_restriction_is_honored(self):
        campaign = chaos.ChaosCampaign(seed=5, trials=10,
                                       kinds=("dispatch", "oom"))
        for sched in campaign:
            assert not sched.service  # no service-pool kinds allowed in
            assert {f.kind for f in sched.driver} <= {"dispatch", "oom"}

    def test_campaign_validates_its_inputs(self):
        with pytest.raises(ValueError, match="seed"):
            chaos.ChaosCampaign(seed="3", trials=5)
        with pytest.raises(ValueError, match="trials"):
            chaos.ChaosCampaign(seed=3, trials=0)
        with pytest.raises(ValueError, match="intensity"):
            chaos.ChaosCampaign(seed=3, trials=5, intensity=0.0)
        with pytest.raises(ValueError, match="intensity"):
            chaos.ChaosCampaign(seed=3, trials=5, intensity=1.5)
        with pytest.raises(ValueError, match="unknown fault kinds"):
            chaos.ChaosCampaign(seed=3, trials=5, kinds=("meteor",))
        with pytest.raises(ValueError, match="n_blocks"):
            chaos.ChaosCampaign(seed=3, trials=5, n_blocks=0)
        with pytest.raises(ValueError, match="out of range"):
            _pinned_campaign().schedules_for(TRIALS)

    def test_fault_literal_round_trips(self):
        for sched in _pinned_campaign():
            for fault in sched.service + sched.driver:
                rebuilt = eval(chaos.fault_literal(fault),  # noqa: S307 - the literal IS the contract under test
                               {"faults": faults})
                assert rebuilt == fault

    def test_schedule_literal_is_runnable(self):
        sched = _pinned_campaign().schedules_for(0)
        namespace = {"faults": faults}
        rebuilt = eval(chaos.schedule_literal(sched.driver),  # noqa: S307
                       namespace)
        assert isinstance(rebuilt, faults.FaultSchedule)
        assert rebuilt.pending() == sum(f.times for f in sched.driver)


class TestCampaign:

    @pytest.mark.hard_timeout(120)
    def test_pinned_campaign_every_invariant_holds(self, tmp_path):
        """The acceptance gate: 20 trials of composed overlapping
        faults (every kind in the vocabulary fires somewhere) and every
        universal invariant holds — all jobs land exactly once, the
        campaign-long disk trail reconciles bit-exactly, every result
        is bit-identical to its fault-free baseline."""
        before = telemetry.snapshot()
        report = chaos.run_campaign(_pinned_campaign(), str(tmp_path))
        assert report["invariants_hold"]
        assert report["trials"] == TRIALS
        assert report["jobs_completed"] == 3 * TRIALS
        assert report["total_firings"] > TRIALS  # composed, not sparse
        # Every kind the generator sampled actually fired somewhere.
        assert set(chaos.ALL_KINDS) == set(report["fired"])
        delta = telemetry.delta(before)
        assert delta.get("chaos_trials", 0) == TRIALS
        assert delta.get("chaos_invariant_failures", 0) == 0

    @pytest.mark.slow
    @pytest.mark.hard_timeout(300)
    def test_high_intensity_campaign(self, tmp_path):
        """The hostile end of the dial: intensity 1.0 composes up to 6
        driver faults + 2 service faults per trial."""
        campaign = chaos.ChaosCampaign(seed=11, trials=30, intensity=1.0)
        report = chaos.run_campaign(campaign, str(tmp_path))
        assert report["invariants_hold"]
        assert report["jobs_completed"] == 3 * 30


class TestCheckerCatchesBugs:
    """Mutation tests: the invariant checker must FAIL when fed the
    bugs it claims to catch — otherwise a green campaign proves
    nothing."""

    @pytest.mark.hard_timeout(120)
    def test_double_charge_and_duplicate_completion_fail(self, tmp_path):
        workload = chaos.default_workload()
        factory = pipeline_backend.TPUBackend
        ledger_dir = str(tmp_path / "ledger")
        completed = {}
        empty = chaos.TrialSchedules(trial=0, service=(), driver=())
        rep = chaos.run_trial(empty, workload, factory, ledger_dir,
                              str(tmp_path / "t0"), completed)
        assert rep["fired"] == {}
        # Plant a double-charge: the completion map claims one job spent
        # twice what the disk trail recorded — the bit-exact
        # reconciliation must refuse.
        tampered = {name: dict(done) for name, done in completed.items()}
        first = next(iter(tampered))
        tampered[first]["spent_epsilon"] = \
            2 * tampered[first]["spent_epsilon"]
        with pytest.raises(drill_lib.DrillFailure,
                           match="must be bit-exact"):
            drill_lib.audit_disk(ledger_dir, tampered)
        # Plant a duplicated completion: re-running trial 0 over the
        # same cumulative map re-lands the same logical names — the
        # exactly-once gate must refuse (and the failure counts).
        before = telemetry.snapshot()
        with pytest.raises(chaos.ChaosInvariantError,
                           match="completed twice"):
            chaos.run_trial(empty, workload, factory, ledger_dir,
                            str(tmp_path / "t0b"), completed)
        delta = telemetry.delta(before)
        assert delta.get("chaos_invariant_failures", 0) == 1


class TestMinimizer:

    # The planted bug: the run "fails" iff a dispatch fault AND an oom
    # fault are BOTH present — a genuine two-fault composition, buried
    # in a six-fault schedule below.
    @staticmethod
    def _planted_check(service, driver):
        kinds = {f.kind for f in service + driver}
        return "dispatch" in kinds and "oom" in kinds

    _COMPOSED = dict(
        service_faults=(faults.Fault("fsync_failure", point="odometer"),),
        driver_faults=(faults.Fault("dispatch", block=2, times=2),
                       faults.Fault("slow", block=1, delay=0.02),
                       faults.Fault("oom", block=1),
                       faults.Fault("hang", delay=0.1),
                       faults.Fault("corrupt", block=3, mode="flip")))

    def test_planted_two_fault_bug_shrinks_to_exactly_those_two(self):
        minimized = chaos.minimize_schedule(self._planted_check,
                                            **self._COMPOSED)
        assert minimized.service == ()
        assert {f.kind for f in minimized.driver} == {"dispatch", "oom"}
        # Locally minimal means weakest strength too: single firings,
        # block wildcards.
        assert all(f.times == 1 and f.block is None
                   for f in minimized.driver)

    def test_minimized_literal_is_runnable_and_still_fails(self):
        minimized = chaos.minimize_schedule(self._planted_check,
                                            **self._COMPOSED)
        namespace = {"faults": faults}
        exec(minimized.literal, namespace)  # noqa: S102 - the emitted reproducer IS the contract under test
        assert isinstance(namespace["service_schedule"],
                          faults.FaultSchedule)
        assert isinstance(namespace["driver_schedule"],
                          faults.FaultSchedule)
        assert namespace["driver_schedule"].pending() == len(
            minimized.driver)
        # ...and the minimized schedule still reproduces the bug.
        assert self._planted_check(minimized.service, minimized.driver)

    def test_minimizer_rejects_a_passing_schedule(self):
        with pytest.raises(ValueError, match="does not fail"):
            chaos.minimize_schedule(
                lambda s, d: False,
                (faults.Fault("dispatch"),), ())

    def test_minimizer_respects_probe_cap(self):
        calls = []

        def check(service, driver):
            calls.append(1)
            return True  # everything "fails": shrinks to nothing

        minimized = chaos.minimize_schedule(
            check, (), tuple(faults.Fault("dispatch", block=b)
                             for b in range(4)), max_probes=5)
        assert minimized.probes <= 5
        assert len(calls) <= 5


class TestStorageFaultsJournalSeam:
    """ENOSPC / fsyncgate / EIO contracts at the block-record store."""

    RECORD = journal_lib.BlockRecord(
        ids=np.arange(3, dtype=np.int64),
        outputs={"count": np.ones(3, dtype=np.float64)})
    RECORD2 = journal_lib.BlockRecord(
        ids=np.arange(4, dtype=np.int64),
        outputs={"count": np.full(4, 2.0)})

    def test_disk_full_fails_closed_without_retry(self, tmp_path):
        journal = journal_lib.BlockJournal(str(tmp_path))
        journal.put("job", "0:64", self.RECORD)
        before = telemetry.snapshot()
        sched = faults.FaultSchedule(
            [faults.Fault("disk_full", point="block")])
        with faults.inject(sched):
            with pytest.raises(journal_lib.StorageUnavailableError,
                               match="ENOSPC"):
                journal.put("job", "0:64", self.RECORD2)
        delta = telemetry.delta(before)
        # ENOSPC is hopeless: exactly one attempt, no rewrite.
        assert delta.get("storage_disk_full", 0) == 1
        assert delta.get("storage_unavailable", 0) == 1
        assert delta.get("storage_fsync_failures", 0) == 0
        # The tmp was unlinked and the PRIOR record remains the durable
        # truth — a fresh journal (disk-only view) proves it.
        assert not [n for n in os.listdir(tmp_path)
                    if n.endswith(".tmp")]
        replayed = journal_lib.BlockJournal(str(tmp_path)).get("job",
                                                               "0:64")
        assert np.array_equal(replayed.ids, self.RECORD.ids)

    def test_fsync_failure_gets_exactly_one_fresh_fd_rewrite(
            self, tmp_path):
        journal = journal_lib.BlockJournal(str(tmp_path))
        before = telemetry.snapshot()
        sched = faults.FaultSchedule(
            [faults.Fault("fsync_failure", point="block")])
        with faults.inject(sched):
            journal.put("job", "0:64", self.RECORD)  # survives: 1 rewrite
        delta = telemetry.delta(before)
        assert delta.get("storage_fsync_failures", 0) == 1
        assert delta.get("storage_unavailable", 0) == 0
        replayed = journal_lib.BlockJournal(str(tmp_path)).get("job",
                                                               "0:64")
        assert np.array_equal(replayed.ids, self.RECORD.ids)

    def test_persistent_fsync_failure_fails_closed(self, tmp_path):
        journal = journal_lib.BlockJournal(str(tmp_path))
        before = telemetry.snapshot()
        sched = faults.FaultSchedule(
            [faults.Fault("fsync_failure", point="block", times=2)])
        with faults.inject(sched):
            with pytest.raises(journal_lib.StorageUnavailableError,
                               match="stayed sick"):
                journal.put("job", "0:64", self.RECORD)
        delta = telemetry.delta(before)
        assert delta.get("storage_fsync_failures", 0) == 2
        assert delta.get("storage_unavailable", 0) == 1
        assert not [n for n in os.listdir(tmp_path)
                    if n.endswith(".tmp")]
        assert journal_lib.BlockJournal(str(tmp_path)).get(
            "job", "0:64") is None

    def test_eio_read_quarantines_never_replays(self, tmp_path):
        journal_lib.BlockJournal(str(tmp_path)).put("job", "0:64",
                                                    self.RECORD)
        before = telemetry.snapshot()
        sched = faults.FaultSchedule(
            [faults.Fault("io_error", point="block")])
        with faults.inject(sched):
            # A FRESH instance reads from disk (the in-memory cache of
            # the writer never touches the read seam).
            got = journal_lib.BlockJournal(str(tmp_path)).get("job",
                                                              "0:64")
        assert got is None
        delta = telemetry.delta(before)
        assert delta.get("storage_io_errors", 0) == 1
        assert delta.get("journal_quarantined", 0) == 1
        names = os.listdir(tmp_path)
        assert any(n.endswith(".corrupt") for n in names)
        assert not any(n.endswith(".npz") for n in names)


class TestStorageFaultsLedgerSeam:
    """The service converts a sick ledger store into a typed shed —
    reservation released, zero odometer records, worker alive."""

    @pytest.mark.hard_timeout(120)
    def test_disk_full_at_charge_sheds_then_recovers(self, tmp_path):
        ledger_dir = str(tmp_path / "ledger")
        service = DPAggregationService(pipeline_backend.TPUBackend(),
                                       ledger_dir, max_concurrent_jobs=1)
        try:
            before = telemetry.snapshot()
            sched = faults.FaultSchedule(
                [faults.Fault("disk_full", point="odometer")])
            with faults.inject(sched, scope="process"):
                handle = service.submit("acme", _small_spec(), _ROWS)
                assert handle.wait(60)
            assert handle.status == JobStatus.SHED
            error = handle.exception(timeout=0)
            assert isinstance(error, AdmissionRejectedError)
            assert error.retry_after_s is not None
            assert handle.spent_epsilon is None
            delta = telemetry.delta(before)
            assert delta.get("service_jobs_shed", 0) == 1
            assert delta.get("storage_unavailable", 0) == 1
            # The store recovers; the SAME logical work resubmits and
            # lands — and the disk trail holds exactly the one
            # completed job's spend (the shed charged nothing).
            retry = service.submit("acme", _small_spec(), _ROWS)
            assert retry.wait(60) and retry.status == JobStatus.DONE
            drill_lib.audit_disk(
                ledger_dir,
                {"j": {"job_id": retry.job_id, "tenant_id": "acme",
                       "spent_epsilon": retry.spent_epsilon}})
        finally:
            service.drain()

    @pytest.mark.hard_timeout(120)
    def test_fsync_exhaustion_at_charge_sheds_cleanly(self, tmp_path):
        ledger_dir = str(tmp_path / "ledger")
        service = DPAggregationService(pipeline_backend.TPUBackend(),
                                       ledger_dir, max_concurrent_jobs=1)
        try:
            sched = faults.FaultSchedule(
                [faults.Fault("fsync_failure", point="odometer",
                              times=2)])
            with faults.inject(sched, scope="process"):
                handle = service.submit("acme", _small_spec(), _ROWS)
                assert handle.wait(60)
            assert handle.status == JobStatus.SHED
            # Zero odometer records for the tenant: a fresh submit is
            # the FIRST charge the disk ever sees.
            good = service.submit("acme", _small_spec(), _ROWS)
            assert good.wait(60) and good.status == JobStatus.DONE
            spend = drill_lib.audit_disk(
                ledger_dir,
                {"j": {"job_id": good.job_id, "tenant_id": "acme",
                       "spent_epsilon": good.spent_epsilon}})
            assert spend["acme"] == good.spent_epsilon
        finally:
            service.drain()


class TestDeadlineAndCancel:

    @pytest.mark.hard_timeout(120)
    def test_expired_deadline_settles_cancelled_charges_nothing(
            self, tmp_path):
        ledger_dir = str(tmp_path / "ledger")
        service = DPAggregationService(pipeline_backend.TPUBackend(),
                                       ledger_dir, max_concurrent_jobs=1)
        try:
            handle = service.submit("acme", _small_spec(), _ROWS,
                                    deadline_s=1e-6)
            assert handle.wait(60)
            assert handle.status == JobStatus.CANCELLED
            error = handle.exception(timeout=0)
            assert isinstance(error, JobCancelledError)
            assert error.reason == "deadline"
            with pytest.raises(JobCancelledError):
                handle.result(timeout=0)
            assert handle.spent_epsilon is None
            # Nothing charged: the tenant's next job is the ledger's
            # first and only record.
            good = service.submit("acme", _small_spec(), _ROWS)
            assert good.wait(60) and good.status == JobStatus.DONE
            drill_lib.audit_disk(
                ledger_dir,
                {"j": {"job_id": good.job_id, "tenant_id": "acme",
                       "spent_epsilon": good.spent_epsilon}})
        finally:
            service.drain()

    @pytest.mark.hard_timeout(120)
    def test_cancel_settles_cancelled_with_typed_error(self, tmp_path):
        service = DPAggregationService(pipeline_backend.TPUBackend(),
                                       str(tmp_path / "ledger"),
                                       max_concurrent_jobs=1)
        try:
            handle = service.submit("acme", _small_spec(), _ROWS)
            requested = handle.cancel()
            assert handle.wait(60)
            if requested and handle.status == JobStatus.CANCELLED:
                error = handle.exception(timeout=0)
                assert isinstance(error, JobCancelledError)
                assert error.reason == "cancelled"
                assert handle.spent_epsilon is None
            else:
                # The job won the race and finished first — then
                # cancel() must have reported there was nothing to do.
                assert handle.status == JobStatus.DONE
                assert not handle.cancel()
        finally:
            service.drain()

    @pytest.mark.hard_timeout(120)
    def test_cancel_after_done_returns_false(self, tmp_path):
        service = DPAggregationService(pipeline_backend.TPUBackend(),
                                       str(tmp_path / "ledger"))
        try:
            handle = service.submit("acme", _small_spec(), _ROWS)
            assert handle.wait(60) and handle.status == JobStatus.DONE
            assert handle.cancel() is False
            assert handle.status == JobStatus.DONE  # unchanged
        finally:
            service.drain()

    def test_counters_track_cancellations(self, tmp_path):
        before = telemetry.snapshot()
        service = DPAggregationService(pipeline_backend.TPUBackend(),
                                       str(tmp_path / "ledger"),
                                       max_concurrent_jobs=1)
        try:
            handle = service.submit("acme", _small_spec(), _ROWS,
                                    deadline_s=1e-6)
            assert handle.wait(60)
            assert handle.status == JobStatus.CANCELLED
        finally:
            service.drain()
        delta = telemetry.delta(before)
        assert delta.get("service_jobs_cancelled", 0) == 1
        assert service.stats()["jobs_cancelled"] >= 1


class TestRetryBudget:

    def test_exhaustion_is_typed_and_counted(self):
        policy = retry_lib.RetryPolicy(max_retries=10, base_delay=0.0,
                                       max_delay=0.0)
        sched = faults.FaultSchedule([faults.Fault("dispatch", times=5)])
        before = telemetry.snapshot()
        with faults.inject(sched):
            with retry_lib.retry_budget_scope(2):
                with pytest.raises(
                        retry_lib.RetryBudgetExhaustedError):
                    retry_lib.retry_call(lambda: "ok", policy,
                                         sleep=lambda _: None)
        delta = telemetry.delta(before)
        assert delta.get("retry_budget_exhausted", 0) == 1
        # Per-operation retries stayed within max_retries: the BUDGET
        # stopped the job, not the per-op cap.
        assert delta.get("block_retries", 0) == 2

    def test_budget_none_is_unlimited(self):
        policy = retry_lib.RetryPolicy(max_retries=10, base_delay=0.0,
                                       max_delay=0.0)
        sched = faults.FaultSchedule([faults.Fault("dispatch", times=4)])
        with faults.inject(sched):
            with retry_lib.retry_budget_scope(None):
                assert retry_lib.retry_call(lambda: "ok", policy,
                                            sleep=lambda _: None) == "ok"

    def test_budget_scope_validates(self):
        with pytest.raises(ValueError, match="non-negative"):
            with retry_lib.retry_budget_scope(-1):
                pass

    @pytest.mark.hard_timeout(120)
    def test_driver_exhausts_budget_typed_then_resumes(self, tmp_path):
        """End-to-end through the entry wrapper: a driver run whose
        max_total_retries is 0 fails TYPED on the first transient
        fault; lifting the cap over the same journal completes
        bit-identically to the fault-free run."""
        mesh = make_mesh(n_devices=2)
        key = jax.random.PRNGKey(5)
        journal = journal_lib.BlockJournal(str(tmp_path))
        want = _blocked_agg_runner(mesh, key)
        strict = retry_lib.RetryPolicy(max_retries=3, base_delay=0.0,
                                       max_delay=0.0,
                                       max_total_retries=0)
        sched = faults.FaultSchedule([faults.Fault("dispatch", block=1)])
        with faults.inject(sched):
            with pytest.raises(retry_lib.RetryBudgetExhaustedError):
                _blocked_agg_runner(mesh, key, journal=journal,
                                    retry=strict)
        relaxed = retry_lib.RetryPolicy(max_retries=3, base_delay=0.0,
                                        max_delay=0.0,
                                        max_total_retries=8)
        kept, out = _blocked_agg_runner(mesh, key, journal=journal,
                                        retry=relaxed)
        assert np.array_equal(kept, want[0])
        assert np.array_equal(out, want[1])
