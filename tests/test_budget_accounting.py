"""Tests for budget accounting (naive + PLD) and the native PLD library.

Modeled on /root/reference/tests/budget_accounting_test.py patterns: split
proportions, scope normalization, restriction enforcement, PLD binary search.
"""

import math

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.accounting import pld as pldlib
from pipelinedp_tpu.aggregate_params import MechanismType


class TestMechanismSpec:

    def test_lazy_access_raises(self):
        spec = pdp.MechanismSpec(mechanism_type=MechanismType.LAPLACE)
        with pytest.raises(AssertionError):
            _ = spec.eps
        with pytest.raises(AssertionError):
            _ = spec.noise_standard_deviation

    def test_set_and_get(self):
        spec = pdp.MechanismSpec(mechanism_type=MechanismType.GAUSSIAN)
        spec.set_eps_delta(0.5, 1e-8)
        assert spec.eps == 0.5
        assert spec.delta == 1e-8
        assert spec.use_delta()

    def test_laplace_does_not_use_delta(self):
        spec = pdp.MechanismSpec(mechanism_type=MechanismType.LAPLACE)
        assert not spec.use_delta()


class TestNaiveBudgetAccountant:

    def test_equal_split_laplace(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        s1 = acc.request_budget(MechanismType.LAPLACE)
        s2 = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        assert s1.eps == pytest.approx(0.5)
        assert s2.eps == pytest.approx(0.5)
        assert s1.delta == 0

    def test_weighted_split(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1, total_delta=1e-6)
        s1 = acc.request_budget(MechanismType.LAPLACE, weight=3)
        s2 = acc.request_budget(MechanismType.GAUSSIAN, weight=1)
        acc.compute_budgets()
        assert s1.eps == pytest.approx(0.75)
        assert s2.eps == pytest.approx(0.25)
        # Only the Gaussian mechanism consumes delta.
        assert s2.delta == pytest.approx(1e-6)

    def test_count_multiplies_weight(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        s1 = acc.request_budget(MechanismType.LAPLACE, count=3)
        s2 = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        assert s1.eps == pytest.approx(0.25)
        assert s2.eps == pytest.approx(0.25)

    def test_gaussian_without_delta_raises(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        with pytest.raises(ValueError, match="Gaussian"):
            acc.request_budget(MechanismType.GAUSSIAN)

    def test_request_after_compute_raises(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        with pytest.raises(Exception, match="after compute_budgets"):
            acc.request_budget(MechanismType.LAPLACE)

    def test_compute_twice_raises(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        with pytest.raises(Exception, match="twice"):
            acc.compute_budgets()

    def test_scope_normalizes_weights(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1, total_delta=0)
        with acc.scope(weight=0.5):
            s1 = acc.request_budget(MechanismType.LAPLACE)
            s2 = acc.request_budget(MechanismType.LAPLACE)
        with acc.scope(weight=0.5):
            s3 = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        assert s1.eps == pytest.approx(0.25)
        assert s2.eps == pytest.approx(0.25)
        assert s3.eps == pytest.approx(0.5)

    def test_num_aggregations_enforced(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1,
                                        total_delta=0,
                                        num_aggregations=2)
        acc._compute_budget_for_aggregation(1)
        acc.request_budget(MechanismType.LAPLACE)
        with pytest.raises(ValueError, match="num_aggregations"):
            acc.compute_budgets()

    def test_num_aggregations_and_weights_conflict(self):
        with pytest.raises(ValueError):
            pdp.NaiveBudgetAccountant(total_epsilon=1,
                                      total_delta=0,
                                      num_aggregations=2,
                                      aggregation_weights=[1, 2])

    def test_aggregation_weights_split_and_enforcement(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=0,
                                        aggregation_weights=[1, 3])
        with acc.scope(weight=1):
            s1 = acc.request_budget(MechanismType.LAPLACE)
        acc._compute_budget_for_aggregation(1)
        with acc.scope(weight=3):
            s2 = acc.request_budget(MechanismType.LAPLACE)
        acc._compute_budget_for_aggregation(3)
        acc.compute_budgets()
        # eps split proportionally to declared aggregation weights.
        assert s1.eps == pytest.approx(0.25)
        assert s2.eps == pytest.approx(0.75)

    def test_aggregation_weights_count_mismatch_raises(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=0,
                                        aggregation_weights=[1, 3])
        with acc.scope(weight=1):
            acc.request_budget(MechanismType.LAPLACE)
        acc._compute_budget_for_aggregation(1)
        with pytest.raises(ValueError, match="aggregation_weights"):
            acc.compute_budgets()

    def test_aggregation_weights_value_mismatch_raises(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=0,
                                        aggregation_weights=[1, 3])
        with acc.scope(weight=1):
            acc.request_budget(MechanismType.LAPLACE)
        acc._compute_budget_for_aggregation(1)
        with acc.scope(weight=2):  # declared 3, actual 2
            acc.request_budget(MechanismType.LAPLACE)
        acc._compute_budget_for_aggregation(2)
        with pytest.raises(ValueError):
            acc.compute_budgets()

    def test_num_aggregations_requires_unit_weights(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                        total_delta=0,
                                        num_aggregations=1)
        with acc.scope(weight=2):
            acc.request_budget(MechanismType.LAPLACE)
        acc._compute_budget_for_aggregation(2)
        with pytest.raises(ValueError, match="weights have to be 1"):
            acc.compute_budgets()


class TestPld:

    def test_gaussian_epsilon_matches_analytic_shape(self):
        # For sigma=2, delta=1e-6: epsilon from PLD must be finite, positive
        # and close to the analytic Gaussian mechanism's calibration.
        pld = pldlib.from_gaussian_mechanism(2.0,
                                             value_discretization_interval=1e-3)
        eps = pld.get_epsilon_for_delta(1e-6)
        assert 0 < eps < 10
        # More noise -> smaller epsilon.
        pld2 = pldlib.from_gaussian_mechanism(
            4.0, value_discretization_interval=1e-3)
        assert pld2.get_epsilon_for_delta(1e-6) < eps

    def test_laplace_pure_dp(self):
        # Laplace(b) is (1/b, 0)-DP: epsilon at delta=0 is 1/b (up to the
        # pessimistic discretization error).
        b = 2.0
        pld = pldlib.from_laplace_mechanism(b,
                                            value_discretization_interval=1e-4)
        eps = pld.get_epsilon_for_delta(0)
        assert eps == pytest.approx(1 / b, abs=1e-3)

    def test_composition_additivity_upper_bound(self):
        # eps of the composition is between the single-mechanism eps and the
        # naive sum of epsilons.
        pld = pldlib.from_laplace_mechanism(1.0,
                                            value_discretization_interval=1e-4)
        composed = pld.compose(pld)
        eps1 = pld.get_epsilon_for_delta(1e-9)
        eps2 = composed.get_epsilon_for_delta(1e-9)
        assert eps1 < eps2 <= 2 * eps1 + 1e-3

    def test_self_compose_matches_compose(self):
        pld = pldlib.from_gaussian_mechanism(3.0,
                                             value_discretization_interval=1e-3)
        a = pld.compose(pld).compose(pld)
        b = pld.self_compose(3)
        assert a.get_epsilon_for_delta(1e-6) == pytest.approx(
            b.get_epsilon_for_delta(1e-6), rel=1e-6)

    def test_delta_monotone_in_epsilon(self):
        pld = pldlib.from_gaussian_mechanism(1.0,
                                             value_discretization_interval=1e-3)
        deltas = [pld.get_delta_for_epsilon(e) for e in (0.0, 0.5, 1.0, 2.0)]
        assert all(d1 >= d2 for d1, d2 in zip(deltas, deltas[1:]))

    def test_from_privacy_parameters(self):
        pld = pldlib.from_privacy_parameters(
            1.0, 1e-6, value_discretization_interval=1e-4)
        eps = pld.get_epsilon_for_delta(1e-6)
        assert eps == pytest.approx(1.0, abs=1e-3)


class TestPldGoldenValues:
    """Cross-validation of the native PLD against independent references.

    dp_accounting (the reference's PLD library) is not installable here, so
    the golden values are derived from methods independent of the FFT/
    discretization pipeline under test:

      * Gaussian, any k: k-fold composition of Gaussian mechanisms is
        EXACTLY the Gaussian mechanism with sigma/sqrt(k) (the privacy loss
        is N(mu, 2mu) with mu additive under composition), and its
        delta(eps) is the Balle-Wang analytic formula
            delta = Phi(1/(2s) - eps*s) - e^eps * Phi(-1/(2s) - eps*s).
      * Laplace, k=1: hockey-stick integral evaluated with scipy.quad.
      * Laplace, k=2: exact atom/continuous decomposition of the loss
        convolution (atoms at +-1/b, interior density e^{-(1-bl)/(2b)}/4),
        integrated with scipy quad/dblquad.
      * Generic (eps0, delta0): three-point loss distribution closed form
            delta(eps) = delta0 + (1-delta0) e^eps0/(1+e^eps0) (1-e^(eps-eps0)).

    Every pinned value was recomputed with those formulas (see the
    derivations above); the PLD must match within pessimistic tolerance:
    never below the exact value, and within rel_tol above it.
    """

    # (sigma, k, delta) -> exact composed epsilon (Balle-Wang closed form).
    GAUSSIAN_GOLDEN = [
        (1.0, 1, 1e-5, 4.377178),
        (2.0, 1, 1e-6, 2.254085),
        (1.0, 10, 1e-5, 17.856587),
        (0.5, 4, 1e-6, 26.356964),
        (3.0, 30, 1e-5, 8.940357),
    ]

    @pytest.mark.parametrize("sigma,k,delta,exact_eps", GAUSSIAN_GOLDEN)
    def test_gaussian_composition_golden(self, sigma, k, delta, exact_eps):
        pld = pldlib.from_gaussian_mechanism(sigma)
        if k > 1:
            pld = pld.self_compose(k)
        eps = pld.get_epsilon_for_delta(delta)
        assert eps >= exact_eps - 1e-5  # pessimistic rounding: never below
        assert eps == pytest.approx(exact_eps, rel=5e-4)

    # (b, k, delta) -> exact composed epsilon (quad integration).
    LAPLACE_GOLDEN = [
        (1.0, 1, 1e-5, 0.999980),
        (0.5, 1, 1e-3, 1.997999),
        (2.0, 1, 1e-6, 0.499998),
        (1.0, 1, 1e-2, 0.979899),
        (1.0, 2, 1e-5, 1.999960),
        (2.0, 2, 1e-6, 0.999996),
    ]

    @pytest.mark.parametrize("b,k,delta,exact_eps", LAPLACE_GOLDEN)
    def test_laplace_golden(self, b, k, delta, exact_eps):
        pld = pldlib.from_laplace_mechanism(b)
        if k > 1:
            pld = pld.self_compose(k)
        eps = pld.get_epsilon_for_delta(delta)
        assert eps >= exact_eps - 1e-5
        assert eps == pytest.approx(exact_eps, rel=1e-4)

    # (eps0, delta0, delta) -> exact epsilon (three-point closed form).
    GENERIC_GOLDEN = [
        (1.0, 1e-6, 1e-4, 0.999865),
        (0.3, 0.0, 1e-3, 0.298258),
    ]

    @pytest.mark.parametrize("eps0,delta0,delta,exact_eps", GENERIC_GOLDEN)
    def test_generic_golden(self, eps0, delta0, delta, exact_eps):
        pld = pldlib.from_privacy_parameters(eps0, delta0)
        eps = pld.get_epsilon_for_delta(delta)
        assert eps >= exact_eps - 1e-5
        assert eps == pytest.approx(exact_eps, rel=1e-4)

    def test_heterogeneous_composition_golden(self):
        # Gaussian(s=2) o Laplace(b=1) o Generic(0.5, 1e-8) at delta=1e-5,
        # pinned from this library at 1e-4 discretization and sanity-bounded
        # by the naive sum of epsilons (upper) and each component (lower).
        pld = (pldlib.from_gaussian_mechanism(2.0).compose(
            pldlib.from_laplace_mechanism(1.0)).compose(
                pldlib.from_privacy_parameters(0.5, 1e-8)))
        eps = pld.get_epsilon_for_delta(1e-5)
        assert eps == pytest.approx(3.355885, rel=1e-3)
        naive_sum = (pldlib.from_gaussian_mechanism(2.0).get_epsilon_for_delta(
            1e-5) + 1.0 + 0.5)
        assert eps < naive_sum

    def test_gaussian_delta_for_epsilon_golden(self):
        # Balle-Wang at sigma=1, eps=1: delta = Phi(-0.5) - e * Phi(-1.5)
        #                                     = 0.12693674 (exact).
        pld = pldlib.from_gaussian_mechanism(1.0)
        assert pld.get_delta_for_epsilon(1.0) == pytest.approx(0.12693674,
                                                               rel=1e-3)


class TestPldIndependentCrossChecks:
    """Cross-validation against implementations NOT sharing code with the
    production PLD pipeline.

    Google's dp_accounting (the reference's library,
    /root/reference/pipeline_dp/budget_accounting.py:579-619) cannot be
    installed in this environment (no package index access), so its golden
    outputs cannot be generated here. These checks substitute two fully
    independent derivations:

      * An RDP (Renyi) accountant bound for composed Gaussians — a different
        accounting formalism entirely. PLD is exact, RDP is an upper bound,
        so eps_PLD <= eps_RDP must hold (and eps_PLD >= the Balle-Wang exact
        value, asserted in TestPldGoldenValues).
      * A from-scratch dense-convolution PLD for composed Laplace mechanisms
        written in ~20 lines of numpy here in the test: the exact loss
        distribution (two atoms + interior density) discretized with ceil
        rounding and composed with np.convolve — no FFT, no shared
        discretization code with accounting/pld.py.
    """

    @pytest.mark.parametrize("sigma,k,delta", [(1.0, 1, 1e-5), (2.0, 4, 1e-6),
                                               (1.0, 16, 1e-5),
                                               (3.0, 30, 1e-5)])
    def test_gaussian_below_rdp_bound(self, sigma, k, delta):
        pld = pldlib.from_gaussian_mechanism(sigma)
        if k > 1:
            pld = pld.self_compose(k)
        eps_pld = pld.get_epsilon_for_delta(delta)
        # RDP of k Gaussians: rdp(alpha) = k * alpha / (2 sigma^2); convert
        # with the improved bound (Balle et al. 2020):
        #   eps = min_a rdp(a) + log1p(-1/a) - log(delta * a) / (a - 1).
        alphas = np.linspace(1.0 + 1e-3, 200.0, 20000)
        rdp = k * alphas / (2.0 * sigma**2)
        eps_rdp = np.min(rdp + np.log1p(-1.0 / alphas) -
                         (np.log(delta) + np.log(alphas)) / (alphas - 1.0))
        assert eps_pld <= eps_rdp + 1e-3

    @staticmethod
    def _laplace_loss_pmf(b: float, grid: float):
        """Pessimistically discretized privacy-loss PMF of Laplace(b),
        sensitivity 1: atoms at +-1/b, interior density e^{-(1-bl)/(2b)}/4."""
        n_bins = int(np.ceil(1.0 / (b * grid)))
        losses = (np.arange(-n_bins, n_bins + 1)) * grid
        pmf = np.zeros_like(losses)
        # Interior mass of bin (l-grid, l] assigned to its UPPER edge (ceil
        # rounding = pessimistic, losses only rounded up).
        edges = np.clip(losses, -1.0 / b, 1.0 / b)
        cdf = lambda l: 0.5 * (np.exp((b * l - 1.0) / (2.0 * b)) - np.exp(
            -1.0 / b))  # integral of interior density from -1/b to l
        pmf[1:] = cdf(edges[1:]) - cdf(edges[:-1])
        pmf[-1] += 0.5  # atom at +1/b: P(x < 0)
        pmf[0] += np.exp(-1.0 / b) / 2.0  # atom at -1/b: P(x > 1)
        return losses, pmf

    @pytest.mark.parametrize("b,k,delta", [(1.0, 4, 1e-5), (0.8, 3, 1e-4),
                                           (2.0, 6, 1e-6)])
    def test_laplace_matches_dense_convolution(self, b, k, delta):
        grid = 1e-4
        losses, pmf = self._laplace_loss_pmf(b, grid)
        composed = pmf
        for _ in range(k - 1):
            composed = np.convolve(composed, pmf)
        n = (len(losses) - 1) // 2
        composed_losses = np.arange(-k * n, k * n + 1) * grid
        # Hockey-stick divergence at eps from the composed PMF.
        eps_grid = np.linspace(0.0, k / b, 4000)
        deltas = np.array([
            np.sum(
                np.where(composed_losses > e,
                         composed * -np.expm1(e - composed_losses), 0.0))
            for e in eps_grid
        ])
        eps_ref = float(np.interp(-delta, -deltas, eps_grid))
        eps_pld = pldlib.from_laplace_mechanism(b).self_compose(
            k).get_epsilon_for_delta(delta)
        # Both are pessimistic discretizations of the same exact object on
        # unrelated grids; they must agree to grid resolution.
        assert eps_pld == pytest.approx(eps_ref, rel=2e-3, abs=2e-3)


class TestPLDBudgetAccountant:

    def test_delta_zero_closed_form(self):
        acc = pdp.PLDBudgetAccountant(total_epsilon=1, total_delta=0)
        s1 = acc.request_budget(MechanismType.LAPLACE)
        s2 = acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        assert acc.minimum_noise_std == pytest.approx(2 * math.sqrt(2))
        assert s1.noise_standard_deviation == pytest.approx(2 * math.sqrt(2))
        assert s2.noise_standard_deviation == pytest.approx(2 * math.sqrt(2))

    def test_binary_search_satisfies_budget(self):
        total_eps, total_delta = 1.0, 1e-6
        acc = pdp.PLDBudgetAccountant(total_epsilon=total_eps,
                                      total_delta=total_delta,
                                      pld_discretization=1e-3)
        acc.request_budget(MechanismType.GAUSSIAN)
        acc.request_budget(MechanismType.GAUSSIAN)
        acc.compute_budgets()
        std = acc.minimum_noise_std
        assert std > 0
        # Verify the composed PLD at the found noise std fits in the budget.
        pld = pldlib.from_gaussian_mechanism(
            std, value_discretization_interval=1e-3).self_compose(2)
        assert pld.get_epsilon_for_delta(total_delta) <= total_eps * 1.01

    def test_pld_beats_naive_for_many_mechanisms(self):
        # PLD composition should allow strictly less noise than naive
        # accounting for >2 Gaussian mechanisms.
        total_eps, total_delta = 1.0, 1e-6
        n = 4
        acc = pdp.PLDBudgetAccountant(total_epsilon=total_eps,
                                      total_delta=total_delta,
                                      pld_discretization=1e-3)
        specs = [acc.request_budget(MechanismType.GAUSSIAN) for _ in range(n)]
        acc.compute_budgets()
        from pipelinedp_tpu import dp_computations
        naive_std = dp_computations.gaussian_sigma(total_eps / n,
                                                   total_delta / n, 1.0)
        assert specs[0].noise_standard_deviation < naive_std

    def test_huge_eps_naive_fallback(self):
        # Beyond the PLD finite-loss cap the accountant splits naively so
        # the huge-eps determinism trick still works; mixed mechanism kinds
        # each get their exact single-mechanism calibration.
        acc = pdp.PLDBudgetAccountant(total_epsilon=1e5, total_delta=1e-6)
        lap = acc.request_budget(MechanismType.LAPLACE)
        gau = acc.request_budget(MechanismType.GAUSSIAN)
        gen = acc.request_budget(MechanismType.GENERIC)
        acc.compute_budgets()
        eps_i = 1e5 / 3
        assert lap.noise_standard_deviation == pytest.approx(
            math.sqrt(2) / eps_i)
        assert gau.noise_standard_deviation < 0.01
        assert gen.eps == pytest.approx(eps_i)
        assert gen.delta == pytest.approx(0.5e-6)

    def test_generic_mechanism_gets_eps_delta(self):
        acc = pdp.PLDBudgetAccountant(total_epsilon=1,
                                      total_delta=1e-6,
                                      pld_discretization=1e-3)
        s = acc.request_budget(MechanismType.GENERIC)
        acc.compute_budgets()
        assert s.eps > 0
        assert s.delta > 0
