"""KS tests on the noise ACTUALLY emitted by the device kernel.

Round-1 gap: distribution tests covered only the host samplers; nothing
checked the noise leaving executor.finalize / the full aggregate_kernel.
Here the residuals of real kernel outputs against the exact aggregates are
tested against the calibrated noise law (reference pattern:
tests/dp_computations_test.py:165-177 — 1M-draw statistical checks).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as scipy_stats

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, executor
from pipelinedp_tpu.aggregate_params import NoiseKind

P = 100_000  # partitions = independent noise draws per run


def _kernel_outputs(noise_kind, stds, metrics=None):
    """Runs the REAL fused kernel over P partitions with one row each
    (value=2.0), so exact count=1 and sum=2 per partition; returns outputs."""
    params = pdp.AggregateParams(
        metrics=metrics or [pdp.Metrics.COUNT, pdp.Metrics.SUM],
        noise_kind=noise_kind,
        max_partitions_contributed=1,
        max_contributions_per_partition=1,
        min_value=0.0,
        max_value=5.0,
        contribution_bounds_already_enforced=True)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    accountant.compute_budgets()
    cfg = executor.make_kernel_config(params, compound, P,
                                      private_selection=False,
                                      selection_params=None)
    min_v, max_v, min_s, max_s, mid = executor.kernel_scalars(params)
    pid = jnp.arange(P, dtype=jnp.int32)
    pk = jnp.arange(P, dtype=jnp.int32)
    values = jnp.full((P,), 2.0)
    valid = jnp.ones((P,), dtype=bool)
    outputs, keep, _ = executor.aggregate_kernel(
        pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
        jnp.asarray(stds, dtype=jnp.float64), jax.random.PRNGKey(42), cfg)
    assert bool(np.asarray(keep).all())
    return {k: np.asarray(v) for k, v in outputs.items()}


class TestKernelNoiseDistribution:

    def test_laplace_count_and_sum_ks(self):
        stds = [3.0, 7.0]  # count, sum noise stds
        out = _kernel_outputs(NoiseKind.LAPLACE, stds)
        for col, exact, std in (("count", 1.0, 3.0), ("sum", 2.0, 7.0)):
            resid = out[col] - exact
            b = std / math.sqrt(2.0)
            ks = scipy_stats.kstest(resid, scipy_stats.laplace(scale=b).cdf)
            # P draws: KS stat threshold ~ 1.95/sqrt(P) at p=0.001.
            assert ks.statistic < 1.95 / math.sqrt(P), (col, ks)

    def test_gaussian_count_and_sum_ks(self):
        stds = [2.5, 5.0]
        out = _kernel_outputs(NoiseKind.GAUSSIAN, stds)
        for col, exact, std in (("count", 1.0, 2.5), ("sum", 2.0, 5.0)):
            resid = out[col] - exact
            ks = scipy_stats.kstest(resid, scipy_stats.norm(scale=std).cdf)
            assert ks.statistic < 1.95 / math.sqrt(P), (col, ks)

    def test_noise_columns_independent(self):
        out = _kernel_outputs(NoiseKind.LAPLACE, [3.0, 3.0])
        r = np.corrcoef(out["count"] - 1.0, out["sum"] - 2.0)[0, 1]
        assert abs(r) < 5.0 / math.sqrt(P)

    def test_noise_across_partitions_independent(self):
        out = _kernel_outputs(NoiseKind.LAPLACE, [3.0, 3.0])
        resid = out["count"] - 1.0
        r = np.corrcoef(resid[:-1], resid[1:])[0, 1]
        assert abs(r) < 5.0 / math.sqrt(P)

    def test_moments_1m_draws(self):
        # Reference-style 1M-draw mean/std check on the emitted noise.
        out1 = _kernel_outputs(NoiseKind.LAPLACE, [4.0, 4.0])
        resid = np.concatenate(
            [out1["count"] - 1.0, out1["sum"] - 2.0])
        n = len(resid)
        assert abs(resid.mean()) < 5 * 4.0 / math.sqrt(n)
        assert resid.std() == pytest.approx(4.0, rel=0.02)

    def test_within_sigma_mass_laplace(self):
        # P(|X| < sigma) for Laplace(std) = 1 - exp(-sqrt(2)) = 0.7569.
        out = _kernel_outputs(NoiseKind.LAPLACE, [4.0, 4.0])
        resid = out["count"] - 1.0
        frac = (np.abs(resid) < 4.0).mean()
        expected = 1 - math.exp(-math.sqrt(2.0))
        assert frac == pytest.approx(expected, abs=4.0 / math.sqrt(P))


class TestSecureKernelNoiseDistribution:

    def _secure_outputs(self, stds, noise_kind, seed=7):
        from pipelinedp_tpu.ops import secure_noise
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=noise_kind,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=5.0,
            contribution_bounds_already_enforced=True)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, accountant)
        accountant.compute_budgets()
        cfg = executor.make_kernel_config(params, compound, P,
                                          private_selection=False,
                                          selection_params=None, secure=True)
        min_v, max_v, min_s, max_s, mid = executor.kernel_scalars(params)
        thr_hi, thr_lo, gran = secure_noise.build_tables(stds, noise_kind)
        tables = (jnp.asarray(thr_hi), jnp.asarray(thr_lo),
                  jnp.asarray(gran))
        pid = jnp.arange(P, dtype=jnp.int32)
        values = jnp.full((P,), 2.0)
        outputs, _, _ = executor.aggregate_kernel(
            pid, pid, values, jnp.ones((P,), dtype=bool), min_v, max_v,
            min_s, max_s, mid, jnp.asarray(stds, dtype=jnp.float64),
            jax.random.PRNGKey(seed), cfg, tables)
        return {k: np.asarray(v) for k, v in outputs.items()}, gran

    def test_secure_kernel_std_and_grid(self):
        stds = [3.0, 6.0]
        out, gran = self._secure_outputs(stds, NoiseKind.LAPLACE)
        for i, (col, exact) in enumerate((("count", 1.0), ("sum", 2.0))):
            resid = out[col] - exact
            assert resid.std() == pytest.approx(stds[i], rel=0.02)
            on_grid = out[col] / gran[i]
            np.testing.assert_allclose(on_grid, np.round(on_grid),
                                       atol=1e-6)

    def test_secure_vs_continuous_ks(self):
        # At fine granularity the discrete Laplace is statistically
        # indistinguishable from continuous Laplace at KS resolution.
        std = 50.0
        out, gran = self._secure_outputs([std, std], NoiseKind.LAPLACE)
        resid = out["count"] - 1.0
        b = std / math.sqrt(2.0)
        ks = scipy_stats.kstest(resid, scipy_stats.laplace(scale=b).cdf)
        # Discretization adds up to ~gran/b to the KS stat.
        assert ks.statistic < 1.95 / math.sqrt(P) + float(gran[0]) / b
