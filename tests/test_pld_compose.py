"""The PLD fast-composition engine and dual-spend admission
(pipelinedp_tpu/accounting/compose.py + the pld.py query fast path).

The contracts under test:

  * **Query fast path** — the suffix-tail-sum ``get_delta_for_epsilon``
    is EXACTLY equivalent (to float64 ulp) to the full-grid mask+sum
    scan it replaced, across Laplace/Gaussian/generic/composed PLDs
    and across the fallback boundaries (huge epsilon, exp-saturated
    loss cells).
  * **Batched composition parity** — the one-shot frequency-domain
    compose matches the sequential pairwise ``compose`` chain within
    1e-9 (acceptance bar; measured slack is orders tighter), matches
    closed-form Gaussian self-composition, and reproduces the pinned
    golden accounting values. The device (jnp.fft) path matches the
    host path within 1e-9 — the host float64 path stays ledger-facing.
  * **Spectrum cache** — hits/misses counted, LRU-bounded, keyed so
    distinct (kind, scale, sensitivity, discretization) never collide.
  * **Evolving-discretization coarsening** — rebucketing conserves
    mass and only ever moves loss UP (pessimistic, sound).
  * **Dual-spend ledger** — the naive sum stays the bit-exact ledger
    of record in BOTH accounting modes; pld mode admits >= 2x the jobs
    on the same lifetime budget at k >= 100 Gaussian jobs; the rebuilt
    spend survives a journal reload.
"""

import math

import numpy as np
import pytest

from pipelinedp_tpu import dp_computations as dpc
from pipelinedp_tpu import input_validators
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.accounting import compose as eng
from pipelinedp_tpu.accounting import pld as pldlib
from pipelinedp_tpu.budget_accounting import PLDBudgetAccountant
from pipelinedp_tpu.runtime import observability as obs
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.runtime.journal import BlockJournal
from pipelinedp_tpu.service.errors import TenantBudgetExceededError
from pipelinedp_tpu.service.ledger import TenantLedger

pytestmark = pytest.mark.pld

# Coarse grids keep every composition in this suite fast; parity and
# equivalence claims are grid-exact, so resolution is not load-bearing.
_D = 1e-3


def _sample_plds():
    """A spread of mechanism PLDs covering every from_* constructor."""
    return [
        pldlib.from_gaussian_mechanism(1.0, _D),
        pldlib.from_gaussian_mechanism(4.0, _D),
        pldlib.from_laplace_mechanism(1.0, _D),
        pldlib.from_laplace_mechanism(0.5, _D),
        pldlib.from_privacy_parameters(0.5, 1e-7, _D),
        pldlib.from_gaussian_mechanism(2.0, _D).compose(
            pldlib.from_laplace_mechanism(1.5, _D)),
    ]


class TestQueryFastPath:
    """get_delta_for_epsilon's suffix-sum path vs the scan it replaced."""

    @pytest.mark.parametrize("idx", range(6))
    def test_fast_equals_scan(self, idx):
        pld = _sample_plds()[idx]
        lo = float(pld.losses[0]) if len(pld.probs) else 0.0
        hi = float(pld.losses[-1]) if len(pld.probs) else 1.0
        grid = np.concatenate([
            np.linspace(lo - 1.0, hi + 1.0, 301),
            pld.losses[:: max(1, len(pld.probs) // 50)],  # exact cell edges
            [0.0, lo, hi],
        ])
        for eps in grid:
            fast = pld.get_delta_for_epsilon(float(eps))
            scan = pld._get_delta_for_epsilon_scan(float(eps))
            assert fast == pytest.approx(scan, abs=1e-12), eps

    def test_huge_epsilon_falls_back_and_agrees(self):
        pld = pldlib.from_laplace_mechanism(1e-4, 1e-2)  # losses ~ 1e4
        for eps in (10999.0, 11001.0, 2e4):
            assert pld.get_delta_for_epsilon(eps) == pytest.approx(
                pld._get_delta_for_epsilon_scan(eps), abs=1e-12)

    def test_epsilon_for_delta_round_trip(self):
        for pld in _sample_plds():
            eps = pld.get_epsilon_for_delta(1e-6)
            # The bisection's answer must actually achieve the delta.
            assert pld.get_delta_for_epsilon(eps) <= 1e-6 + 1e-12

    def test_delta_monotone_nonincreasing(self):
        pld = _sample_plds()[0]
        grid = np.linspace(-2.0, 8.0, 200)
        deltas = [pld.get_delta_for_epsilon(float(e)) for e in grid]
        assert all(a >= b - 1e-12 for a, b in zip(deltas, deltas[1:]))


class TestBatchedComposition:
    """One-shot frequency-domain compose vs the pairwise chain."""

    def test_matches_pairwise_within_1e9(self):
        plds = _sample_plds()[:4]
        counts = [3, 2, 2, 1]
        batched = eng.compose_plds(plds, counts)
        seq = None
        for p, c in zip(plds, counts):
            for _ in range(c):
                seq = p if seq is None else seq.compose(p)
        assert len(batched.probs) == len(seq.probs)
        assert np.max(np.abs(batched.probs - seq.probs)) <= 1e-9
        assert batched.infinity_mass == pytest.approx(seq.infinity_mass,
                                                      abs=1e-9)
        for delta in (1e-4, 1e-6, 1e-8):
            assert batched.get_epsilon_for_delta(delta) == pytest.approx(
                seq.get_epsilon_for_delta(delta), rel=1e-9)

    def test_spectrum_powers_equal_repeated_entries(self):
        one = pldlib.from_gaussian_mechanism(2.0, _D)
        powered = eng.compose_plds([one], [6])
        repeated = eng.compose_plds([one] * 6)
        np.testing.assert_allclose(powered.probs, repeated.probs,
                                   atol=1e-15)

    def test_matches_closed_form_gaussian(self):
        # k-fold Gaussian(sigma) IS Gaussian(sigma/sqrt(k)); both sides
        # go through the discretizer, so agreement is tight but not
        # exact (different grids).
        k, sigma = 16, 4.0
        kfold = eng.compose_plds([pldlib.from_gaussian_mechanism(sigma, _D)],
                                 [k])
        single = pldlib.from_gaussian_mechanism(sigma / math.sqrt(k), _D)
        for delta in (1e-6, 1e-8):
            assert kfold.get_epsilon_for_delta(delta) == pytest.approx(
                single.get_epsilon_for_delta(delta), rel=2e-3)

    def test_device_path_matches_host(self):
        # Documented tolerance: the jnp.fft path is the throughput path
        # and must stay within 1e-9 of the ledger-facing host path
        # (measured slack is ~1e-18 on CPU; the bound leaves room for
        # accelerator FFT reassociation).
        plds = _sample_plds()[:4]
        counts = [2, 3, 1, 2]
        host = eng.compose_plds(plds, counts)
        dev = eng.compose_plds(plds, counts, device=True)
        assert np.max(np.abs(host.probs - dev.probs)) <= 1e-9
        assert dev.get_epsilon_for_delta(1e-6) == pytest.approx(
            host.get_epsilon_for_delta(1e-6), abs=1e-9)

    def test_infinity_mass_composes(self):
        p = pldlib.from_privacy_parameters(0.3, 1e-3, _D)
        composed = eng.compose_plds([p], [10])
        assert composed.infinity_mass == pytest.approx(
            -math.expm1(10 * math.log1p(-p.infinity_mass)), rel=1e-12)

    def test_rejects_bad_inputs(self):
        one = pldlib.from_gaussian_mechanism(1.0, _D)
        with pytest.raises(ValueError, match="at least one"):
            eng.compose_plds([])
        with pytest.raises(ValueError, match="counts"):
            eng.compose_plds([one], [0])
        with pytest.raises(ValueError, match="counts"):
            eng.compose_plds([one], [1, 2])
        other = pldlib.from_gaussian_mechanism(1.0, 2 * _D)
        with pytest.raises(ValueError, match="intervals"):
            eng.compose_plds([one, other])


class TestGoldenValues:
    """The batched engine against pinned reference epsilons (the same
    independently-derived closed-form/quadrature values the pairwise
    golden suite pins — see test_budget_accounting.py for the
    derivations)."""

    GOLDEN = [
        ("gaussian", 1.0, 1, 1e-5, 4.377178),
        ("gaussian", 3.0, 30, 1e-5, 8.940357),
        ("laplace", 1.0, 2, 1e-5, 1.999960),
    ]

    @pytest.mark.parametrize("kind,scale,k,delta,exact_eps", GOLDEN)
    def test_batched_golden(self, kind, scale, k, delta, exact_eps):
        build = (pldlib.from_gaussian_mechanism if kind == "gaussian"
                 else pldlib.from_laplace_mechanism)
        composed = eng.compose_plds([build(scale)], [k])
        eps = composed.get_epsilon_for_delta(delta)
        assert eps >= exact_eps - 1e-5  # pessimistic: never below exact
        assert eps == pytest.approx(exact_eps, rel=5e-4)


class TestCoarsening:
    """Evolving-discretization rebucketing: sound and mass-conserving."""

    def test_mass_conserved_and_pessimistic(self):
        pld = pldlib.from_gaussian_mechanism(1.0, _D)
        coarse = eng.coarsen_pld(pld, 4)
        assert coarse.interval == pytest.approx(4 * _D)
        assert np.sum(coarse.probs) == pytest.approx(np.sum(pld.probs),
                                                     abs=1e-12)
        # Ceiling rebucketing only moves loss UP, so delta at any eps
        # can only grow (a sound upper bound can loosen, never tighten).
        for eps in (0.0, 1.0, 3.0):
            assert (coarse.get_delta_for_epsilon(eps) >=
                    pld.get_delta_for_epsilon(eps) - 1e-12)

    def test_max_grid_triggers_coarsening(self):
        pld = pldlib.from_gaussian_mechanism(1.0, _D)
        small = eng.compose_plds([pld], [64], max_grid=1 << 12)
        big = eng.compose_plds([pld], [64])
        assert len(small.probs) <= 1 << 12
        assert small.interval > big.interval
        # Still a sound bound: coarse epsilon >= fine epsilon.
        assert (small.get_epsilon_for_delta(1e-6) >=
                big.get_epsilon_for_delta(1e-6) - 1e-9)


class TestSpectrumCache:

    def test_hits_misses_and_reuse(self):
        cache = eng.SpectrumCache()
        before = telemetry.snapshot()
        a = cache.get("MechanismType.GAUSSIAN", 2.0, 1.0, _D)
        b = cache.get("MechanismType.GAUSSIAN", 2.0, 1.0, _D)
        assert a is b
        c = cache.get("MechanismType.GAUSSIAN", 3.0, 1.0, _D)
        assert c is not a
        diff = telemetry.delta(before)
        assert diff.get("pld_cache_hits", 0) == 1
        assert diff.get("pld_cache_misses", 0) == 2

    def test_distinct_keys_never_collide(self):
        cache = eng.SpectrumCache()
        variants = [
            ("MechanismType.GAUSSIAN", 2.0, 1.0, _D),
            ("MechanismType.LAPLACE", 2.0, 1.0, _D),
            ("MechanismType.GAUSSIAN", 2.0, 1.0, 2 * _D),
            ("MechanismType.GAUSSIAN", 2.0, 2.0, _D),
        ]
        built = [cache.get(*v) for v in variants]
        assert len(cache) == len(variants)
        assert len({id(p) for p in built}) == len(variants)

    def test_lru_eviction_bounds_entries(self):
        cache = eng.SpectrumCache(max_entries=3)
        for scale in (1.0, 2.0, 3.0, 4.0, 5.0):
            cache.get("MechanismType.LAPLACE", scale, 1.0, 1e-2)
        assert len(cache) == 3

    def test_generic_kind_builds_dominating_pld(self):
        cache = eng.SpectrumCache()
        pld = cache.get("job_failed", (0.5, 1e-6), 1.0, _D)
        # The three-point PLD of an (eps0, delta0) guarantee: its
        # epsilon at delta0 is eps0 (up to grid rounding above).
        assert pld.get_epsilon_for_delta(1e-6) == pytest.approx(0.5,
                                                                rel=1e-2)


class TestAccountantRewire:
    """PLDBudgetAccountant through the cache + batched engine."""

    def test_budget_still_satisfied(self):
        accountant = PLDBudgetAccountant(1.0, 1e-6,
                                         pld_discretization=1e-3)
        specs = [accountant.request_budget(MechanismType.GAUSSIAN)
                 for _ in range(4)]
        accountant.compute_budgets()
        composed = accountant._compose_distributions(
            accountant.minimum_noise_std)
        assert composed.get_epsilon_for_delta(1e-6) <= 1.0 + 1e-6
        assert all(s.noise_standard_deviation ==
                   specs[0].noise_standard_deviation for s in specs)

    def test_rejects_bad_discretization(self):
        with pytest.raises(ValueError, match="pld_discretization"):
            PLDBudgetAccountant(1.0, 1e-6, pld_discretization=-1e-4)
        with pytest.raises(ValueError, match="pld_discretization"):
            PLDBudgetAccountant(1.0, 1e-6, pld_discretization=0.9)


def _gaussian_record(eps, delta):
    std = dpc.gaussian_sigma(eps, delta, 1.0)
    return {
        "seq": 0, "job_id": None, "metric": "count",
        "mechanism_kind": "MechanismType.GAUSSIAN", "weight": 1.0,
        "sensitivity": 1.0, "count": 1, "process_index": 0,
        "eps": eps, "delta": delta, "noise_std": std,
    }


def _admit_until_refused(ledger, eps, delta, cap):
    n = 0
    while n < cap:
        job = f"{ledger.tenant_id}--j{n + 1}"
        try:
            ledger.reserve(job, eps)
        except TenantBudgetExceededError:
            break
        ledger.charge(job, [_gaussian_record(eps, delta)])
        n += 1
    return n


class TestDualSpendLedger:

    def test_naive_mode_unchanged_and_bit_exact(self):
        led = TenantLedger("acct-a", 1.0, BlockJournal(None))
        n = _admit_until_refused(led, 0.1, 1e-8, cap=50)
        assert n == 10
        expected = 0.0
        for _ in range(n):
            expected += 0.1  # the same left-to-right float64 fold
        assert led.spent_epsilon() == expected  # bit-exact, not approx
        snap = led.snapshot()
        assert snap["accounting_mode"] == "naive"
        assert snap["admission_spent_epsilon"] == snap["spent_epsilon"]

    def test_pld_mode_capacity_multiplier(self):
        """The acceptance bar: >= 2x jobs admitted on one fixed budget
        at k >= 100 Gaussian jobs, with the naive ledger-of-record sum
        still bit-exact."""
        eps, delta, budget = 0.1, 1e-8, 5.0
        naive_led = TenantLedger("acct-n", budget, BlockJournal(None),
                                 pld_discretization=_D)
        n_naive = _admit_until_refused(naive_led, eps, delta, cap=200)
        assert n_naive == 50

        pld_led = TenantLedger("acct-p", budget, BlockJournal(None),
                               accounting_mode="pld",
                               pld_discretization=_D)
        cap = max(2 * n_naive, 100) + 10
        n_pld = _admit_until_refused(pld_led, eps, delta, cap=cap)
        assert n_pld >= max(2 * n_naive, 100)
        # The ledger of record is untouched by the admission mode.
        expected = 0.0
        for _ in range(n_pld):
            expected += eps
        assert pld_led.spent_epsilon() == expected
        snap = pld_led.snapshot()
        assert snap["accounting_mode"] == "pld"
        assert snap["pld_spent_epsilon"] < snap["spent_epsilon"]
        assert snap["admission_spent_epsilon"] <= snap["spent_epsilon"]
        # The saved-epsilon gauge reflects the last rebuild.
        saved = telemetry.gauge_snapshot().get(
            "tenant_pld_epsilon_saved", {}).get("acct-p")
        assert saved == pytest.approx(
            snap["spent_epsilon"] - snap["pld_spent_epsilon"], abs=1e-9)

    def test_pld_admission_never_looser_than_budget(self):
        # Even in pld mode a request that exceeds the remaining budget
        # under the COMPOSED spend is refused.
        led = TenantLedger("acct-r", 0.5, BlockJournal(None),
                           accounting_mode="pld", pld_discretization=_D)
        led.reserve("acct-r--j1", 0.4)
        with pytest.raises(TenantBudgetExceededError):
            led.reserve("acct-r--j2", 0.2)

    def test_pld_spend_survives_reload(self, tmp_path):
        journal = BlockJournal(str(tmp_path))
        led = TenantLedger("acct-d", 2.0, journal, accounting_mode="pld",
                           pld_discretization=_D)
        for i in range(5):
            job = f"acct-d--j{i + 1}"
            led.reserve(job, 0.1)
            led.charge(job, [_gaussian_record(0.1, 1e-8)])
        reloaded = TenantLedger("acct-d", 2.0, BlockJournal(str(tmp_path)),
                                accounting_mode="pld",
                                pld_discretization=_D)
        assert reloaded.spent_epsilon() == led.spent_epsilon()
        assert reloaded.pld_spent_epsilon() == pytest.approx(
            led.pld_spent_epsilon(), abs=1e-12)

    def test_pending_records_skipped_like_naive(self):
        rec = _gaussian_record(0.1, 1e-8)
        pending = dict(rec, eps=None, delta=None, noise_std=None)
        eps, _ = eng.composed_epsilon_from_records([rec, pending, rec],
                                                   discretization=_D)
        only = eng.composed_epsilon_from_records([rec, rec],
                                                 discretization=_D)[0]
        assert eps == pytest.approx(only, abs=1e-12)

    def test_rejects_bad_knobs(self):
        with pytest.raises(ValueError, match="tenant_accounting"):
            TenantLedger("acct-x", 1.0, BlockJournal(None),
                         accounting_mode="exact")
        with pytest.raises(ValueError, match="pld_discretization"):
            TenantLedger("acct-x", 1.0, BlockJournal(None),
                         pld_discretization=float("nan"))


class TestOdometerNoiseStd:

    def test_round_trips_through_journal(self, tmp_path):
        journal = BlockJournal(str(tmp_path))
        rows = [_gaussian_record(0.2, 1e-7)]
        obs.persist_odometer(journal, "acct-o", records=rows)
        loaded = obs.load_odometer(journal, "acct-o")
        assert loaded[0]["noise_std"] == rows[0]["noise_std"]

    def test_legacy_trail_without_column_loads_none(self, tmp_path):
        from pipelinedp_tpu.runtime.journal import BlockRecord
        journal = BlockJournal(str(tmp_path))
        journal.put("acct-o", obs.ODOMETER_KEY, BlockRecord(
            ids=np.asarray([0], dtype=np.int64),
            outputs={
                "eps": np.asarray([0.1]), "delta": np.asarray([1e-8]),
                "weight": np.asarray([1.0]),
                "sensitivity": np.asarray([1.0]),
                "count": np.asarray([1], dtype=np.int64),
                "process_index": np.asarray([0], dtype=np.int32),
                "job_id": np.asarray([""], dtype=np.str_),
                "metric": np.asarray([""], dtype=np.str_),
                "mechanism_kind": np.asarray(["MechanismType.GAUSSIAN"],
                                             dtype=np.str_),
            }))
        loaded = obs.load_odometer(journal, "acct-o")
        assert loaded[0]["noise_std"] is None
        # And the spend rebuild still works off the (eps, delta) share.
        eps, _ = eng.composed_epsilon_from_records(loaded,
                                                   discretization=_D)
        assert math.isfinite(eps) and eps > 0


class TestMetricsExport:

    def test_pld_metrics_render_and_parse_strict(self):
        eng.compose_plds([pldlib.from_gaussian_mechanism(1.0, _D)], [2])
        telemetry.set_gauge("tenant_pld_epsilon_saved", 0.25,
                            job_id="acct-m")
        text = obs.render_prometheus()
        names = ("pdp_pld_compositions", "pdp_pld_cache_hits",
                 "pdp_pld_cache_misses", "pdp_tenant_pld_epsilon_saved")
        for name in names:
            assert any(line.startswith(name) for line in text.splitlines())
        parsed = obs.parse_prometheus(text)  # strict grammar must hold
        assert parsed["pdp_pld_compositions"]["type"] == "counter"


class TestValidators:

    @pytest.mark.parametrize("bad", ["exact", "", None, 1, True])
    def test_tenant_accounting_rejects(self, bad):
        with pytest.raises(ValueError, match="tenant_accounting"):
            input_validators.validate_tenant_accounting(bad, "t")

    @pytest.mark.parametrize("ok", ["naive", "pld"])
    def test_tenant_accounting_accepts(self, ok):
        input_validators.validate_tenant_accounting(ok, "t")

    @pytest.mark.parametrize(
        "bad", [0.0, -1e-4, 1e-8, 0.6, float("nan"), float("inf"), True,
                "fine"])
    def test_pld_discretization_rejects(self, bad):
        with pytest.raises(ValueError, match="pld_discretization"):
            input_validators.validate_pld_discretization(bad, "t")

    @pytest.mark.parametrize("ok", [1e-7, 1e-4, 0.5])
    def test_pld_discretization_accepts(self, ok):
        input_validators.validate_pld_discretization(ok, "t")
