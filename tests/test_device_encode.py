"""Device-resident hash ingest (encode_mode="hash_device").

Covers the four contracts of device_encode.py + the ingest hash route:

  * code parity — the device sort/unique factorize assigns EXACTLY the
    first-occurrence codes the host encoder assigns, so kernel inputs
    (and with them every DP release under the same noise keys) are
    bit-identical between the two encode modes;
  * collision safety — two raw keys colliding on the primary 64-bit
    hash trip the detector (secondary lane disagrees), increment the
    ``ingest_hash_collisions`` counter, and fall back to the exact host
    encoder bit-identically;
  * deferred decode — partition keys are looked up only at DP-selected
    indices (HashVocab.prefetch), with zero O(rows) host transfers
    under reshard.forbid_row_fetches;
  * end-to-end parity — all four meshed drivers and the engine release
    identical results from both encodings at mesh sizes 1/4/8 and
    pipeline depths 1/8, with equal budget-ledger mechanism counts.
"""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import columnar, device_encode, executor, ingest
from pipelinedp_tpu import input_validators
from pipelinedp_tpu.parallel import reshard
from pipelinedp_tpu.parallel.mesh import make_mesh
from pipelinedp_tpu.runtime import pipeline as rt_pipeline
from pipelinedp_tpu.runtime import telemetry as rt_telemetry

import jax.numpy as jnp


def _stream(n=3000, n_users=250, n_parts=30, seed=5):
    rng = np.random.default_rng(seed)
    pids = np.char.add("u", rng.integers(0, n_users, n).astype(str))
    pks = np.char.add("p", rng.integers(0, n_parts, n).astype(str))
    vals = rng.integers(0, 10, n).astype(np.float64)
    return pids, pks, vals


def _chunks(pids, pks, vals, chunk=500):
    n = len(pids)
    return [(pids[i:i + chunk], pks[i:i + chunk], vals[i:i + chunk])
            for i in range(0, n, chunk)]


def _padded(encoded):
    return tuple(np.asarray(c) for c in executor.pad_rows(encoded))


def _assert_kernel_input_parity(host, dev):
    """Both encodings must feed the fused kernel bit-identical arrays."""
    hp = _padded(host)
    dp = _padded(dev)
    assert hp[0].shape == dp[0].shape
    for h, d, name in zip(hp, dp, ("pid", "pk", "values", "valid")):
        assert np.array_equal(h, d), f"{name} kernel inputs diverged"
    assert host.n_privacy_ids == dev.n_privacy_ids
    assert len(host.partition_vocab) == len(dev.partition_vocab)


# ---------------------------------------------------------------------------
# Host hash
# ---------------------------------------------------------------------------


class TestHashKeyColumn:

    def test_deterministic_and_lane_independent(self):
        raw = np.array(["a", "b", "a", "c"])
        h0 = ingest.hash_key_column(raw)
        h1 = ingest.hash_key_column(raw, lane=1)
        assert np.array_equal(h0, ingest.hash_key_column(raw))
        assert h0.dtype == np.uint64
        assert h0[0] == h0[2] and h1[0] == h1[2]
        assert not np.array_equal(h0, h1)

    def test_sentinel_hash_is_unreachable(self):
        h = ingest.hash_key_column(np.arange(1000))
        assert not (h == np.uint64(device_encode.HASH_SENTINEL)).any()

    def test_numeric_key_identity_matches_host_equality(self):
        # 3 (int) and 3.0 (float) are one key to the host encoder.
        assert ingest.hash_key_column(np.array([3]))[0] == \
            ingest.hash_key_column(np.array([3.0]))[0]
        assert ingest.hash_key_column(np.array([-0.0]))[0] == \
            ingest.hash_key_column(np.array([0.0]))[0]

    def test_nan_keys_share_one_hash(self):
        h = ingest.hash_key_column(
            np.array([float("nan"), np.nan, 1.0]))
        assert h[0] == h[1] and h[0] != h[2]

    def test_mixed_object_int_and_str_do_not_merge(self):
        # pandas hash_array silently stringifies mixed arrays; the
        # gated route must keep int 1 and "1" distinct keys.
        raw = np.empty(2, object)
        raw[0], raw[1] = 1, "1"
        h = ingest.hash_key_column(raw)
        assert h[0] != h[1]

    def test_hash_is_array_width_invariant(self):
        # The same key must hash identically whatever fixed width its
        # chunk's array carries (chunks of differing '<U_' widths are
        # one vocabulary to the host encoder).
        a = np.asarray(["ab", "c"], dtype="<U2")
        b = np.asarray(["ab", "c"], dtype="<U9")
        assert np.array_equal(ingest.hash_key_column(a),
                              ingest.hash_key_column(b))
        assert np.array_equal(ingest.hash_key_column(a, 1),
                              ingest.hash_key_column(b, 1))
        # And character ORDER still matters.
        assert ingest.hash_key_column(np.asarray(["ab"]))[0] != \
            ingest.hash_key_column(np.asarray(["ba"]))[0]

    def test_composite_tuple_keys_stable(self):
        raw = np.empty(3, object)
        raw[0], raw[1], raw[2] = (1, "a"), (1, "a"), (2, "b")
        h = ingest.hash_key_column(raw)
        assert h[0] == h[1] and h[0] != h[2]

    def test_no_pandas_fallback_consistent(self, monkeypatch):
        raw_num = np.arange(50) % 7
        raw_str = np.char.add("k", (np.arange(50) % 9).astype(str))
        monkeypatch.setattr(ingest, "_pd", None)
        for raw in (raw_num, raw_str):
            h = ingest.hash_key_column(raw)
            assert np.array_equal(h, ingest.hash_key_column(raw))
            codes, n = device_encode.factorize_codes(
                jnp.asarray(device_encode.pack_hash_rows(h)))
            ref, uni = columnar.factorize(raw)
            assert int(n) == len(uni)


# ---------------------------------------------------------------------------
# Device factorize kernels
# ---------------------------------------------------------------------------


class TestFactorizeCodes:

    def test_first_occurrence_codes_match_host_factorize(self):
        rng = np.random.default_rng(3)
        raw = rng.integers(0, 97, 800)
        h = ingest.hash_key_column(raw)
        codes, n = device_encode.factorize_codes(
            jnp.asarray(device_encode.pack_hash_rows(h)))
        ref, uniques = columnar.factorize(raw)
        assert np.array_equal(np.asarray(codes), ref)
        assert int(n) == len(uniques)

    def test_sentinel_rows_code_to_minus_one(self):
        h = ingest.hash_key_column(np.array([7, 8, 7]))
        packed = device_encode.pack_hash_rows(h)
        packed = np.concatenate(
            [packed,
             np.full((3, 3), device_encode._U32_MAX, np.uint32)])
        codes, n = device_encode.factorize_codes(jnp.asarray(packed))
        assert np.array_equal(np.asarray(codes), [0, 1, 0, -1, -1, -1])
        assert int(n) == 2

    def test_invalid_rows_keep_vocabulary_slots(self):
        # An invalid (nonfinite-dropped) row's key still claims its
        # first-occurrence slot — codes after it must not shift.
        h = ingest.hash_key_column(np.array(["a", "b", "c", "b"]))
        valid = np.array([True, False, True, True])
        codes, n = device_encode.factorize_codes(
            jnp.asarray(device_encode.pack_hash_rows(h, valid)))
        assert np.array_equal(np.asarray(codes), [0, -1, 2, 1])
        assert int(n) == 3

    @pytest.mark.parametrize("n_devices", [1, 4, 8])
    def test_mesh_factorize_matches_single_device(self, n_devices):
        rng = np.random.default_rng(11)
        raw = rng.integers(0, 61, 512)
        h = device_encode.pack_hash_rows(ingest.hash_key_column(raw))
        mesh = make_mesh(n_devices=n_devices)
        codes, n = device_encode.mesh_factorize_codes(
            mesh, jnp.asarray(h))
        ref, uniques = columnar.factorize(raw)
        assert np.array_equal(np.asarray(codes), ref)
        assert n == len(uniques)


class TestMergeHashUniques:

    def test_dedupe_and_first_positions(self):
        h1 = [np.array([5, 9], np.uint64), np.array([9, 2], np.uint64)]
        h2 = [np.array([50, 90], np.uint64), np.array([90, 20], np.uint64)]
        keys = [np.array(["a", "b"], object), np.array(["b", "c"], object)]
        pos = [np.array([0, 1], np.int64), np.array([3, 2], np.int64)]
        s1, k, n, p = device_encode.merge_hash_uniques(h1, h2, keys, pos)
        assert list(s1) == [2, 5, 9]
        assert list(k) == ["c", "a", "b"]
        assert n == 3
        assert list(p) == [2, 0, 1]

    def test_collision_raises(self):
        h1 = [np.array([5], np.uint64), np.array([5], np.uint64)]
        h2 = [np.array([1], np.uint64), np.array([2], np.uint64)]
        with pytest.raises(device_encode.HashCollisionError,
                           match="primary hash 5"):
            device_encode.merge_hash_uniques(h1, h2)


# ---------------------------------------------------------------------------
# Stream-encode parity
# ---------------------------------------------------------------------------


class TestStreamEncodeParity:

    def test_kernel_inputs_bit_identical(self):
        pids, pks, vals = _stream()
        host = ingest.stream_encode_columns(_chunks(pids, pks, vals))
        dev = ingest.stream_encode_columns(_chunks(pids, pks, vals),
                                           encode_mode="hash_device")
        _assert_kernel_input_parity(host, dev)
        assert list(dev.partition_vocab) == list(host.partition_vocab)

    @pytest.mark.parametrize("threads,depth", [(1, 1), (2, 8)])
    def test_pipelined_hash_encode_identical(self, threads, depth):
        pids, pks, vals = _stream()
        serial = ingest.stream_encode_columns(
            _chunks(pids, pks, vals), encode_mode="hash_device")
        piped = ingest.stream_encode_columns(
            _chunks(pids, pks, vals), encode_mode="hash_device",
            encode_threads=threads, pipeline_depth=depth)
        for a, b in zip(_padded(serial), _padded(piped)):
            assert np.array_equal(a, b)

    def test_nonfinite_drop_keeps_code_alignment(self):
        pids, pks, vals = _stream()
        vals = vals.copy()
        vals[2] = np.nan  # early drop: later codes must not shift
        vals[100] = np.inf
        host = ingest.stream_encode_columns(
            _chunks(pids, pks, vals), nonfinite="drop")
        dev = ingest.stream_encode_columns(
            _chunks(pids, pks, vals), nonfinite="drop",
            encode_mode="hash_device")
        _assert_kernel_input_parity(host, dev)

    def test_public_partitions(self):
        pids, pks, vals = _stream()
        public = [f"p{i}" for i in range(20)]
        host = ingest.stream_encode_columns(
            _chunks(pids, pks, vals), public_partitions=public)
        dev = ingest.stream_encode_columns(
            _chunks(pids, pks, vals), public_partitions=public,
            encode_mode="hash_device")
        _assert_kernel_input_parity(host, dev)
        assert dev.public_encoded and \
            list(dev.partition_vocab) == public

    def test_empty_stream(self):
        enc = ingest.stream_encode_columns([],
                                           encode_mode="hash_device")
        assert enc.n_rows == 0 and len(enc.partition_vocab) == 0
        assert enc.n_privacy_ids == 0

    def test_hash_vocab_decodes_lazily(self):
        pids, pks, vals = _stream()
        host = ingest.stream_encode_columns(_chunks(pids, pks, vals))
        dev = ingest.stream_encode_columns(_chunks(pids, pks, vals),
                                           encode_mode="hash_device")
        vocab = dev.partition_vocab
        ref = list(host.partition_vocab)
        vocab.prefetch([3, 7])
        assert vocab._cache and len(vocab._cache) == 2
        assert vocab[3] == ref[3] and vocab[7] == ref[7]
        # Unprefetched access degrades to one whole-table materialize.
        assert vocab[11] == ref[11]
        assert list(vocab) == ref
        with pytest.raises(IndexError):
            vocab[len(ref)]

    def test_mesh_encode_local_shard(self):
        pids, pks, vals = _stream(n=1600)
        mesh = make_mesh(n_devices=4)
        enc = ingest.encode_local_shard_to_mesh(
            _chunks(pids, pks, vals), mesh, encode_mode="hash_device")
        serial = ingest.stream_encode_columns(_chunks(pids, pks, vals))
        valid = np.asarray(enc.pk) >= 0
        assert valid.sum() == len(pids)
        assert np.array_equal(np.asarray(enc.pid)[valid],
                              np.asarray(serial.pid))
        assert np.array_equal(np.asarray(enc.pk)[valid],
                              np.asarray(serial.pk))
        assert list(enc.partition_vocab) == list(serial.partition_vocab)
        assert enc.n_privacy_ids == serial.n_privacy_ids

    def test_simulated_pod_hash_exchange(self):
        import pickle
        pids, pks, vals = _stream(n=1600)
        n = len(pids)
        half = n // 2
        payloads = {}
        for p, (lo, hi) in enumerate([(0, half), (half, n)]):
            shard = ingest._hash_encode_shard(
                iter(_chunks(pids[lo:hi], pks[lo:hi], vals[lo:hi])),
                None, "error")
            payloads[p] = pickle.dumps(shard.meta)
        mesh = make_mesh(n_devices=4)
        enc0 = ingest.encode_local_shard_to_mesh(
            _chunks(pids[:half], pks[:half], vals[:half]), mesh,
            exchange=lambda payload: [payloads[0], payloads[1]],
            encode_mode="hash_device")
        serial = ingest.stream_encode_columns(_chunks(pids, pks, vals))
        valid = np.asarray(enc0.pk) >= 0
        # Process 0 uploaded its half with GLOBAL codes and the GLOBAL
        # vocabulary (keys first seen on the simulated process 1
        # decode through the exchanged metas).
        assert np.array_equal(np.asarray(enc0.pid)[valid],
                              np.asarray(serial.pid)[:half])
        assert np.array_equal(np.asarray(enc0.pk)[valid],
                              np.asarray(serial.pk)[:half])
        assert list(enc0.partition_vocab) == \
            list(serial.partition_vocab)
        assert enc0.n_privacy_ids == serial.n_privacy_ids


# ---------------------------------------------------------------------------
# Collision safety (the crafted-collision satellite)
# ---------------------------------------------------------------------------


def _collide_keys(monkeypatch, victim="p1", target="p0"):
    """Monkeypatches the PRIMARY hash lane so `victim` collides with
    `target` while the secondary lane still tells them apart — the
    situation the two-lane detector exists for."""
    orig = ingest.hash_key_column_pair

    def colliding(raw):
        h0, h1 = orig(raw)
        arr = columnar._as_key_array(raw)
        h0 = h0.copy()
        h0[arr == victim] = orig(np.asarray([target], object))[0][0]
        return h0, h1

    monkeypatch.setattr(ingest, "hash_key_column_pair", colliding)


class TestCollisionSafety:

    def test_detector_trips_counts_and_falls_back_bit_identically(
            self, monkeypatch):
        pids, pks, vals = _stream()
        host = ingest.stream_encode_columns(_chunks(pids, pks, vals))
        _collide_keys(monkeypatch)
        before = rt_telemetry.snapshot()
        enc = ingest.stream_encode_columns(_chunks(pids, pks, vals),
                                           encode_mode="hash_device")
        assert rt_telemetry.delta(before).get(
            "ingest_hash_collisions", 0) == 1
        # The fallback IS the exact host encoder: bit-identical columns
        # and the identical (eagerly decoded) vocabulary.
        assert np.array_equal(np.asarray(enc.pid), np.asarray(host.pid))
        assert np.array_equal(np.asarray(enc.pk), np.asarray(host.pk))
        assert list(enc.partition_vocab) == list(host.partition_vocab)

    def test_one_shot_iterator_raises_actionably(self, monkeypatch):
        pids, pks, vals = _stream()
        _collide_keys(monkeypatch)
        before = rt_telemetry.snapshot()
        with pytest.raises(device_encode.HashCollisionError,
                           match="one-shot iterator"):
            ingest.stream_encode_columns(
                iter(_chunks(pids, pks, vals)),
                encode_mode="hash_device")
        assert rt_telemetry.delta(before).get(
            "ingest_hash_collisions", 0) == 1

    def test_privacy_id_collision_also_trips(self, monkeypatch):
        pids, pks, vals = _stream()
        _collide_keys(monkeypatch, victim="u1", target="u2")
        before = rt_telemetry.snapshot()
        enc = ingest.stream_encode_columns(_chunks(pids, pks, vals),
                                           encode_mode="hash_device")
        assert rt_telemetry.delta(before).get(
            "ingest_hash_collisions", 0) == 1
        host = ingest.stream_encode_columns(_chunks(pids, pks, vals))
        assert np.array_equal(np.asarray(enc.pid), np.asarray(host.pid))

    def test_pod_path_falls_back_too(self, monkeypatch):
        pids, pks, vals = _stream(n=1200)
        mesh = make_mesh(n_devices=4)
        host = ingest.encode_local_shard_to_mesh(
            _chunks(pids, pks, vals), mesh)
        _collide_keys(monkeypatch)
        before = rt_telemetry.snapshot()
        enc = ingest.encode_local_shard_to_mesh(
            _chunks(pids, pks, vals), mesh, encode_mode="hash_device")
        assert rt_telemetry.delta(before).get(
            "ingest_hash_collisions", 0) == 1
        assert np.array_equal(np.asarray(enc.pid), np.asarray(host.pid))
        assert np.array_equal(np.asarray(enc.pk), np.asarray(host.pk))
        assert list(enc.partition_vocab) == list(host.partition_vocab)


# ---------------------------------------------------------------------------
# Engine + all four meshed drivers
# ---------------------------------------------------------------------------


def _agg_params():
    return pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                        pdp.Metrics.SUM],
                               noise_kind=pdp.NoiseKind.LAPLACE,
                               max_partitions_contributed=4,
                               max_contributions_per_partition=8,
                               min_value=0.0,
                               max_value=9.0)


_EXTRACTORS = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: float(r[2]))


class TestEngineParity:
    """Hash-device == host releases, decoded and order-normalized, for
    the engine over every driver route, with equal ledger counts."""

    def _aggregate(self, mode, mesh=None, depth=None, **backend_kw):
        pids, pks, vals = _stream()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e6,
                                        total_delta=1e-5)
        backend = pdp.TPUBackend(noise_seed=29, mesh=mesh,
                                 encode_mode=mode, encode_threads=2,
                                 pipeline_depth=depth, **backend_kw)
        engine = pdp.DPEngine(acc, backend)
        result = engine.aggregate(
            pdp.ChunkSource(_chunks(pids, pks, vals)), _agg_params(),
            _EXTRACTORS)
        acc.compute_budgets()
        return dict(result), acc.mechanism_count

    def _select(self, mode, mesh=None, **backend_kw):
        pids, pks, vals = _stream()
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e6,
                                        total_delta=1e-5)
        backend = pdp.TPUBackend(noise_seed=29, mesh=mesh,
                                 encode_mode=mode, **backend_kw)
        engine = pdp.DPEngine(acc, backend)
        result = engine.select_partitions(
            pdp.ChunkSource(_chunks(pids, pks, vals)),
            pdp.SelectPartitionsParams(max_partitions_contributed=4),
            _EXTRACTORS)
        acc.compute_budgets()
        return sorted(result), acc.mechanism_count

    @pytest.mark.parametrize("n_devices,depth", [(1, 1), (4, 8), (8, 8)])
    def test_dense_aggregate_parity(self, n_devices, depth):
        mesh = make_mesh(n_devices=n_devices)
        host, m_host = self._aggregate("host", mesh, depth)
        with reshard.forbid_row_fetches():
            dev, m_dev = self._aggregate("hash_device", mesh, depth)
        assert m_host == m_dev
        assert host and set(host) == set(dev)
        for k in host:
            assert host[k].count == dev[k].count
            assert host[k].sum == dev[k].sum

    @pytest.mark.parametrize("n_devices", [4])
    def test_blocked_aggregate_parity(self, n_devices):
        mesh = make_mesh(n_devices=n_devices)
        kw = dict(large_partition_threshold=16)
        host, m_host = self._aggregate("host", mesh, None, **kw)
        dev, m_dev = self._aggregate("hash_device", mesh, None, **kw)
        assert m_host == m_dev
        assert host and set(host) == set(dev)
        for k in host:
            assert host[k].count == dev[k].count
            assert host[k].sum == dev[k].sum

    @pytest.mark.parametrize("n_devices", [1, 4, 8])
    def test_dense_select_parity(self, n_devices):
        mesh = make_mesh(n_devices=n_devices)
        host, m_host = self._select("host", mesh)
        dev, m_dev = self._select("hash_device", mesh)
        assert m_host == m_dev and host and host == dev

    def test_blocked_select_parity(self):
        mesh = make_mesh(n_devices=4)
        kw = dict(large_partition_threshold=16)
        host, m_host = self._select("host", mesh, **kw)
        with reshard.forbid_row_fetches():
            dev, m_dev = self._select("hash_device", mesh, **kw)
        assert m_host == m_dev and host and host == dev

    def test_unsharded_engine_parity(self):
        host, m_host = self._aggregate("host")
        dev, m_dev = self._aggregate("hash_device")
        assert m_host == m_dev and host and set(host) == set(dev)
        for k in host:
            assert host[k].count == dev[k].count
            assert host[k].sum == dev[k].sum

    def test_chunk_source_overrides_backend_mode(self):
        pids, pks, vals = _stream(n=800)
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1e6,
                                        total_delta=1e-5)
        engine = pdp.DPEngine(
            acc, pdp.TPUBackend(noise_seed=29, encode_mode="host"))
        before = rt_telemetry.snapshot()
        result = engine.aggregate(
            pdp.ChunkSource(_chunks(pids, pks, vals),
                            encode_mode="hash_device"),
            _agg_params(), _EXTRACTORS)
        acc.compute_budgets()
        assert dict(result)
        assert rt_telemetry.delta(before).get(
            "pipeline_device_encode_chunks", 0) > 0


# ---------------------------------------------------------------------------
# Knob validation + accumulator fills
# ---------------------------------------------------------------------------


class TestEncodeModeKnob:

    def test_validator(self):
        input_validators.validate_encode_mode("host", "t")
        input_validators.validate_encode_mode("hash_device", "t")
        for bad in ("device", "", None, 7, "HASH_DEVICE"):
            with pytest.raises(ValueError, match="encode_mode"):
                input_validators.validate_encode_mode(bad, "t")

    def test_backend_validates(self):
        with pytest.raises(ValueError, match="encode_mode"):
            pdp.TPUBackend(encode_mode="bogus")
        assert pdp.TPUBackend(encode_mode="hash_device").encode_mode == \
            "hash_device"

    def test_chunk_source_validates(self):
        with pytest.raises(ValueError, match="encode_mode"):
            pdp.ChunkSource([], encode_mode="bogus")
        assert pdp.ChunkSource([]).encode_mode is None

    def test_for_job_view_inherits_encode_mode(self):
        backend = pdp.TPUBackend(encode_mode="hash_device")
        assert backend.for_job("j").encode_mode == "hash_device"

    def test_stream_encode_rejects_bad_mode(self):
        with pytest.raises(ValueError, match="encode_mode"):
            ingest.stream_encode_columns([], encode_mode="bogus")


class TestAccumulatorFills:

    @pytest.mark.parametrize("donate", [False, True])
    def test_custom_fills_pad_the_tail(self, donate):
        sent = int(device_encode._U32_MAX)
        acc = rt_pipeline.DeviceRowAccumulator(
            donate=donate, fills=(sent, sent, 0))
        h = device_encode.pack_hash_rows(
            ingest.hash_key_column(np.arange(5)))
        k = device_encode.pack_hash_rows(
            ingest.hash_key_column(np.arange(5) % 2))
        v = np.arange(5.0)
        if donate:
            cap = executor.row_bucket(5)
            h, k, v = ingest._pad_chunk_rows(h, k, v, cap,
                                             (sent, sent, 0))
        acc.append(h, k, v, 5)
        bufs = acc.finalize()
        assert bufs[0].shape[0] == executor.row_bucket(5)
        tail = np.asarray(bufs[0])[5:]
        assert (tail == sent).all()
