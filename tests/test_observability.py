"""Fleet observability plane coverage (runtime/observability.py).

Five contracts:

  * **Gauges** are first-class declared metrics: set_gauge validates
    against the registry (kind AND membership, mirrored statically by
    the registry-drift rule), scopes by job, and clears on the
    coordinated epoch reset.
  * **Live export** is grammatically strict: render_prometheus() must
    round-trip through parse_prometheus() (the no-external-dep line
    grammar), over HTTP from the background endpoint and through the
    atomic-file mode — and a scrape taken MID-RUN sees current levels.
  * **Memory watermarks** attribute device memory to phases: the byte-
    accounted fallback tracks live/peak exactly, span closes attach the
    watermark when sampling is on, and an OOM degradation's instant
    carries the watermark that triggered it.
  * **The budget odometer** reconciles EXACTLY: one ordered record per
    _register_mechanism, record count == mechanism_count, eps shares
    summing bit-identically to the ledger's spent epsilon — and the
    trail persists through the CRC-verified journal.
  * **Cross-process rollup** merges per-process exports exactly once
    each: counters sum, health keys by (job, process), and the merged
    Perfetto trace carries each controller's spans on its own pid
    track with no incident double-counted.

Plus the telemetry.reset() vs concurrent job_scope race (the epoch
reset must never corrupt a live job's counters or health registry).
"""

import json
import logging
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import budget_accounting, combiners
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.runtime import health as rt_health
from pipelinedp_tpu.runtime import journal as rt_journal
from pipelinedp_tpu.runtime import observability as obs
from pipelinedp_tpu.runtime import retry as rt_retry
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.runtime import trace

pytestmark = pytest.mark.observability


@pytest.fixture(autouse=True)
def _obs_epoch():
    """Fresh epoch per test; every exporter stopped, tracing off."""
    telemetry.reset()
    yield
    obs.stop_all_exporters()
    trace.disable()
    telemetry.reset()


class TestGauges:

    def test_set_and_snapshot(self):
        telemetry.set_gauge("pipeline_queue_depth", 5)
        telemetry.set_gauge("live_devices", 4, job_id="job-g")
        snap = telemetry.gauge_snapshot()
        assert snap["pipeline_queue_depth"] == {"": 5.0}
        assert snap["live_devices"] == {"job-g": 4.0}

    def test_set_gauge_rejects_undeclared(self):
        with pytest.raises(ValueError, match="not a declared metric"):
            telemetry.set_gauge("totally_made_up_gauge", 1)

    def test_kind_mismatch_rejected_both_ways(self):
        with pytest.raises(ValueError, match="declared as a counter"):
            telemetry.set_gauge("block_retries", 1)
        with pytest.raises(ValueError, match="declared as a gauge"):
            telemetry.record("pipeline_queue_depth")

    def test_job_scope_attribution(self):
        with rt_health.job_scope("job-gauge"):
            telemetry.set_gauge("pipeline_queue_depth", 7)
        assert telemetry.gauge_snapshot()["pipeline_queue_depth"] == {
            "job-gauge": 7.0
        }

    def test_overwrite_is_a_level_not_a_count(self):
        telemetry.set_gauge("pipeline_queue_depth", 3)
        telemetry.set_gauge("pipeline_queue_depth", 1)
        assert telemetry.gauge_snapshot()["pipeline_queue_depth"][""] == 1.0

    def test_reset_clears_gauges(self):
        telemetry.set_gauge("pipeline_queue_depth", 3)
        telemetry.reset()
        assert telemetry.gauge_snapshot() == {}


class TestPrometheusText:

    def test_render_parses_under_strict_grammar(self):
        telemetry.record("block_retries", 3)
        telemetry.set_gauge("pipeline_queue_depth", 2, job_id="j1")
        parsed = obs.parse_prometheus(obs.render_prometheus())
        assert parsed["pdp_block_retries"]["type"] == "counter"
        assert parsed["pdp_block_retries"]["samples"][""] == 3.0
        assert parsed["pdp_pipeline_queue_depth"]["samples"][
            'job_id=j1'] == 2.0

    def test_every_declared_metric_has_help_and_type(self):
        parsed = obs.parse_prometheus(obs.render_prometheus())
        for metric in telemetry.REGISTRY.values():
            entry = parsed[obs.PROM_PREFIX + metric.name]
            assert entry["type"] == metric.kind
            assert entry["help"]

    def test_zero_counters_export_as_zero(self):
        parsed = obs.parse_prometheus(obs.render_prometheus())
        assert parsed["pdp_block_retries"]["samples"][""] == 0.0

    def test_label_escaping_round_trips(self):
        telemetry.set_gauge("live_devices", 2, job_id='job"with\\quote')
        text = obs.render_prometheus()
        parsed = obs.parse_prometheus(text)
        assert parsed["pdp_live_devices"]["samples"]

    @pytest.mark.parametrize("bad", [
        "pdp_x 1",                      # sample before TYPE
        "# TYPE pdp_x histogram\npdp_x 1",   # unsupported type
        "# TYPE pdp_x counter\npdp_x one",   # non-numeric value
        "# TYPE pdp_x counter\npdp_x{j=unquoted} 1",  # unquoted label
        "!!!",
    ])
    def test_grammar_violations_raise(self, bad):
        with pytest.raises(ValueError):
            obs.parse_prometheus(bad)


class TestExporters:

    def test_requires_exactly_one_mode(self):
        with pytest.raises(ValueError, match="exactly one"):
            obs.MetricsExporter()
        with pytest.raises(ValueError, match="exactly one"):
            obs.MetricsExporter(port=0, path="/tmp/x")

    def test_file_mode_writes_parseable_snapshots(self, tmp_path):
        path = str(tmp_path / "metrics.prom")
        telemetry.record("block_retries")
        exporter = obs.start_exporter(path=path, interval_s=0.05)
        try:
            assert os.path.exists(path)  # written before start returns
            parsed = obs.parse_prometheus(open(path).read())
            assert parsed["pdp_block_retries"]["samples"][""] == 1.0
            # MID-RUN liveness: a later increment lands in a later
            # atomic re-write of the same file.
            telemetry.record("block_retries", 4)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                parsed = obs.parse_prometheus(open(path).read())
                if parsed["pdp_block_retries"]["samples"][""] == 5.0:
                    break
                time.sleep(0.02)
            assert parsed["pdp_block_retries"]["samples"][""] == 5.0
        finally:
            exporter.stop()

    def test_http_endpoint_scrapes_live(self):
        telemetry.record("journal_replays", 2)
        exporter = obs.start_exporter(port=0)
        try:
            assert exporter.port > 0
            with urllib.request.urlopen(exporter.endpoint,
                                        timeout=10) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                text = resp.read().decode()
            parsed = obs.parse_prometheus(text)
            assert parsed["pdp_journal_replays"]["samples"][""] == 2.0
            # A second scrape observes state recorded since the first.
            telemetry.record("journal_replays")
            with urllib.request.urlopen(exporter.endpoint,
                                        timeout=10) as resp:
                parsed = obs.parse_prometheus(resp.read().decode())
            assert parsed["pdp_journal_replays"]["samples"][""] == 3.0
        finally:
            exporter.stop()

    def test_backend_knobs_validate_and_expose(self, tmp_path):
        with pytest.raises(ValueError, match="metrics_port"):
            pdp.TPUBackend(metrics_port=-1)
        with pytest.raises(ValueError, match="metrics_path"):
            pdp.TPUBackend(metrics_path="")
        path = str(tmp_path / "m.prom")
        backend = pdp.TPUBackend(metrics_port=0, metrics_path=path)
        try:
            endpoint = backend.metrics_endpoint()
            assert endpoint.startswith("http://127.0.0.1:")
            parsed = obs.parse_prometheus(backend.scrape_metrics())
            assert "pdp_block_retries" in parsed
            assert os.path.exists(path)
        finally:
            backend.stop_metrics()
        assert backend.metrics_endpoint() is None

    def test_scrape_refreshes_sampled_gauges(self):
        obs.account_bytes(1 << 20)
        parsed = obs.parse_prometheus(obs.render_prometheus())
        assert parsed["pdp_device_memory_live_bytes"]["samples"][""] >= \
            float(1 << 20)


class TestMemoryWatermark:

    def test_accounted_fallback_tracks_live_and_peak(self):
        obs.account_bytes(100)
        obs.account_bytes(200)
        obs.release_bytes(150)
        wm = obs.memory_watermark()
        if wm["source"] == "accounted":
            assert wm["live_bytes"] == 150
            assert wm["peak_bytes"] == 300
        else:
            # Platform provides device stats: the accounted fallback is
            # shadowed but the shape contract holds.
            assert wm["live_bytes"] >= 0 and wm["peak_bytes"] >= 0

    def test_account_arrays_and_reset(self):
        n = obs.account_arrays(np.zeros(10, np.float64),
                               np.zeros(4, np.int32), None)
        assert n == 96
        telemetry.reset()
        wm = obs.memory_watermark()
        if wm["source"] == "accounted":
            assert wm["live_bytes"] == 0 and wm["peak_bytes"] == 0

    def test_span_sampling_attaches_watermark_attrs(self):
        trace.enable()
        obs.enable_memory_sampling()
        try:
            obs.account_bytes(4096)
            with trace.span("phase_under_test"):
                pass
        finally:
            obs.disable_memory_sampling()
        events = trace.to_trace_events()["traceEvents"]
        span_ev = [e for e in events
                   if e.get("name") == "phase_under_test"][0]
        assert "mem_live_bytes" in span_ev["args"]
        assert "mem_peak_bytes" in span_ev["args"]
        assert span_ev["args"]["mem_peak_bytes"] >= \
            span_ev["args"]["mem_live_bytes"] >= 0

    def test_sampler_detached_after_reset(self):
        obs.enable_memory_sampling()
        telemetry.reset()
        trace.enable()
        with trace.span("clean"):
            pass
        events = trace.to_trace_events()["traceEvents"]
        span_ev = [e for e in events if e.get("name") == "clean"][0]
        assert "mem_live_bytes" not in span_ev["args"]

    def test_oom_degradation_instant_carries_watermark(self):
        trace.enable()
        obs.account_bytes(12345)
        failed = []

        def run_range(base, capacity, generation, end):
            if capacity > 64 and not failed:
                failed.append(capacity)
                raise rt_retry.BlockOOMError(0, MemoryError("synthetic"))

        rt_retry.run_with_degradation(run_range, n_partitions=128,
                                      block_partitions=128)
        events = trace.to_trace_events()["traceEvents"]
        oom = [e for e in events
               if e.get("name") == "block_oom_degradations"]
        assert len(oom) == 1
        args = oom[0]["args"]
        assert args["mem_source"] in ("device", "accounted")
        assert args["mem_peak_bytes"] >= 0
        if args["mem_source"] == "accounted":
            assert args["mem_live_bytes"] == 12345


class TestOdometer:

    def test_records_are_ordered_and_reconcile(self):
        acc = budget_accounting.NaiveBudgetAccountant(
            total_epsilon=2.0, total_delta=1e-6)
        acc.request_budget(MechanismType.LAPLACE)
        acc.request_budget(MechanismType.GENERIC, weight=3.0)
        report = obs.odometer_report(accountant=acc)
        assert report["mechanisms"] == acc.mechanism_count == 2
        assert report["pending"] == 2  # budgets not computed yet
        assert [r["seq"] for r in report["records"]] == sorted(
            r["seq"] for r in report["records"])
        acc.compute_budgets()
        report = obs.odometer_report(accountant=acc)
        assert report["pending"] == 0
        assert report["spent_epsilon"] == acc.spent_epsilon()
        assert report["spent_epsilon"] == pytest.approx(2.0)
        assert report["remaining_epsilon"] == pytest.approx(0.0)
        assert report["reconciled"]

    def test_two_accountants_do_not_mix(self):
        a = budget_accounting.NaiveBudgetAccountant(1.0, 1e-6)
        b = budget_accounting.NaiveBudgetAccountant(4.0, 1e-6)
        a.request_budget(MechanismType.LAPLACE)
        b.request_budget(MechanismType.LAPLACE)
        b.request_budget(MechanismType.LAPLACE)
        assert obs.odometer_report(accountant=a)["mechanisms"] == 1
        assert obs.odometer_report(accountant=b)["mechanisms"] == 2
        assert obs.odometer_report()["mechanisms"] == 3

    def test_job_and_metric_provenance(self):
        acc = budget_accounting.NaiveBudgetAccountant(1.0, 1e-6)
        with rt_health.job_scope("odo-job"):
            params = pdp.AggregateParams(
                metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                max_partitions_contributed=1,
                max_contributions_per_partition=1,
                min_value=0.0, max_value=1.0)
            combiners.create_compound_combiner(params, acc)
        report = obs.odometer_report(accountant=acc)
        assert [r["metric"] for r in report["records"]] == ["count",
                                                            "sum"]
        assert all(r["job_id"] == "odo-job" for r in report["records"])
        assert all(r["mechanism_kind"] for r in report["records"])
        assert obs.odometer_report(accountant=acc,
                                   job_id="other")["mechanisms"] == 0

    def test_pld_accountant_feeds_the_odometer_too(self):
        acc = budget_accounting.PLDBudgetAccountant(
            total_epsilon=1.0, total_delta=1e-6)
        acc.request_budget(MechanismType.GAUSSIAN)
        assert obs.odometer_report(accountant=acc)["mechanisms"] == \
            acc.mechanism_count == 1

    def test_persist_and_load_through_journal(self, tmp_path):
        acc = budget_accounting.NaiveBudgetAccountant(1.0, 1e-6)
        with rt_health.job_scope("persist-job"):
            acc.request_budget(MechanismType.LAPLACE)
        acc.compute_budgets()
        journal = rt_journal.BlockJournal(str(tmp_path))
        obs.persist_odometer(journal, "persist-job")
        # A FRESH journal instance (cross-process resume shape) loads
        # the trail back through the CRC-verified read path.
        loaded = obs.load_odometer(
            rt_journal.BlockJournal(str(tmp_path)), "persist-job")
        assert len(loaded) == 1
        assert loaded[0]["job_id"] == "persist-job"
        assert loaded[0]["eps"] == pytest.approx(1.0)
        assert loaded[0]["mechanism_kind"]

    def test_driver_teardown_persists_odometer(self, tmp_path):
        """A journaled blocked-driver run leaves the audit trail in the
        journal directory at teardown (runtime/entry.py wiring)."""
        import jax

        from pipelinedp_tpu import executor
        from pipelinedp_tpu.parallel import large_p

        P = 256
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=1.0)
        acc = budget_accounting.NaiveBudgetAccountant(1.0, 1e-6)
        compound = combiners.create_compound_combiner(params, acc)
        acc.compute_budgets()
        cfg = executor.make_kernel_config(params, compound, P,
                                          private_selection=False,
                                          selection_params=None)
        stds = np.zeros_like(
            np.asarray(executor.compute_noise_stds(compound, params)))
        pid = np.arange(64, dtype=np.int32)
        pk = (pid % 16).astype(np.int32)
        values = np.ones(64)
        valid = np.ones(64, bool)
        mn, mx, mns, mxs, mid = executor.kernel_scalars(params)
        journal = rt_journal.BlockJournal(str(tmp_path))
        large_p.aggregate_blocked(
            pid, pk, values, valid, mn, mx, mns, mxs, mid, stds,
            jax.random.PRNGKey(0), cfg, block_partitions=128,
            journal=journal, job_id="odo-drv")
        loaded = obs.load_odometer(
            rt_journal.BlockJournal(str(tmp_path)), "odo-drv")
        assert len(loaded) == obs.odometer_report()["mechanisms"]
        assert any(r["metric"] == "count" for r in loaded)

    def test_backend_odometer_accessor(self):
        acc = budget_accounting.NaiveBudgetAccountant(1.0, 1e-6)
        acc.request_budget(MechanismType.LAPLACE)
        backend = pdp.TPUBackend()
        report = backend.odometer(accountant=acc)
        assert report["mechanisms"] == 1


class TestCrossProcessRollup:

    def _simulate_process(self, directory, process_index, job,
                          incidents):
        """Records one synthetic controller's epoch and exports it."""
        telemetry.reset()
        trace.enable()
        with rt_health.job_scope(job):
            with trace.span("dispatch", block=1):
                pass
            for name, n in incidents.items():
                telemetry.record(name, n)
        path = obs.export_process_state(directory,
                                        process_index=process_index)
        telemetry.reset()
        return path

    def test_merge_sums_counters_and_keys_health_by_process(self,
                                                           tmp_path):
        self._simulate_process(str(tmp_path), 0, "job-a",
                               {"journal_replays": 2})
        self._simulate_process(str(tmp_path), 1, "job-a",
                               {"journal_replays": 3,
                                "host_losses": 1})
        pod = obs.aggregate_directory(str(tmp_path))
        assert pod["processes"] == [0, 1]
        assert pod["counters"]["journal_replays"] == 5
        assert pod["counters"]["host_losses"] == 1
        assert set(pod["health"]) == {"job-a@p0", "job-a@p1"}

    def test_merged_trace_has_distinct_pid_tracks(self, tmp_path):
        self._simulate_process(str(tmp_path), 0, "job-a", {})
        self._simulate_process(str(tmp_path), 1, "job-a", {})
        pod = obs.aggregate_directory(str(tmp_path))
        events = pod["trace"]["traceEvents"]
        span_pids = {e["pid"] for e in events if e["ph"] == "X"}
        assert span_pids == {0, 1}
        names = {e["pid"]: e["args"]["name"] for e in events
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names == {0: "pipelinedp-tpu p0",
                         1: "pipelinedp-tpu p1"}

    def test_incidents_appear_exactly_once_after_merge(self, tmp_path):
        """The merge ingests each per-process buffer exactly once: an
        incident instant count on each pid track equals that process's
        own counter — never doubled."""
        self._simulate_process(str(tmp_path), 0, "job-a",
                               {"host_losses": 1})
        self._simulate_process(str(tmp_path), 1, "job-a",
                               {"host_losses": 1})
        pod = obs.aggregate_directory(str(tmp_path))
        events = pod["trace"]["traceEvents"]
        for pid in (0, 1):
            on_track = [e for e in events if e["ph"] == "i" and
                        e["name"] == "host_losses" and e["pid"] == pid]
            assert len(on_track) == 1
        assert pod["counters"]["host_losses"] == 2

    def test_re_export_supersedes_not_duplicates(self, tmp_path):
        """A process re-exporting (retry, second drain) atomically
        replaces its file — the rollup never sees the same controller
        twice."""
        self._simulate_process(str(tmp_path), 0, "job-a",
                               {"host_losses": 1})
        self._simulate_process(str(tmp_path), 0, "job-a",
                               {"host_losses": 1})
        pod = obs.aggregate_directory(str(tmp_path))
        assert pod["processes"] == [0]
        assert pod["counters"]["host_losses"] == 1

    def test_pod_rollup_writer_waits_and_merges(self, tmp_path):
        self._simulate_process(str(tmp_path), 0, "job-a", {})

        def late_sibling():
            time.sleep(0.3)
            self._simulate_process(str(tmp_path), 1, "job-a", {})

        t = threading.Thread(target=late_sibling)
        t.start()
        try:
            path = obs.write_pod_rollup(str(tmp_path), 2, timeout_s=10)
        finally:
            t.join()
        assert path and os.path.basename(path) == obs.POD_ROLLUP_NAME
        with open(path) as f:
            rollup = json.load(f)
        assert rollup["processes"] == [0, 1]

    def test_rollup_proceeds_past_a_dead_controller(self, tmp_path,
                                                    caplog):
        self._simulate_process(str(tmp_path), 0, "job-a", {})
        with caplog.at_level(logging.WARNING):
            path = obs.write_pod_rollup(str(tmp_path), 2, timeout_s=0.2)
        assert path is not None
        assert "missing" in caplog.text
        with open(path) as f:
            assert json.load(f)["processes"] == [0]

    def test_odometer_merges_in_process_order(self, tmp_path):
        for pi in (1, 0):
            telemetry.reset()
            acc = budget_accounting.NaiveBudgetAccountant(1.0, 1e-6)
            acc.request_budget(MechanismType.LAPLACE)
            obs.export_process_state(str(tmp_path), process_index=pi)
        telemetry.reset()
        pod = obs.aggregate_directory(str(tmp_path))
        assert [r["seq"] for r in pod["odometer"]] == [0, 0]


class TestTraceBufferOverflow:

    def test_drops_are_a_declared_counter_with_warn_once(self, caplog):
        trace.enable(buffer_limit=5)
        with caplog.at_level(logging.WARNING,
                             logger=logging.getLogger().name):
            for _ in range(12):
                trace.instant("tick")
        summary = trace.trace_summary()
        assert summary["n_events"] == 5
        assert summary["dropped_events"] == 7
        assert summary["truncated"] is True
        assert telemetry.snapshot()["trace_dropped_events"] == 7
        warnings = [r for r in caplog.records
                    if "trace: event buffer full" in r.getMessage()]
        assert len(warnings) == 1  # warn-once per epoch

    def test_untruncated_epoch_is_flagged_clean(self):
        trace.enable()
        trace.instant("tick")
        summary = trace.trace_summary()
        assert summary["truncated"] is False
        assert "trace_dropped_events" not in telemetry.snapshot()

    def test_job_filtered_summary_still_flags_truncation(self):
        trace.enable(buffer_limit=3)
        with rt_health.job_scope("trunc-job"):
            for _ in range(10):
                trace.instant("tick")
        assert trace.trace_summary(job_id="trunc-job")["truncated"]


class TestResetVsConcurrentJobScopes:
    """telemetry.reset() racing live job scopes (the satellite): two
    threads inside job_scope during an epoch reset must neither crash
    nor corrupt either job's counters / the health registry."""

    def test_reset_race_does_not_corrupt_jobs(self):
        stop = threading.Event()
        errors = []

        def worker(job):
            try:
                while not stop.is_set():
                    with rt_health.job_scope(job):
                        for _ in range(20):
                            telemetry.record("block_retries")
                            telemetry.record_duration("phase_r", 0.001)
                            telemetry.set_gauge("pipeline_queue_depth",
                                                1)
            except Exception as e:  # noqa: BLE001 - the test asserts NO exception of any kind escapes the racing scopes
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(f"race-{i}",))
                   for i in range(2)]
        for t in threads:
            t.start()
        try:
            for _ in range(30):
                # force=True: this test deliberately exercises the
                # reset-vs-live-scope concurrency safety the guard
                # would otherwise (correctly) refuse.
                telemetry.reset(force=True)
                time.sleep(0.002)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads)

        # The epoch after the storm is coherent: a fresh scope records
        # into a clean registry with exact attribution.
        telemetry.reset()
        with rt_health.job_scope("after-race"):
            telemetry.record("block_retries", 3)
            telemetry.record_duration("phase_after", 0.5)
        assert telemetry.snapshot() == {"block_retries": 3}
        assert set(telemetry.job_timing_snapshot()) == {"after-race"}
        snaps = rt_health.snapshot_all()
        assert set(snaps) == {"after-race"}
        assert snaps["after-race"]["counters"]["block_retries"] == 3

    def test_reset_mid_scope_keeps_thread_consistent(self):
        """A FORCED reset INSIDE an open scope: the thread's tracked
        JobHealth keeps accepting events (orphaned, never crashing);
        the next scope re-registers cleanly. (The unforced reset now
        refuses while scopes are live — tests/test_service.py
        TestResetGuard pins that.)"""
        with rt_health.job_scope("orphan-job"):
            telemetry.reset(force=True)
            telemetry.record("block_retries")  # posts to the orphan
        assert "orphan-job" not in rt_health.snapshot_all()
        with rt_health.job_scope("orphan-job"):
            telemetry.record("block_retries")
        snaps = rt_health.snapshot_all()
        assert snaps["orphan-job"]["counters"]["block_retries"] == 1
