"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host-platform virtual devices.

Note: the environment may pre-register an external TPU platform plugin and
force jax_platforms to it via sitecustomize (overriding the JAX_PLATFORMS
env var), so the config must be reset *programmatically* after importing
jax — before any backend is initialized.
"""

import _thread
import os
import threading

import pytest

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    # Also registered in pytest.ini; kept here so a stray invocation from
    # another rootdir stays warning-free. The tier-1 command runs
    # `-m 'not slow'`, so `faults` tests — the fault-injection harness
    # suite, including the hang/corrupt kinds — are part of tier-1 by
    # default and selectable alone with `-m faults`.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection/robustness tests, including the "
        "hang/corrupt kinds (runs in tier-1; select alone with "
        "-m faults)")
    config.addinivalue_line(
        "markers",
        "hard_timeout(seconds): outer hard timeout enforced by the "
        "conftest guard — a watchdog BUG in the code under test cannot "
        "hang tier-1")
    config.addinivalue_line(
        "markers",
        "staticcheck: the AST DP-invariant analyzer gate and its "
        "fixtures (always-on tier-1, NOT slow; select alone with "
        "-m staticcheck)")
    config.addinivalue_line(
        "markers",
        "pipeline: the device-resident streaming executor (ingest "
        "thread pool, staging queue, donated accumulator) — "
        "bit-identity, backpressure and fault tests (tier-1, NOT slow; "
        "select alone with -m pipeline)")
    config.addinivalue_line(
        "markers",
        "multihost: multi-controller pod scale-out — process-topology "
        "helpers, process-scoped journals, whole-host loss, and the "
        "spawn-based 2-process jax.distributed CPU dryrun (tier-1, NOT "
        "slow; select alone with -m multihost)")
    config.addinivalue_line(
        "markers",
        "observability: the fleet observability plane — gauges, "
        "Prometheus export, memory watermarks, the privacy-budget "
        "odometer and the cross-process rollup (tier-1, NOT slow; "
        "select alone with -m observability)")
    config.addinivalue_line(
        "markers",
        "service: the resident multi-tenant DP-aggregation service — "
        "concurrent tenants over one backend, persisted tenant budget "
        "ledgers, admission control/load shedding, cross-job "
        "compile-cache reuse (tier-1, NOT slow; select alone with "
        "-m service)")
    config.addinivalue_line(
        "markers",
        "aot: the single-dispatch warm path — AOT executable cache, "
        "fused release kernels, compute/drain overlap: bit-identity, "
        "cache-key correctness, per-job retrace attribution (tier-1, "
        "NOT slow; select alone with -m aot)")
    config.addinivalue_line(
        "markers",
        "batching: megabatched serving — the coalescing tier that runs "
        "identical-spec concurrent jobs as lanes of one vmapped release "
        "launch: per-lane bit-identity vs solo, fallthrough/fallback "
        "paths, ledger reconciliation, launch-count collapse (tier-1, "
        "NOT slow; select alone with -m batching)")
    config.addinivalue_line(
        "markers",
        "fleet: fleet operations — elastic scale-UP, journal-based "
        "job migration, and the zero-loss rolling-restart drill "
        "(tier-1, NOT slow; select alone with -m fleet)")
    config.addinivalue_line(
        "markers",
        "chaos: randomized composed-fault campaigns — seeded schedule "
        "generation, the universal invariant checker (exactly-once "
        "jobs, bit-exact ledgers, bit-identical results), "
        "storage-fault hardening and the delta-debugging schedule "
        "minimizer (tier-1, NOT slow; select alone with -m chaos)")
    config.addinivalue_line(
        "markers",
        "numeric_armor: overflow-safe accumulation, the fail-closed "
        "release sentinel, discrete/snapped noise and the "
        "extreme_values fault kind (tier-1, NOT slow; select alone "
        "with -m numeric_armor)")
    config.addinivalue_line(
        "markers",
        "pld: the PLD fast-composition engine and dual-spend admission "
        "— batched-FFT vs pairwise parity, closed-form/golden "
        "accounting checks, the query fast path, the spectrum cache "
        "and the tenant capacity multiplier (tier-1, NOT slow; select "
        "alone with -m pld)")


@pytest.fixture(autouse=True)
def _hard_timeout_guard(request):
    """Outer safety net for the watchdog/hang tests: if a test marked
    hard_timeout runs past its limit (i.e. the deadline machinery under
    test failed to cancel an injected hang), interrupt the main thread so
    the test FAILS instead of wedging the whole tier-1 run. The injected
    hang hooks sleep in small increments, so KeyboardInterrupt lands
    promptly."""
    marker = request.node.get_closest_marker("hard_timeout")
    if marker is None:
        yield
        return
    limit = float(marker.args[0]) if marker.args else 120.0
    timer = threading.Timer(limit, _thread.interrupt_main)
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()
