"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host-platform virtual devices.

Note: the environment may pre-register an external TPU platform plugin and
force jax_platforms to it via sitecustomize (overriding the JAX_PLATFORMS
env var), so the config must be reset *programmatically* after importing
jax — before any backend is initialized.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    # Registered here (no pytest.ini in this repo) so -m filters stay
    # warning-free. The tier-1 command runs `-m 'not slow'`, so `faults`
    # tests — the fault-injection harness suite — are part of tier-1 by
    # default and selectable alone with `-m faults`.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection/robustness tests (runs in tier-1; "
        "select alone with -m faults)")
