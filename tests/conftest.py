"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host-platform virtual devices. Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
