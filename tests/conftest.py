"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on XLA's host-platform virtual devices.

Note: the environment may pre-register an external TPU platform plugin and
force jax_platforms to it via sitecustomize (overriding the JAX_PLATFORMS
env var), so the config must be reset *programmatically* after importing
jax — before any backend is initialized.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
