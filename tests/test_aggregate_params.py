"""Tests for the parameter/validation layer.

Modeled on the reference's validation-table test style
(/root/reference/tests/aggregate_params_test.py, dp_engine_test.py:96-143).
"""

import pytest

import pipelinedp_tpu as pdp


def _count_params(**kwargs):
    defaults = dict(metrics=[pdp.Metrics.COUNT],
                    noise_kind=pdp.NoiseKind.LAPLACE,
                    max_partitions_contributed=2,
                    max_contributions_per_partition=3)
    defaults.update(kwargs)
    return pdp.AggregateParams(**defaults)


class TestMetric:

    def test_str(self):
        assert str(pdp.Metrics.COUNT) == "COUNT"
        assert str(pdp.Metrics.PERCENTILE(90)) == "PERCENTILE(90)"

    def test_eq_hash(self):
        assert pdp.Metrics.PERCENTILE(90) == pdp.Metrics.PERCENTILE(90)
        assert pdp.Metrics.PERCENTILE(90) != pdp.Metrics.PERCENTILE(50)
        assert hash(pdp.Metrics.SUM) == hash(pdp.Metric("SUM"))
        assert pdp.Metrics.COUNT != "COUNT"

    def test_is_percentile(self):
        assert pdp.Metrics.PERCENTILE(50).is_percentile
        assert not pdp.Metrics.COUNT.is_percentile


class TestNoiseKindMechanismType:

    def test_conversion_roundtrip(self):
        assert (pdp.NoiseKind.LAPLACE.convert_to_mechanism_type() ==
                pdp.MechanismType.LAPLACE)
        assert (pdp.NoiseKind.GAUSSIAN.convert_to_mechanism_type() ==
                pdp.MechanismType.GAUSSIAN)
        assert pdp.MechanismType.LAPLACE.to_noise_kind() == pdp.NoiseKind.LAPLACE
        assert (pdp.MechanismType.GAUSSIAN.to_noise_kind() ==
                pdp.NoiseKind.GAUSSIAN)
        with pytest.raises(ValueError):
            pdp.MechanismType.GENERIC.to_noise_kind()


class TestAggregateParamsValidation:

    def test_valid_count(self):
        _count_params()

    def test_valid_sum_with_value_bounds(self):
        _count_params(metrics=[pdp.Metrics.SUM], min_value=0, max_value=5)

    def test_valid_sum_with_partition_bounds(self):
        _count_params(metrics=[pdp.Metrics.SUM],
                      min_sum_per_partition=0,
                      max_sum_per_partition=5)

    @pytest.mark.parametrize(
        "kwargs,match",
        [
            (dict(min_value=1), "both set or both None"),
            (dict(max_value=1), "both set or both None"),
            (dict(min_sum_per_partition=1), "both set or both None"),
            (dict(min_value=1, max_value=0), "equal to or greater"),
            (dict(min_value=float("nan"), max_value=1), "finite number"),
            (dict(min_value=float("inf"), max_value=1), "finite number"),
            (dict(min_value=0, max_value=1, min_sum_per_partition=0,
                  max_sum_per_partition=1), "both set"),
            (dict(max_partitions_contributed=None), "both"),
            (dict(max_partitions_contributed=0), "positive integer"),
            (dict(max_partitions_contributed=1.5), "positive integer"),
            (dict(pre_threshold=0), "positive integer"),
        ],
    )
    def test_invalid_params(self, kwargs, match):
        with pytest.raises(ValueError, match=match):
            _count_params(**kwargs)

    def test_metrics_need_bounds(self):
        with pytest.raises(ValueError, match="bounds per partition"):
            _count_params(metrics=[pdp.Metrics.SUM])
        with pytest.raises(ValueError, match="min_sum_per_partition is not"):
            _count_params(metrics=[pdp.Metrics.MEAN],
                          min_sum_per_partition=0,
                          max_sum_per_partition=1)

    def test_vector_sum_incompatible_with_scalar_metrics(self):
        with pytest.raises(ValueError, match="vector sum"):
            _count_params(metrics=[pdp.Metrics.VECTOR_SUM, pdp.Metrics.SUM],
                          min_value=0,
                          max_value=1)

    def test_privacy_id_count_with_enforced_bounds(self):
        with pytest.raises(ValueError, match="PRIVACY_ID_COUNT"):
            _count_params(metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
                          contribution_bounds_already_enforced=True)

    def test_max_contributions_exclusive(self):
        pdp.AggregateParams(metrics=[pdp.Metrics.COUNT], max_contributions=5)
        with pytest.raises(ValueError, match="only one"):
            _count_params(max_contributions=5)
        with pytest.raises(ValueError, match="either max_contributions"):
            pdp.AggregateParams(metrics=[pdp.Metrics.COUNT])

    def test_custom_combiners_with_metrics(self):
        with pytest.raises(ValueError, match="Custom combiners"):
            _count_params(custom_combiners=[object()])

    def test_str_readable(self):
        s = str(_count_params())
        assert "COUNT" in s and "max_partitions_contributed=2" in s


class TestEpsilonDeltaValidation:

    @pytest.mark.parametrize("eps,delta", [(0, 0), (-1, 0), (float("inf"), 0),
                                           (float("nan"), 0), (1, -1e-9),
                                           (1, 1.0), (1, float("nan"))])
    def test_invalid(self, eps, delta):
        from pipelinedp_tpu import input_validators
        with pytest.raises(ValueError):
            input_validators.validate_epsilon_delta(eps, delta, "test")

    def test_valid(self):
        from pipelinedp_tpu import input_validators
        input_validators.validate_epsilon_delta(1.0, 0, "test")
        input_validators.validate_epsilon_delta(0.1, 1e-10, "test")
