"""Direct tests for contribution bounders, sampling utils, and reports.

Mirrors the reference's dedicated per-module suites
(tests/contribution_bounders_test.py, tests/sampling_utils_test.py,
tests/report_generator_test.py): each bounding strategy is driven directly
through LocalBackend with a transparent aggregate_fn, so the sampling
semantics (what is kept, what is dropped, what reaches the aggregator) are
asserted without engine noise on top.
"""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import contribution_bounders, report_generator
from pipelinedp_tpu import sampling_utils


def _params(l0=None, linf=None, max_contributions=None):
    if l0 is not None and linf is None and max_contributions is None:
        # Per-partition-SUM-clipping form: the engine routes these params to
        # SamplingCrossPartitionContributionBounder, which reads only L0
        # (Linf is enforced by the combiner via sum clipping).
        return pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                   noise_kind=pdp.NoiseKind.GAUSSIAN,
                                   max_partitions_contributed=l0,
                                   max_contributions_per_partition=1,
                                   min_sum_per_partition=0.0,
                                   max_sum_per_partition=100.0)
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT],
        noise_kind=pdp.NoiseKind.GAUSSIAN,
        max_partitions_contributed=l0,
        max_contributions_per_partition=linf,
        max_contributions=max_contributions)


def _bound(bounder, rows, params, aggregate_fn=list):
    backend = pdp.LocalBackend(seed=7)
    report = report_generator.ReportGenerator(params, "test")
    out = bounder.bound_contributions(rows, params, backend, report,
                                      aggregate_fn)
    return list(out), report


class TestSamplingCrossAndPerPartition:
    BOUNDER = contribution_bounders.SamplingCrossAndPerPartitionContributionBounder

    def test_empty_collection(self):
        out, _ = _bound(self.BOUNDER(), [], _params(l0=2, linf=2))
        assert out == []

    def test_within_bounds_nothing_dropped(self):
        rows = [("u1", "A", 1.0), ("u1", "B", 2.0), ("u2", "A", 3.0)]
        out, _ = _bound(self.BOUNDER(), rows, _params(l0=2, linf=2),
                        aggregate_fn=sum)
        assert sorted(out) == [(("u1", "A"), 1.0), (("u1", "B"), 2.0),
                               (("u2", "A"), 3.0)]

    def test_per_partition_bound_applied(self):
        # One user, 5 identical contributions to one partition, linf=2:
        # exactly 2 survive regardless of which are sampled.
        rows = [("u1", "A", 3.0)] * 5
        out, _ = _bound(self.BOUNDER(), rows, _params(l0=1, linf=2),
                        aggregate_fn=sum)
        assert out == [(("u1", "A"), 6.0)]

    def test_cross_partition_bound_applied(self):
        # One user in 6 partitions, l0=2: exactly 2 (pid, pk) pairs remain,
        # each with its full (single) contribution.
        rows = [("u1", f"pk{i}", 1.0) for i in range(6)]
        out, _ = _bound(self.BOUNDER(), rows, _params(l0=2, linf=4),
                        aggregate_fn=sum)
        assert len(out) == 2
        assert all(pid == "u1" and acc == 1.0 for (pid, _), acc in out)
        kept_pks = {pk for (_, pk), _ in out}
        assert kept_pks <= {f"pk{i}" for i in range(6)}
        assert len(kept_pks) == 2

    def test_aggregate_fn_sees_value_lists(self):
        rows = [("u1", "A", 1.0), ("u1", "A", 2.0)]
        out, _ = _bound(self.BOUNDER(), rows, _params(l0=1, linf=5),
                        aggregate_fn=lambda vals: sorted(vals))
        assert out == [(("u1", "A"), [1.0, 2.0])]

    def test_report_stages_narrate_both_bounds(self):
        _, report = _bound(self.BOUNDER(), [("u1", "A", 1.0)],
                           _params(l0=3, linf=4))
        text = report.report()
        assert "Per-partition contribution bounding" in text
        assert "Cross-partition contribution bounding" in text


class TestSamplingPerPrivacyId:
    BOUNDER = contribution_bounders.SamplingPerPrivacyIdContributionBounder

    def test_empty_collection(self):
        out, _ = _bound(self.BOUNDER(), [], _params(max_contributions=3))
        assert out == []

    def test_within_bounds_nothing_dropped(self):
        rows = [("u1", "A", 1.0), ("u1", "B", 2.0), ("u2", "A", 3.0)]
        out, _ = _bound(self.BOUNDER(), rows, _params(max_contributions=3),
                        aggregate_fn=sum)
        assert sorted(out) == [(("u1", "A"), 1.0), (("u1", "B"), 2.0),
                               (("u2", "A"), 3.0)]

    def test_total_bound_applied_across_partitions(self):
        # 8 identical-value contributions spread over 4 partitions with
        # max_contributions=3: exactly 3 values total survive.
        rows = [("u1", f"pk{i % 4}", 1.0) for i in range(8)]
        out, _ = _bound(self.BOUNDER(), rows, _params(max_contributions=3),
                        aggregate_fn=sum)
        assert sum(acc for _, acc in out) == 3.0
        assert all(pid == "u1" for (pid, _), _ in out)

    def test_report_stage(self):
        _, report = _bound(self.BOUNDER(), [("u1", "A", 1.0)],
                           _params(max_contributions=5))
        assert "not more than 5 contributions" in report.report()


class TestSamplingCrossPartition:
    BOUNDER = contribution_bounders.SamplingCrossPartitionContributionBounder

    def test_empty_collection(self):
        out, _ = _bound(self.BOUNDER(), [], _params(l0=2))
        assert out == []

    def test_l0_applied_values_within_partition_untouched(self):
        # L0-only strategy: kept partitions retain ALL their values (the
        # combiner is responsible for Linf via sum clipping).
        rows = [("u1", "A", 1.0)] * 4 + [("u1", "B", 2.0)] * 4 + [
            ("u1", "C", 3.0)
        ] * 4
        out, _ = _bound(self.BOUNDER(), rows, _params(l0=2), aggregate_fn=sum)
        assert len(out) == 2
        per_pk = {"A": 4.0, "B": 8.0, "C": 12.0}
        for (pid, pk), acc in out:
            assert pid == "u1"
            assert acc == per_pk[pk]


class TestChooseFromListWithoutReplacement:

    @pytest.mark.parametrize("n,size", [(0, 3), (2, 3), (3, 3)])
    def test_small_input_returned_unchanged(self, n, size):
        a = list(range(n))
        assert sampling_utils.choose_from_list_without_replacement(
            a, size) is a

    @pytest.mark.parametrize("n,size", [(10, 1), (10, 5), (100, 99)])
    def test_samples_exactly_size_distinct_elements(self, n, size):
        a = list(range(n))
        out = sampling_utils.choose_from_list_without_replacement(a, size)
        assert len(out) == size
        assert len(set(out)) == size
        assert set(out) <= set(a)

    def test_preserves_python_element_types(self):
        # The reference samples indices, not elements, so tuples survive as
        # tuples (not converted to numpy arrays/scalars).
        a = [("pk1", [1.0]), ("pk2", [2.0]), ("pk3", [3.0]),
             ("pk4", [4.0])]
        out = sampling_utils.choose_from_list_without_replacement(a, 2)
        assert all(isinstance(x, tuple) and isinstance(x[0], str) for x in out)

    def test_seeded_rng_is_deterministic(self):
        import numpy as np
        a = list(range(50))
        out1 = sampling_utils.choose_from_list_without_replacement(
            a, 10, rng=np.random.default_rng(3))
        out2 = sampling_utils.choose_from_list_without_replacement(
            a, 10, rng=np.random.default_rng(3))
        assert out1 == out2


class TestValueSampler:

    def test_rate_one_keeps_everything(self):
        sampler = sampling_utils.ValueSampler(1.0)
        assert all(sampler.keep(v) for v in range(200))

    def test_rate_zero_keeps_nothing(self):
        sampler = sampling_utils.ValueSampler(0.0)
        assert not any(sampler.keep(v) for v in range(200))

    def test_deterministic_across_instances(self):
        kept1 = [sampling_utils.ValueSampler(0.5).keep(v) for v in range(100)]
        kept2 = [sampling_utils.ValueSampler(0.5).keep(v) for v in range(100)]
        assert kept1 == kept2

    def test_empirical_rate_close_to_nominal(self):
        sampler = sampling_utils.ValueSampler(0.3)
        kept = sum(sampler.keep(v) for v in range(20_000))
        # SHA1-hash keep decisions behave like iid Bernoulli(0.3):
        # 6 sigma = 6 * sqrt(.3 * .7 * 20000) ~ 389.
        assert abs(kept - 6000) < 400


class TestReportGenerator:

    def test_no_params_renders_empty(self):
        report = report_generator.ReportGenerator(None, "aggregate")
        report.add_stage("never shown")
        assert report.report() == ""

    def test_stages_numbered_in_order(self):
        params = _params(l0=2, linf=1)
        report = report_generator.ReportGenerator(params, "aggregate", True)
        report.add_stage("first stage")
        report.add_stage("second stage")
        text = report.report()
        assert "DPEngine method: aggregate" in text
        assert text.index(" 1. first stage") < text.index(" 2. second stage")

    def test_lazy_stage_resolved_at_report_time(self):
        params = _params(l0=1, linf=1)
        report = report_generator.ReportGenerator(params, "aggregate", True)
        box = {"eps": None}
        report.add_stage(lambda: f"noise with eps={box['eps']}")
        box["eps"] = 0.5  # simulates compute_budgets() filling the spec
        assert "noise with eps=0.5" in report.report()


class TestExplainComputationReport:

    def test_text_before_aggregation_raises(self):
        out = report_generator.ExplainComputationReport()
        with pytest.raises(ValueError, match="not set"):
            out.text()

    def test_failing_lazy_stage_points_at_compute_budgets(self):
        params = _params(l0=1, linf=1)
        gen = report_generator.ReportGenerator(params, "aggregate", True)

        def boom():
            raise AssertionError("budget not computed")

        gen.add_stage(boom)
        out = report_generator.ExplainComputationReport()
        out._set_report_generator(gen)
        with pytest.raises(ValueError, match="compute_budgets"):
            out.text()

    def test_end_to_end_through_engine(self):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend(seed=0))
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        out = report_generator.ExplainComputationReport()
        result = engine.aggregate([("u1", "A", 1.0), ("u2", "A", 2.0)],
                                  _params(l0=1, linf=1),
                                  extractors,
                                  public_partitions=["A"],
                                  out_explain_computation_report=out)
        accountant.compute_budgets()
        list(result)
        text = out.text()
        assert "DPEngine method: aggregate" in text
        assert "Computation graph" in text
