"""Tests for dataset_histograms (modeled on the reference's
tests/dataset_histograms/ suites: bin boundaries, histogram contents on small
datasets, quantiles, ratio_dropped, pre-aggregated parity, columnar parity).
"""

import numpy as np
import pytest

from pipelinedp_tpu import DataExtractors, PreAggregateExtractors, LocalBackend
from pipelinedp_tpu.dataset_histograms import histograms as hist
from pipelinedp_tpu.dataset_histograms import computing_histograms as ch
from pipelinedp_tpu.dataset_histograms import histogram_error_estimator as est
import pipelinedp_tpu as pdp


BACKEND = LocalBackend()


def _get(one_element_col):
    result = list(one_element_col)
    assert len(result) == 1
    return result[0]


class TestLogBinning:

    @pytest.mark.parametrize("value,lower,upper", [
        (1, 1, 2),
        (999, 999, 1000),
        (1000, 1000, 1010),
        (1001, 1000, 1010),
        (1234, 1230, 1240),
        (9999, 9990, 10000),
        (10000, 10000, 10100),
        (12345, 12300, 12400),
        (123456, 123000, 124000),
    ])
    def test_scalar(self, value, lower, upper):
        assert ch._to_bin_lower_upper_logarithmic(value) == (lower, upper)

    def test_vectorized_matches_scalar(self):
        values = np.concatenate([
            np.arange(1, 2000),
            np.array([9999, 10000, 10001, 12345, 99999, 100000, 100001,
                      123456, 10**7, 10**7 + 5]),
        ])
        lowers, uppers = ch._bin_lowers_log_vectorized(values)
        for v, l, u in zip(values, lowers, uppers):
            assert ch._to_bin_lower_upper_logarithmic(int(v)) == (l, u), v


class TestHistogramDataclasses:

    def _histogram(self):
        bins = [
            hist.FrequencyBin(lower=1, upper=2, count=10, sum=10, max=1),
            hist.FrequencyBin(lower=2, upper=3, count=5, sum=10, max=2),
            hist.FrequencyBin(lower=5, upper=6, count=5, sum=25, max=5),
        ]
        return hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, bins)

    def test_totals(self):
        h = self._histogram()
        assert h.total_count() == 20
        assert h.total_sum() == 45
        assert h.max_value() == 5
        assert h.is_integer

    def test_quantiles(self):
        h = self._histogram()
        # left ratios: bin1: 0, bin2: 10/20=0.5, bin3: 15/20=0.75
        assert h.quantiles([0.0, 0.4, 0.5, 0.74, 0.75, 1.0]) == [1, 1, 2, 2, 5,
                                                                 5]

    def test_quantiles_empty_raises(self):
        h = hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS,
                           [hist.FrequencyBin(1, 2, 0, 0, 1)])
        with pytest.raises(ValueError):
            h.quantiles([0.5])

    def test_ratio_dropped(self):
        h = self._histogram()
        ratios = hist.compute_ratio_dropped(h)
        # thresholds: 0 → all dropped; 5 = max → 0 dropped
        assert ratios[0] == (0, 1)
        assert ratios[-1] == (5, 0.0)
        d = dict(ratios)
        # threshold 1: each element keeps 1: dropped = 45 - 20 = 25
        assert d[1] == pytest.approx(25 / 45)
        # threshold 2: 10*1 + 5*2 + 5*2 kept = 30 → dropped 15
        assert d[2] == pytest.approx(15 / 45)

    def test_ratio_dropped_max_not_bin_lower(self):
        bins = [hist.FrequencyBin(lower=1, upper=2, count=2, sum=2, max=1),
                hist.FrequencyBin(lower=3, upper=4, count=1, sum=7, max=7)]
        # NOTE: artificial bin where max > lower.
        h = hist.Histogram(hist.HistogramType.L0_CONTRIBUTIONS, bins)
        ratios = hist.compute_ratio_dropped(h)
        assert ratios[-1] == (7, 0.0)


DATA = [
    # (privacy_id, partition_key, value)
    (1, 'a', 1.0),
    (1, 'a', 2.0),
    (1, 'b', 3.0),
    (2, 'a', 4.0),
    (2, 'c', 5.0),
    (2, 'c', 6.0),
    (3, 'a', 7.0),
]
EXTRACTORS = DataExtractors(privacy_id_extractor=lambda x: x[0],
                            partition_extractor=lambda x: x[1],
                            value_extractor=lambda x: x[2])


class TestComputeDatasetHistograms:

    def _compute(self):
        return _get(ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))

    def test_l0(self):
        h = self._compute().l0_contributions_histogram
        # pid1 → 2 partitions, pid2 → 2, pid3 → 1
        assert h.name == hist.HistogramType.L0_CONTRIBUTIONS
        assert {(b.lower, b.count) for b in h.bins} == {(1, 1), (2, 2)}

    def test_l1(self):
        h = self._compute().l1_contributions_histogram
        # pid1 → 3 records, pid2 → 3, pid3 → 1
        assert {(b.lower, b.count) for b in h.bins} == {(1, 1), (3, 2)}

    def test_linf(self):
        h = self._compute().linf_contributions_histogram
        # pairs: (1,a)=2, (1,b)=1, (2,a)=1, (2,c)=2, (3,a)=1
        assert {(b.lower, b.count) for b in h.bins} == {(1, 3), (2, 2)}

    def test_linf_sum(self):
        h = self._compute().linf_sum_contributions_histogram
        # pair sums: 3.0, 3.0, 4.0, 11.0, 7.0
        assert not h.is_integer
        assert h.total_count() == 5
        assert h.total_sum() == pytest.approx(28.0)
        assert h.lower == pytest.approx(3.0)
        assert h.upper == pytest.approx(11.0)

    def test_count_per_partition(self):
        h = self._compute().count_per_partition_histogram
        # a → 4 rows, b → 1, c → 2
        assert {(b.lower, b.count) for b in h.bins} == {(1, 1), (2, 1), (4, 1)}

    def test_privacy_id_per_partition(self):
        h = self._compute().count_privacy_id_per_partition
        # a → 3 pids, b → 1, c → 1
        assert {(b.lower, b.count) for b in h.bins} == {(1, 2), (3, 1)}

    def test_columnar_parity(self):
        pids = np.array([r[0] for r in DATA])
        pk_map = {'a': 0, 'b': 1, 'c': 2}
        pks = np.array([pk_map[r[1]] for r in DATA])
        values = np.array([r[2] for r in DATA])
        columnar = ch.compute_dataset_histograms_columnar(pids, pks, values)
        backend_result = self._compute()
        for field in ('l0_contributions_histogram',
                      'l1_contributions_histogram',
                      'linf_contributions_histogram',
                      'count_per_partition_histogram',
                      'count_privacy_id_per_partition'):
            got = getattr(columnar, field)
            want = getattr(backend_result, field)
            assert sorted((b.lower, b.count, b.sum) for b in got.bins) == \
                sorted((b.lower, b.count, b.sum) for b in want.bins), field
        got_sum = columnar.linf_sum_contributions_histogram
        want_sum = backend_result.linf_sum_contributions_histogram
        assert got_sum.total_count() == want_sum.total_count()
        assert got_sum.total_sum() == pytest.approx(want_sum.total_sum())


class TestPreaggregatedHistograms:

    def test_parity_with_raw(self):
        # preaggregate by hand: (pk, (count, sum, n_partitions, n_contribs))
        preagg = [
            ('a', (2, 3.0, 2, 3)),  # pid1@a
            ('b', (1, 3.0, 2, 3)),  # pid1@b
            ('a', (1, 4.0, 2, 3)),  # pid2@a
            ('c', (2, 11.0, 2, 3)),  # pid2@c
            ('a', (1, 7.0, 1, 1)),  # pid3@a
        ]
        extractors = PreAggregateExtractors(
            partition_extractor=lambda x: x[0],
            preaggregate_extractor=lambda x: x[1])
        got = _get(
            ch.compute_dataset_histograms_on_preaggregated_data(
                preagg, extractors, BACKEND))
        want = _get(ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))
        for field in ('l0_contributions_histogram',
                      'l1_contributions_histogram',
                      'linf_contributions_histogram',
                      'count_per_partition_histogram',
                      'count_privacy_id_per_partition'):
            got_h = getattr(got, field)
            want_h = getattr(want, field)
            assert sorted((b.lower, b.count) for b in got_h.bins) == \
                sorted((b.lower, b.count) for b in want_h.bins), field


def _preaggregate(rows):
    from pipelinedp_tpu.analysis import pre_aggregation
    ext = DataExtractors(privacy_id_extractor=lambda x: x[0],
                         partition_extractor=lambda x: x[1],
                         value_extractor=lambda x: 0)
    return list(pre_aggregation.preaggregate(rows, BACKEND, ext))


def _bins(raw_fn, preagg_fn, rows, pre_aggregated, distinct=False):
    """Runs one histogram computation on raw or preaggregated (pid, pk).

    The raw functions consume (pid, pk) tuples — distinct pairs for the
    l0 / privacy-id-per-partition histograms, with duplicates otherwise
    (see compute_dataset_histograms wiring).
    """
    if pre_aggregated:
        col = [(pk, agg) for pk, agg in _preaggregate(rows)]
        return _get(preagg_fn(col, BACKEND))
    col = sorted(set(rows)) if distinct else rows
    return _get(raw_fn(col, BACKEND))


class TestPerHistogramEdgeCases:
    """Edge-case matrix per histogram type, raw and pre-aggregated inputs
    (reference: tests/dataset_histograms/computing_histograms_test.py)."""

    L0_CASES = [
        ("empty", [], []),
        ("small", [(1, 1), (1, 2), (2, 1)],
         [(1, 1, 1, 1), (2, 1, 2, 2)]),
        ("each_id_one_contribution", [(i, i) for i in range(100)],
         [(1, 100, 100, 1)]),
        ("one_id_one_partition", [(0, 0)], [(1, 1, 1, 1)]),
        ("one_id_many_partitions_log_bin", [(0, i) for i in range(1234)],
         [(1230, 1, 1234, 1234)]),
        ("two_ids_overlapping", [(0, i) for i in range(15)] +
         [(1, i) for i in range(10, 25)], [(15, 2, 30, 15)]),
    ]

    @pytest.mark.parametrize("pre_aggregated", [False, True],
                             ids=["raw", "preagg"])
    @pytest.mark.parametrize("name,rows,expected",
                             L0_CASES, ids=[c[0] for c in L0_CASES])
    def test_l0(self, name, rows, expected, pre_aggregated):
        h = _bins(ch._compute_l0_contributions_histogram,
                  ch._compute_l0_contributions_histogram_on_preaggregated_data,
                  rows, pre_aggregated, distinct=True)
        assert h.name == hist.HistogramType.L0_CONTRIBUTIONS
        got = [(b.lower, b.count, b.sum, b.max) for b in h.bins]
        assert got == expected, name

    L1_CASES = [
        ("empty", [], []),
        ("small", [(1, 1), (1, 2), (2, 1)],
         [(1, 1, 1, 1), (2, 1, 2, 2)]),
        ("one_id_repeat_one_partition", [(0, 0)] * 100,
         [(100, 1, 100, 100)]),
        ("one_id_many_partitions", [(0, i // 2) for i in range(1235)],
         [(1230, 1, 1235, 1235)]),
        ("three_ids", [(0, i) for i in range(15)] +
         [(1, i) for i in range(10, 25)] + [(2, i) for i in range(11)],
         [(11, 1, 11, 11), (15, 2, 30, 15)]),
    ]

    @pytest.mark.parametrize("pre_aggregated", [False, True],
                             ids=["raw", "preagg"])
    @pytest.mark.parametrize("name,rows,expected",
                             L1_CASES, ids=[c[0] for c in L1_CASES])
    def test_l1(self, name, rows, expected, pre_aggregated):
        h = _bins(ch._compute_l1_contributions_histogram,
                  ch._compute_l1_contributions_histogram_on_preaggregated_data,
                  rows, pre_aggregated)
        assert h.name == hist.HistogramType.L1_CONTRIBUTIONS
        got = [(b.lower, b.count, b.sum, b.max) for b in h.bins]
        assert got == expected, name

    LINF_CASES = [
        ("empty", [], []),
        ("small", [(1, 1), (1, 2), (2, 1)],
         [(1, 3, 3, 1)]),
        ("one_pair_repeated", [(0, 0)] * 1234,
         [(1230, 1, 1234, 1234)]),
        ("mixed_pairs", [(0, 0)] * 3 + [(0, 1)] * 2 + [(1, 0)],
         [(1, 1, 1, 1), (2, 1, 2, 2), (3, 1, 3, 3)]),
    ]

    @pytest.mark.parametrize("pre_aggregated", [False, True],
                             ids=["raw", "preagg"])
    @pytest.mark.parametrize("name,rows,expected",
                             LINF_CASES, ids=[c[0] for c in LINF_CASES])
    def test_linf(self, name, rows, expected, pre_aggregated):
        h = _bins(
            ch._compute_linf_contributions_histogram,
            ch._compute_linf_contributions_histogram_on_preaggregated_data,
            rows, pre_aggregated)
        assert h.name == hist.HistogramType.LINF_CONTRIBUTIONS
        got = [(b.lower, b.count, b.sum, b.max) for b in h.bins]
        assert got == expected, name

    COUNT_PER_PARTITION_CASES = [
        ("empty", [], []),
        ("two_partitions", [(1, 1), (1, 2), (2, 1)],
         [(1, 1, 1, 1), (2, 1, 2, 2)]),
        ("one_partition_many_rows", [(i % 7, 0) for i in range(999)],
         [(999, 1, 999, 999)]),
    ]

    @pytest.mark.parametrize("pre_aggregated", [False, True],
                             ids=["raw", "preagg"])
    @pytest.mark.parametrize("name,rows,expected",
                             COUNT_PER_PARTITION_CASES,
                             ids=[c[0] for c in COUNT_PER_PARTITION_CASES])
    def test_count_per_partition(self, name, rows, expected, pre_aggregated):
        h = _bins(ch._compute_partition_count_histogram,
                  ch._compute_partition_count_histogram_on_preaggregated_data,
                  rows, pre_aggregated)
        assert h.name == hist.HistogramType.COUNT_PER_PARTITION
        got = [(b.lower, b.count, b.sum, b.max) for b in h.bins]
        assert got == expected, name

    PID_PER_PARTITION_CASES = [
        ("empty", [], []),
        ("two_partitions", [(1, 1), (1, 2), (2, 1)],
         [(1, 1, 1, 1), (2, 1, 2, 2)]),
        ("distinct_ids_counted_once", [(0, 0)] * 50 + [(1, 0)] * 50,
         [(2, 1, 2, 2)]),
    ]

    @pytest.mark.parametrize("pre_aggregated", [False, True],
                             ids=["raw", "preagg"])
    @pytest.mark.parametrize("name,rows,expected",
                             PID_PER_PARTITION_CASES,
                             ids=[c[0] for c in PID_PER_PARTITION_CASES])
    def test_privacy_id_per_partition(self, name, rows, expected,
                                      pre_aggregated):
        h = _bins(
            ch._compute_partition_privacy_id_count_histogram,
            ch.
            _compute_partition_privacy_id_count_histogram_on_preaggregated_data,
            rows, pre_aggregated, distinct=True)
        assert h.name == hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION
        got = [(b.lower, b.count, b.sum, b.max) for b in h.bins]
        assert got == expected, name


class TestLinfSumHistogram:
    """Float-binned sum-contributions histogram (10k buckets)."""

    def _rows(self, sums):
        # One ((pid, pk), value) row per requested per-pair sum.
        return [((i, i), s) for i, s in enumerate(sums)]

    def test_single_value(self):
        h = _get(
            ch._compute_linf_sum_contributions_histogram(
                self._rows([5.0]), BACKEND))
        assert h.name == hist.HistogramType.LINF_SUM_CONTRIBUTIONS
        assert len(h.bins) == 1
        assert h.bins[0].count == 1
        assert h.bins[0].sum == pytest.approx(5.0)

    def test_uniform_values_fill_buckets(self):
        sums = list(np.linspace(0.0, 100.0, 1000))
        h = _get(
            ch._compute_linf_sum_contributions_histogram(
                self._rows(sums), BACKEND))
        assert h.total_count() == 1000
        assert h.total_sum() == pytest.approx(sum(sums), rel=1e-6)
        assert h.max_value() == pytest.approx(100.0)

    def test_negative_values(self):
        sums = [-10.0, -5.0, 0.0, 5.0]
        h = _get(
            ch._compute_linf_sum_contributions_histogram(
                self._rows(sums), BACKEND))
        assert h.total_count() == 4
        assert h.bins[0].lower == pytest.approx(-10.0)


class TestErrorEstimator:

    def test_estimate_rmse_count(self):
        histograms = _get(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))
        estimator = est.create_error_estimator(histograms,
                                               base_std=1.0,
                                               metric=pdp.Metrics.COUNT,
                                               noise=pdp.NoiseKind.LAPLACE)
        # With bounds above max contributions nothing is dropped:
        # stddev = base_std * l0 * linf
        rmse = estimator.estimate_rmse(l0_bound=2, linf_bound=2)
        # ratio_dropped = 0 → rmse = std = 4 for every partition
        assert rmse == pytest.approx(4.0)

    def test_estimate_rmse_requires_linf_for_count(self):
        histograms = _get(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))
        estimator = est.create_error_estimator(histograms, 1.0,
                                               pdp.Metrics.COUNT,
                                               pdp.NoiseKind.LAPLACE)
        with pytest.raises(ValueError):
            estimator.estimate_rmse(l0_bound=1)

    def test_estimator_rejects_sum(self):
        histograms = _get(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))
        with pytest.raises(ValueError):
            est.create_error_estimator(histograms, 1.0, pdp.Metrics.SUM,
                                       pdp.NoiseKind.LAPLACE)

    def test_ratio_dropped_interpolation(self):
        histograms = _get(
            ch.compute_dataset_histograms(DATA, EXTRACTORS, BACKEND))
        estimator = est.create_error_estimator(histograms, 1.0,
                                               pdp.Metrics.PRIVACY_ID_COUNT,
                                               pdp.NoiseKind.GAUSSIAN)
        assert estimator.get_ratio_dropped_l0(0) == 1
        assert estimator.get_ratio_dropped_l0(100) == 0
        # l0 per pid: [2, 2, 1]; threshold 1 drops 2 of 5 pair-contributions
        assert estimator.get_ratio_dropped_l0(1) == pytest.approx(2 / 5)


class TestDeviceHistogramsParity:
    """Device histograms must match the host columnar path bin-for-bin."""

    def _random_columns(self, seed, n=3000, users=80, parts=40):
        rng = np.random.default_rng(seed)
        pids = rng.integers(0, users, n).astype(np.int32)
        pks = (np.power(rng.random(n), 2.5) * parts).astype(np.int32)
        values = (rng.random(n) * 7.0 - 2.0)
        return pids, pks, values

    @staticmethod
    def _assert_same_int_hist(dev, host):
        assert dev.name == host.name
        got = [(b.lower, b.upper, b.count, b.sum, b.max) for b in dev.bins]
        want = [(b.lower, b.upper, b.count, b.sum, b.max)
                for b in host.bins]
        assert got == want, (dev.name, got[:5], want[:5])

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_int_histograms_match_host(self, seed):
        from pipelinedp_tpu.dataset_histograms import device_histograms as dh
        pids, pks, values = self._random_columns(seed)
        host = ch.compute_dataset_histograms_columnar(pids, pks, values)
        dev = dh.compute_dataset_histograms_device(pids, pks, values)
        self._assert_same_int_hist(dev.l0_contributions_histogram,
                                   host.l0_contributions_histogram)
        self._assert_same_int_hist(dev.l1_contributions_histogram,
                                   host.l1_contributions_histogram)
        self._assert_same_int_hist(dev.linf_contributions_histogram,
                                   host.linf_contributions_histogram)
        self._assert_same_int_hist(dev.count_per_partition_histogram,
                                   host.count_per_partition_histogram)
        self._assert_same_int_hist(
            dev.count_privacy_id_per_partition,
            host.count_privacy_id_per_partition)

    def test_float_histogram_matches_host(self):
        from pipelinedp_tpu.dataset_histograms import device_histograms as dh
        pids, pks, values = self._random_columns(5)
        values = values.astype(np.float32)  # both paths bin identical f32s
        host = ch.compute_dataset_histograms_columnar(pids, pks, values)
        dev = dh.compute_dataset_histograms_device(pids, pks, values)
        hb = host.linf_sum_contributions_histogram.bins
        db = dev.linf_sum_contributions_histogram.bins
        assert sum(b.count for b in db) == sum(b.count for b in hb)
        # Align bins by index over the shared [min, max] range; f32 vs f64
        # edge arithmetic may shift a sum that lands within float eps of an
        # edge by one bin, so demand >99% exact-index agreement.
        lo = min(b.lower for b in hb)
        hi = max(b.upper for b in hb)
        buckets = ch.NUMBER_OF_BUCKETS_IN_LINF_SUM_CONTRIBUTIONS_HISTOGRAM
        width = (hi - lo) / buckets

        def index_map(bins):
            return {int(round((b.lower - lo) / width)): b.count
                    for b in bins}

        hmap, dmap = index_map(hb), index_map(db)
        agree = sum(1 for i, c in dmap.items() if hmap.get(i) == c)
        assert agree >= 0.99 * len(hmap), (agree, len(hmap))

    def test_large_value_binning_decade_edges(self):
        from pipelinedp_tpu.dataset_histograms import device_histograms as dh
        # One user with k rows in one partition exercises the L1/Linf bin
        # of exactly k — probe the decade-edge values the integer binning
        # must place exactly (10^3, 10^3+1, 10^4 - 1, 10^6, ...).
        for k in (999, 1000, 1001, 9999, 10000, 123456, 10**6):
            pids = np.zeros(k, np.int32)
            pks = np.zeros(k, np.int32)
            host = ch.compute_dataset_histograms_columnar(pids, pks)
            dev = dh.compute_dataset_histograms_device(pids, pks)
            self._assert_same_int_hist(dev.l1_contributions_histogram,
                                       host.l1_contributions_histogram)

    def test_no_values_skips_float_histogram(self):
        from pipelinedp_tpu.dataset_histograms import device_histograms as dh
        pids, pks, _ = self._random_columns(7, n=500)
        dev = dh.compute_dataset_histograms_device(pids, pks)
        assert dev.linf_sum_contributions_histogram is None
        assert dev.l0_contributions_histogram.bins

    def test_empty_input(self):
        from pipelinedp_tpu.dataset_histograms import device_histograms as dh
        dev = dh.compute_dataset_histograms_device(np.zeros(0, np.int32),
                                                   np.zeros(0, np.int32),
                                                   np.zeros(0))
        assert dev.l0_contributions_histogram.bins == []
        assert dev.linf_sum_contributions_histogram.bins == []
