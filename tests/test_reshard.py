"""On-device all_to_all reshard (parallel/reshard.py) on the 8-device
virtual CPU mesh: co-location, host/device path parity on every meshed
route, and the transfer guard proving device-resident inputs never stage
rows through the host."""

import logging

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.parallel import make_mesh
from pipelinedp_tpu.parallel import reshard


def _data(n=10_000, n_ids=700, n_pk=50, seed=0, invalid_frac=0.1):
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n_ids, n).astype(np.int32)
    pk = rng.integers(0, n_pk, n).astype(np.int32)
    values = rng.uniform(0, 5, n).astype(np.float32)
    valid = rng.random(n) >= invalid_frac
    return pid, pk, values, valid


def _device(*cols):
    import jax.numpy as jnp
    return tuple(jnp.asarray(c) for c in cols)


def _spec(P, l0=50, linf=64, eps=1.0):
    from pipelinedp_tpu import combiners, executor
    from pipelinedp_tpu.aggregate_params import MechanismType
    from pipelinedp_tpu.ops import selection_ops
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                          pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=l0,
                                 max_contributions_per_partition=linf,
                                 min_value=0.0,
                                 max_value=5.0)
    acc = pdp.NaiveBudgetAccountant(total_epsilon=eps, total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, acc)
    budget = acc.request_budget(MechanismType.GENERIC)
    acc.compute_budgets()
    selection = selection_ops.selection_params_from_host(
        params.partition_selection_strategy, budget.eps, budget.delta,
        params.max_partitions_contributed, None)
    cfg = executor.make_kernel_config(params, compound, P,
                                      private_selection=True,
                                      selection_params=selection)
    stds = np.zeros_like(executor.compute_noise_stds(compound, params))
    return cfg, selection, stds, executor.kernel_scalars(params)


class TestDeviceReshard:

    @pytest.mark.parametrize("n_devices", [1, 4, 8])
    def test_colocates_and_preserves_rows(self, n_devices):
        mesh = make_mesh(n_devices=n_devices)
        pid, pk, values, valid = _data()
        rp, rk, rv, rva = map(
            np.asarray,
            reshard.device_reshard_rows_by_pid(
                mesh, *_device(pid, pk, values, valid)))
        assert len(rp) % n_devices == 0
        per = len(rp) // n_devices
        shard_of = {}
        for s in range(n_devices):
            sl = slice(s * per, (s + 1) * per)
            for p in rp[sl][rva[sl]]:
                assert shard_of.setdefault(int(p), s) == s
        # The exchanged row multiset is exactly the valid input rows.
        a = sorted(zip(pid[valid].tolist(), pk[valid].tolist(),
                       values[valid].tolist()))
        b = sorted(zip(rp[rva].tolist(), rk[rva].tolist(),
                       rv[rva].tolist()))
        assert a == b

    def test_bounded_padding_near_uniform(self):
        # Near-uniform ids: hash bucketing must land within the documented
        # bound — out_cap <= ~9/8 of the max shard load, and total padded
        # size within 2x of ideal even under hash imbalance.
        mesh = make_mesh(n_devices=8)
        pid, pk, values, valid = _data(n=40_000, n_ids=8000,
                                       invalid_frac=0.0)
        rp, _, _, rva = map(
            np.asarray,
            reshard.device_reshard_rows_by_pid(
                mesh, *_device(pid, pk, values, valid)))
        assert rva.sum() == 40_000
        assert len(rp) < 2.0 * 40_000

    def test_dominant_pid_warns_on_skew(self, caplog):
        # One id holding half the rows breaks the hash-balance assumption;
        # the reshard must say so instead of silently padding 8x.
        mesh = make_mesh(n_devices=8)
        n_tail = 7000
        pid = np.concatenate([
            np.zeros(7000, dtype=np.int32),
            np.arange(1, 1 + n_tail, dtype=np.int32)
        ])
        n = len(pid)
        cols = _device(pid, pid, np.ones(n, np.float32), np.ones(n, bool))
        with caplog.at_level(logging.WARNING):
            _, _, _, rva = map(
                np.asarray,
                reshard.device_reshard_rows_by_pid(mesh, *cols))
        assert rva.sum() == n
        assert any("hash" in r.message for r in caplog.records)

    def test_empty_and_zero_width_values(self):
        import jax.numpy as jnp
        mesh = make_mesh(n_devices=8)
        rp, _, rv, rva = map(
            np.asarray,
            reshard.device_reshard_rows_by_pid(
                mesh, jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.int32),
                jnp.zeros((0, 0), jnp.float32), jnp.zeros(0, bool)))
        assert rva.sum() == 0 and rv.shape[1] == 0
        # Zero-width values column (the selection path) with real rows.
        pid, pk, _, valid = _data(n=4000)
        rp, _, rv, rva = map(
            np.asarray,
            reshard.device_reshard_rows_by_pid(
                mesh, *_device(pid, pk,
                               np.zeros((len(pid), 0), np.float32), valid)))
        assert rva.sum() == valid.sum() and rv.shape[1] == 0

    def test_vector_values_column(self):
        mesh = make_mesh(n_devices=4)
        pid, pk, _, valid = _data(n=3000)
        vec = np.stack([pid.astype(np.float32),
                        np.ones(len(pid), np.float32)], axis=1)
        rp, _, rv, rva = map(
            np.asarray,
            reshard.device_reshard_rows_by_pid(
                mesh, *_device(pid, pk, vec, valid)))
        assert rv.shape[1] == 2
        # Each row's vector rode the exchange with its pid.
        np.testing.assert_allclose(rv[rva, 0], rp[rva].astype(np.float32))

    def test_stage_rows_rejects_bad_mode(self):
        mesh = make_mesh(n_devices=4)
        pid, pk, values, valid = _data(n=100)
        with pytest.raises(ValueError, match="reshard"):
            reshard.stage_rows_to_mesh(mesh, pid, pk, values, valid,
                                       "bogus")

    def test_backend_rejects_bad_mode(self):
        with pytest.raises(ValueError, match="reshard"):
            pdp.TPUBackend(reshard="bogus")


class TestTransferGuard:

    def test_guard_catches_row_fetch(self):
        import jax.numpy as jnp
        big = jnp.zeros(1 << 13)
        with reshard.forbid_row_fetches():
            with pytest.raises(AssertionError, match="device->host"):
                np.asarray(big)

    def test_guard_allows_control_tables_and_host_arrays(self):
        import jax.numpy as jnp
        from pipelinedp_tpu.parallel import mesh as mesh_lib
        with reshard.forbid_row_fetches():
            np.asarray(jnp.zeros(64))  # control-table sized: fine
            np.asarray(np.zeros(1 << 20))  # host numpy: not a transfer
            mesh_lib.host_fetch(jnp.zeros(1 << 13))  # sanctioned

    def test_device_inputs_never_stage_through_host(self):
        # The tentpole guarantee: a device-resident aggregation performs
        # ZERO O(rows) device->host fetches through reshard + kernels.
        import jax
        from pipelinedp_tpu.parallel import sharded
        mesh = make_mesh(n_devices=8)
        P = 50
        cfg, _, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data()
        cols = _device(pid, pk, values, valid)
        key = jax.random.PRNGKey(0)
        with reshard.forbid_row_fetches():
            outputs, keep, _ = sharded.sharded_aggregate_arrays(
                mesh, *cols, min_v, max_v, min_s, max_s, mid, stds, key,
                cfg)
        assert np.asarray(keep).shape == (P,)

    def test_host_inputs_would_fail_the_guard(self):
        # Sanity that the guard scope is meaningful: forcing the HOST
        # permutation on device-resident inputs downloads the rows and
        # must trip the guard.
        mesh = make_mesh(n_devices=8)
        pid, pk, values, valid = _data()
        cols = _device(pid, pk, values, valid)
        with reshard.forbid_row_fetches():
            with pytest.raises(AssertionError, match="device->host"):
                reshard.stage_rows_to_mesh(mesh, *cols, reshard="host")


class TestMeshedRouteParity:
    """Host-staged vs collective reshard must give identical results on
    every meshed route (noise-free; bounds non-binding so placement
    cannot change sampling)."""

    def test_dense_sharded_aggregate(self):
        import jax
        from pipelinedp_tpu.parallel import sharded
        mesh = make_mesh(n_devices=8)
        P = 50
        cfg, _, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P,
                                                               eps=1e7)
        pid, pk, values, valid = _data()
        key = jax.random.PRNGKey(0)
        out_h, keep_h, _ = sharded.sharded_aggregate_arrays(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg)
        with reshard.forbid_row_fetches():
            out_d, keep_d, _ = sharded.sharded_aggregate_arrays(
                mesh, *_device(pid, pk, values, valid), min_v, max_v,
                min_s, max_s, mid, stds, key, cfg)
        assert np.array_equal(np.asarray(keep_h), np.asarray(keep_d))
        assert np.asarray(keep_h).sum() > 0
        np.testing.assert_allclose(np.asarray(out_h["count"]),
                                   np.asarray(out_d["count"]), atol=1e-3)
        np.testing.assert_allclose(np.asarray(out_h["sum"]),
                                   np.asarray(out_d["sum"]), rtol=1e-4,
                                   atol=1e-3)

    def test_reshard_mode_escape_hatches(self):
        import jax
        from pipelinedp_tpu.parallel import sharded
        mesh = make_mesh(n_devices=8)
        P = 50
        cfg, _, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data()
        key = jax.random.PRNGKey(0)
        ref, keep_ref, _ = sharded.sharded_aggregate_arrays(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg)
        # host mode on device inputs, device mode on host inputs.
        _, keep_h, _ = sharded.sharded_aggregate_arrays(
            mesh, *_device(pid, pk, values, valid), min_v, max_v, min_s,
            max_s, mid, stds, key, cfg, reshard="host")
        _, keep_d, _ = sharded.sharded_aggregate_arrays(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, reshard="device")
        assert np.array_equal(np.asarray(keep_ref), np.asarray(keep_h))
        assert np.array_equal(np.asarray(keep_ref), np.asarray(keep_d))

    def test_sharded_select_partitions(self):
        import jax
        from pipelinedp_tpu.parallel import sharded
        mesh = make_mesh(n_devices=8)
        P = 50
        _, selection, _, _ = _spec(P, eps=1e7)
        pid, pk, _, valid = _data()
        key = jax.random.PRNGKey(1)
        keep_h = np.asarray(
            sharded.sharded_select_partitions(mesh, pid, pk, valid, key,
                                              50, P, selection))
        with reshard.forbid_row_fetches():
            keep_d = np.asarray(
                sharded.sharded_select_partitions(
                    mesh, *_device(pid, pk, valid), key, 50, P, selection))
        assert np.array_equal(keep_h, keep_d)
        assert keep_h.sum() > 0

    def test_blocked_aggregate(self):
        import jax
        import jax.numpy as jnp
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=8)
        P = 100_000
        cfg, _, stds, (min_v, max_v, min_s, max_s, mid) = _spec(
            P, l0=64, linf=8, eps=30)
        rng = np.random.default_rng(1)
        n = 30_000
        pid = rng.integers(0, 3000, n).astype(np.int64)
        pk = (np.power(rng.random(n), 6.0) * P).astype(np.int32)
        values = rng.uniform(0, 5, n).astype(np.float32)
        valid = np.ones(n, bool)
        key = jax.random.PRNGKey(2)
        kept_h, out_h = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=1 << 14)
        with reshard.forbid_row_fetches():
            kept_d, out_d = large_p.aggregate_blocked_sharded(
                mesh, jnp.asarray(pid), jnp.asarray(pk),
                jnp.asarray(values), jnp.asarray(valid), min_v, max_v,
                min_s, max_s, mid, stds, key, cfg,
                block_partitions=1 << 14)
        assert len(kept_h) > 0
        assert np.array_equal(kept_h, kept_d)
        np.testing.assert_allclose(out_h["count"], out_d["count"],
                                   atol=1e-3)
        np.testing.assert_allclose(out_h["sum"], out_d["sum"], rtol=1e-4,
                                   atol=1e-3)

    def test_blocked_select_partitions(self):
        import jax
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=8)
        P, l0 = 100_000, 30
        _, selection, _, _ = _spec(P, l0=l0, eps=1e7)
        rows = []
        for p in (5, 50_000, 99_999):
            for u in range(80):
                rows.append((u * 100_003 + p, p))
        pid = np.array([r[0] for r in rows], np.int64)
        pk = np.array([r[1] for r in rows], np.int32)
        valid = np.ones(len(rows), bool)
        key = jax.random.PRNGKey(5)
        kept_h = large_p.select_partitions_blocked_sharded(
            mesh, pid, pk, valid, key, l0, P, selection,
            block_partitions=1 << 14)
        with reshard.forbid_row_fetches():
            kept_d = large_p.select_partitions_blocked_sharded(
                mesh, *_device(pid, pk, valid), key, l0, P, selection,
                block_partitions=1 << 14)
        assert kept_h.tolist() == [5, 50_000, 99_999]
        assert np.array_equal(kept_h, kept_d)

    def test_engine_streamed_ingest_device_resident(self):
        # End to end: streamed-ingest EncodedData through the meshed
        # engine keeps its columns device-resident (auto -> collective
        # reshard) and must match LocalBackend.
        from pipelinedp_tpu import ingest
        rows = [("u%d" % (i % 50), "pk%d" % (i % 7), float(i % 5))
                for i in range(1000)]
        chunks = [(np.array([r[0] for r in rows[i:i + 300]], object),
                   np.array([r[1] for r in rows[i:i + 300]], object),
                   np.array([r[2] for r in rows[i:i + 300]]))
                  for i in range(0, len(rows), 300)]
        encoded = ingest.stream_encode_columns(iter(chunks))
        mesh = make_mesh(n_devices=8)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=7,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=5.0)
        ex = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                partition_extractor=lambda r: r[1],
                                value_extractor=lambda r: r[2])

        def agg(backend, data):
            acc = pdp.NaiveBudgetAccountant(total_epsilon=1e7,
                                            total_delta=1e-5)
            engine = pdp.DPEngine(acc, backend)
            result = engine.aggregate(data, params, ex)
            acc.compute_budgets()
            return dict(result)

        expected = agg(pdp.LocalBackend(seed=0), rows)
        actual = agg(pdp.TPUBackend(mesh=mesh, noise_seed=0), encoded)
        assert set(actual) == set(expected)
        for pk in expected:
            assert actual[pk].count == pytest.approx(expected[pk].count,
                                                     abs=0.05)
            assert actual[pk].sum == pytest.approx(expected[pk].sum,
                                                   abs=0.05)
