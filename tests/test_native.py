"""Tests for the native C++ DP primitives (pipelinedp_tpu/native).

Follows the reference's statistical-test strategy (SURVEY.md §4.4): large
sample draws checked for mean/std and distributional closeness (KS) against
the floating-point reference distributions, plus exact parity checks of the
calibration / partition-selection closed forms against the Python
implementations they mirror.
"""

import math

import numpy as np
import pytest
from scipy import stats

from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import native
from pipelinedp_tpu import partition_selection
from pipelinedp_tpu.aggregate_params import PartitionSelectionStrategy

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

N = 200_000


@pytest.fixture(autouse=True)
def deterministic_rng():
    native.seed_test_rng(12345)
    yield
    native.use_secure_rng()


class TestSecureNoiseDistributions:

    def test_discrete_laplace_matches_continuous(self):
        # DLap with scale t/s = 1000/1 ≈ continuous Laplace(1000).
        samples = native.discrete_laplace(1000, 1, N).astype(np.float64)
        ks = stats.kstest(samples / 1000.0, stats.laplace(scale=1).cdf)
        assert ks.statistic < 0.01, ks

    def test_discrete_gaussian_matches_continuous(self):
        # sigma^2 = 1e6 → sigma = 1000 ≫ 1 grid step.
        samples = native.discrete_gaussian(1_000_000, 1, N).astype(np.float64)
        ks = stats.kstest(samples / 1000.0, stats.norm(scale=1).cdf)
        assert ks.statistic < 0.01, ks

    def test_secure_laplace_add_moments(self):
        scale = 2.5
        out = native.secure_laplace_add(np.zeros(N), scale)
        assert abs(out.mean()) < 0.05
        assert out.std() == pytest.approx(scale * math.sqrt(2), rel=0.02)
        ks = stats.kstest(out, stats.laplace(scale=scale).cdf)
        assert ks.statistic < 0.01, ks

    def test_secure_gaussian_add_moments(self):
        sigma = 3.0
        out = native.secure_gaussian_add(np.zeros(N), sigma)
        assert abs(out.mean()) < 0.05
        assert out.std() == pytest.approx(sigma, rel=0.02)
        ks = stats.kstest(out, stats.norm(scale=sigma).cdf)
        assert ks.statistic < 0.01, ks

    def test_snapping_granularity(self):
        # All outputs must lie on the power-of-two granularity grid.
        scale = 2.5
        out = native.secure_laplace_add(np.full(100, 17.3), scale)
        g = 2.0**(math.ceil(math.log2(scale)) - 40)
        on_grid = np.abs(out / g - np.round(out / g))
        assert np.all(on_grid < 1e-6)

    def test_values_are_shifted(self):
        out = native.secure_laplace_add(np.full(1000, 100.0), 1.0)
        assert out.mean() == pytest.approx(100.0, abs=0.2)

    def test_deterministic_under_test_seed(self):
        native.seed_test_rng(7)
        a = native.discrete_laplace(100, 1, 100)
        native.seed_test_rng(7)
        b = native.discrete_laplace(100, 1, 100)
        np.testing.assert_array_equal(a, b)


class TestGaussianCalibrationParity:

    @pytest.mark.parametrize("eps,delta,l2", [
        (1.0, 1e-6, 1.0),
        (0.1, 1e-10, 3.5),
        (10.0, 1e-5, 1.0),
        (5.0, 1e-12, math.sqrt(7)),
    ])
    def test_sigma_matches_python(self, eps, delta, l2):
        assert native.gaussian_sigma(eps, delta, l2) == pytest.approx(
            dp_computations.gaussian_sigma(eps, delta, l2), rel=1e-9)

    @pytest.mark.parametrize("sigma,eps,l2", [
        (1.0, 1.0, 1.0),
        (4.0, 0.5, 2.0),
        (0.5, 30.0, 1.0),
    ])
    def test_delta_matches_python(self, sigma, eps, l2):
        assert native.gaussian_delta(sigma, eps, l2) == pytest.approx(
            dp_computations.gaussian_delta(sigma, eps, l2), rel=1e-9)


class TestPartitionSelectionParity:

    COUNTS = np.concatenate([
        np.arange(0, 50, dtype=np.int64),
        np.array([100, 1000, 100000, 10**7], dtype=np.int64)
    ])

    @pytest.mark.parametrize("pre_threshold", [None, 10])
    @pytest.mark.parametrize("eps,delta,l0", [
        (1.0, 1e-5, 1),
        (0.5, 1e-8, 3),
        (20.0, 1e-4, 2),
    ])
    def test_truncated_geometric(self, eps, delta, l0, pre_threshold):
        selector = partition_selection.create_partition_selection_strategy(
            PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, eps, delta, l0,
            pre_threshold)
        want = selector.probability_of_keep_vec(self.COUNTS)
        got = native.truncated_geometric_prob_keep(eps, delta, l0,
                                                   pre_threshold, self.COUNTS)
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-300)

    @pytest.mark.parametrize("eps,delta,l0", [
        (1.0, 1e-5, 1),
        (0.5, 1e-8, 3),
    ])
    def test_laplace_thresholding(self, eps, delta, l0):
        selector = partition_selection.create_partition_selection_strategy(
            PartitionSelectionStrategy.LAPLACE_THRESHOLDING, eps, delta, l0,
            None)
        want = selector.probability_of_keep_vec(self.COUNTS)
        got = native.laplace_prob_keep(eps, delta, l0, None, self.COUNTS)
        np.testing.assert_allclose(got, want, rtol=1e-9)
        assert native.laplace_threshold(eps, delta,
                                        l0) == pytest.approx(
                                            selector.threshold, rel=1e-12)

    @pytest.mark.parametrize("eps,delta,l0", [
        (1.0, 1e-5, 1),
        (0.5, 1e-8, 3),
    ])
    def test_gaussian_thresholding(self, eps, delta, l0):
        selector = partition_selection.create_partition_selection_strategy(
            PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING, eps, delta, l0,
            None)
        want = selector.probability_of_keep_vec(self.COUNTS)
        got = native.gaussian_prob_keep(eps, delta, l0, None, self.COUNTS)
        np.testing.assert_allclose(got, want, rtol=1e-7)
        sigma, threshold = native.gaussian_thresholding_params(eps, delta, l0)
        assert sigma == pytest.approx(selector.sigma, rel=1e-9)
        assert threshold == pytest.approx(selector.threshold, rel=1e-7)

    def test_sample_keep_frequencies(self):
        probs = np.full(N, 0.25)
        kept = native.sample_keep(probs)
        assert kept.mean() == pytest.approx(0.25, abs=0.01)

    @pytest.mark.parametrize("strategy", [
        PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
        PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
        PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
    ])
    def test_probability_of_keep_warning_clean(self, strategy):
        # The privacy path must be warning-clean even at extreme counts:
        # np.where evaluates both branches, so an unclamped exp in the
        # dead branch overflows at large n (the Laplace survival function
        # regression this test pins). Escalate every warning to an error.
        import warnings
        selector = partition_selection.create_partition_selection_strategy(
            strategy, 1.0, 1e-8, 2, None)
        counts = np.concatenate([
            self.COUNTS,
            np.array([10**9, 10**12, 10**15], dtype=np.int64)
        ])
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            probs = selector.probability_of_keep_vec(counts)
            scalar = [selector.probability_of_keep(int(c))
                      for c in (0, 1, 10**9)]
        assert np.all((probs >= 0.0) & (probs <= 1.0))
        assert probs[-1] == pytest.approx(1.0)
        assert scalar[0] == 0.0 and scalar[-1] == pytest.approx(1.0)


class TestSecureNoiseMechanismIntegration:

    def test_use_secure_noise_laplace(self):
        dp_computations.use_secure_noise(True)
        try:
            mech = dp_computations.LaplaceMechanism.create_from_epsilon(
                1.0, 1.0)
            vals = np.array([mech.add_noise(10.0) for _ in range(2000)])
            assert vals.mean() == pytest.approx(10.0, abs=0.2)
            assert vals.std() == pytest.approx(math.sqrt(2), rel=0.15)
            g = 2.0**(-40)  # scale 1.0 → granularity 2^-40
            on_grid = np.abs(vals / g - np.round(vals / g))
            assert np.all(on_grid < 1e-3)
        finally:
            dp_computations.use_secure_noise(False)

    def test_apply_mechanisms_covered_by_secure_mode(self):
        # VARIANCE / VECTOR_SUM noise flows through apply_*_mechanism — the
        # secure gate must cover those too, not just the mechanism classes.
        dp_computations.use_secure_noise(True)
        try:
            v = dp_computations.apply_laplace_mechanism(7.0, 1.0, 1.0)
            g = 2.0**(-40)  # b = 1.0 → granularity 2^-40
            assert abs(v / g - round(v / g)) < 1e-3
            v2 = dp_computations.apply_gaussian_mechanism(7.0, 1.0, 1e-6, 1.0)
            assert v2 != 7.0  # noised
        finally:
            dp_computations.use_secure_noise(False)

    def test_use_secure_noise_gaussian(self):
        dp_computations.use_secure_noise(True)
        try:
            mech = (dp_computations.GaussianMechanism
                    .create_from_epsilon_delta(1.0, 1e-6, 1.0))
            vals = np.array([mech.add_noise(5.0) for _ in range(2000)])
            assert vals.mean() == pytest.approx(5.0, abs=0.5)
            assert vals.std() == pytest.approx(mech.std, rel=0.15)
        finally:
            dp_computations.use_secure_noise(False)


class TestVocabEncode:
    """Native open-addressing vocabulary encoder (the ingest fallback when
    pandas is absent; must agree with pandas.factorize exactly)."""

    @pytest.mark.parametrize("make", [
        lambda rng: np.char.add("key_",
                                rng.integers(0, 500, 20_000).astype(str)),
        lambda rng: rng.integers(-1000, 1000, 20_000),
        lambda rng: rng.random(20_000).round(2),
        lambda rng: np.char.add("k", rng.integers(0, 3, 17).astype(str)),
    ])
    def test_matches_pandas_factorize(self, make):
        import pandas as pd
        from pipelinedp_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(11)
        arr = make(rng)
        encoded = native.vocab_encode(arr)
        assert encoded is not None
        codes, first_rows = encoded
        ref_codes, ref_uniques = pd.factorize(arr, use_na_sentinel=False)
        np.testing.assert_array_equal(codes, ref_codes)
        np.testing.assert_array_equal(arr[first_rows],
                                      np.asarray(ref_uniques))

    def test_rejects_object_dtype(self):
        from pipelinedp_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        arr = np.array([("a", 1), ("b", 2), ("a", 1)], dtype=object)
        assert native.vocab_encode(np.asarray(arr)) is None

    def test_empty(self):
        from pipelinedp_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        codes, first = native.vocab_encode(np.zeros(0, dtype=np.int64))
        assert len(codes) == 0 and len(first) == 0

    def test_factorize_without_pandas(self, monkeypatch):
        # The columnar path must route through the native encoder when
        # pandas is unavailable.
        from pipelinedp_tpu import columnar, native
        if not native.available():
            pytest.skip("native library unavailable")
        monkeypatch.setattr(columnar, "_pd", None)
        arr = np.char.add("pk", np.arange(1000).astype(str))[
            np.random.default_rng(0).integers(0, 1000, 5000)]
        codes, vocab = columnar.factorize(arr)
        assert (np.asarray(vocab)[codes] == arr).all()
        # first-occurrence order preserved (native path, not sorted unique)
        assert vocab[codes[0]] == arr[0]

    def test_factorize_object_array_with_nan(self, monkeypatch):
        # np.unique's sort-adjacency dedup breaks when NaN sits among
        # object keys (equal regular keys can land non-adjacent and get
        # TWO codes); factorize must detect this and take the dict path,
        # with all NaN keys sharing one code.
        from pipelinedp_tpu import columnar
        monkeypatch.setattr(columnar, "_pd", None)
        arr = columnar._as_key_array([1, float("nan"), 1, np.nan, 2])
        codes, vocab = columnar.factorize(arr)
        np.testing.assert_array_equal(codes, [0, 1, 0, 1, 2])
        assert vocab[0] == 1 and np.isnan(vocab[1]) and vocab[2] == 2

    def test_negative_zero_unified(self):
        from pipelinedp_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        codes, first = native.vocab_encode(np.array([0.0, -0.0, 0.0, -0.0]))
        assert list(codes) == [0, 0, 0, 0]

    def test_nan_float_keys_fall_back(self):
        from pipelinedp_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        assert native.vocab_encode(np.array([1.0, np.nan, 1.0])) is None
