"""Combiner create/merge/compute matrix (reference: tests/combiners_test.py).

Every public combiner gets the create-accumulator / merge / compute-metrics
triad tested, in both the no-noise (huge-eps) and noised regimes, plus the
factory's metric -> combiner-set mapping and worker-boundary pickling.
"""

import pickle

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, dp_computations
from pipelinedp_tpu.aggregate_params import MechanismType

HUGE_EPS = 1e6


def _params(**kwargs):
    defaults = dict(metrics=[pdp.Metrics.COUNT],
                    max_partitions_contributed=2,
                    max_contributions_per_partition=3,
                    min_value=0.0,
                    max_value=5.0)
    defaults.update(kwargs)
    return pdp.AggregateParams(**defaults)


def _spec(mechanism_type=MechanismType.LAPLACE, eps=HUGE_EPS, n_specs=1):
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=1e-6)
    specs = [accountant.request_budget(mechanism_type)
             for _ in range(n_specs)]
    accountant.compute_budgets()
    return specs[0] if n_specs == 1 else specs


class TestCountCombiner:

    def _combiner(self, eps=HUGE_EPS, mech=MechanismType.LAPLACE):
        return combiners.CountCombiner(_spec(mech, eps), _params())

    def test_create_accumulator(self):
        c = self._combiner()
        assert c.create_accumulator([]) == 0
        assert c.create_accumulator([1, 2, 3]) == 3

    def test_merge_accumulators(self):
        assert self._combiner().merge_accumulators(2, 5) == 7

    def test_compute_metrics_no_noise(self):
        got = self._combiner().compute_metrics(5)
        assert got["count"] == pytest.approx(5, abs=1e-2)

    def test_compute_metrics_with_noise(self):
        c = self._combiner(eps=1.0)
        draws = np.array([c.compute_metrics(1000)["count"]
                          for _ in range(300)])
        assert draws.std() > 1.0  # noise actually applied
        assert draws.mean() == pytest.approx(1000, abs=draws.std())

    @pytest.mark.parametrize("mech,dist", [
        (MechanismType.LAPLACE, "laplace"),
        (MechanismType.GAUSSIAN, "gaussian"),
    ])
    def test_mechanism_kind(self, mech, dist):
        c = self._combiner(mech=mech)
        assert dist in type(c.get_mechanism()).__name__.lower()

    def test_sensitivities(self):
        s = self._combiner().sensitivities()
        # l0 = max_partitions, linf = max_contributions_per_partition.
        assert s.l0 == 2 and s.linf == 3

    def test_explain_computation(self):
        text = self._combiner().explain_computation()()
        assert "DP count" in text

    def test_metrics_names(self):
        assert self._combiner().metrics_names() == ["count"]

    def test_pickle_roundtrip_drops_mechanism(self):
        c = self._combiner()
        c.get_mechanism()  # populate the lazy cache
        c2 = pickle.loads(pickle.dumps(c))
        assert c2.compute_metrics(5)["count"] == pytest.approx(5, abs=1e-2)


class TestPrivacyIdCountCombiner:

    def _combiner(self, eps=HUGE_EPS):
        return combiners.PrivacyIdCountCombiner(_spec(eps=eps), _params())

    def test_create_accumulator_is_presence_indicator(self):
        c = self._combiner()
        assert c.create_accumulator([1, 2, 3]) == 1
        assert c.create_accumulator([]) == 0

    def test_merge_and_compute(self):
        c = self._combiner()
        assert c.merge_accumulators(1, 1) == 2
        assert c.compute_metrics(9)["privacy_id_count"] == pytest.approx(
            9, abs=1e-2)

    def test_no_per_partition_sampling_needed(self):
        assert not self._combiner().expects_per_partition_sampling()

    def test_sensitivities(self):
        s = self._combiner().sensitivities()
        assert s.l0 == 2 and s.linf == 1


class TestSumCombiner:

    def _per_contribution(self, eps=HUGE_EPS):
        return combiners.SumCombiner(_spec(eps=eps),
                                     _params(metrics=[pdp.Metrics.SUM]))

    def _per_partition(self, eps=HUGE_EPS):
        params = _params(metrics=[pdp.Metrics.SUM],
                         min_value=None,
                         max_value=None,
                         min_sum_per_partition=0.0,
                         max_sum_per_partition=10.0)
        return combiners.SumCombiner(_spec(eps=eps), params)

    def test_create_accumulator_clips_each_contribution(self):
        c = self._per_contribution()
        # [-1 -> 0, 10 -> 5, 2 -> 2]
        assert c.create_accumulator([-1.0, 10.0, 2.0]) == pytest.approx(7.0)
        assert c.create_accumulator([]) == 0.0

    def test_create_accumulator_clips_partition_sum(self):
        c = self._per_partition()
        assert c.create_accumulator([20.0, 5.0]) == pytest.approx(10.0)
        assert c.create_accumulator([-50.0]) == pytest.approx(0.0)
        assert c.create_accumulator([3.0, 4.0]) == pytest.approx(7.0)

    @pytest.mark.parametrize("per_partition", [False, True])
    def test_merge_accumulators(self, per_partition):
        c = self._per_partition() if per_partition else (
            self._per_contribution())
        assert c.merge_accumulators(3.0, 4.5) == pytest.approx(7.5)

    def test_compute_metrics_no_noise(self):
        got = self._per_contribution().compute_metrics(12.5)
        assert got["sum"] == pytest.approx(12.5, abs=1e-2)

    def test_compute_metrics_with_noise(self):
        c = self._per_contribution(eps=1.0)
        draws = np.array([c.compute_metrics(100.0)["sum"]
                          for _ in range(300)])
        assert draws.std() > 1.0
        assert draws.mean() == pytest.approx(100.0, abs=3 * draws.std())

    def test_sampling_requirement_depends_on_regime(self):
        assert self._per_contribution().expects_per_partition_sampling()
        assert not self._per_partition().expects_per_partition_sampling()

    def test_per_partition_sensitivity_ignores_linf(self):
        # Per-partition bounds: linf = max(|min_sum|, |max_sum|), l0 = 2.
        s = self._per_partition().sensitivities()
        assert s.l0 == 2 and s.linf == pytest.approx(10.0)


class TestMeanCombiner:

    def _combiner(self, eps=HUGE_EPS, metrics=("mean",)):
        count_spec, sum_spec = _spec(eps=eps, n_specs=2)
        params = _params(metrics=[pdp.Metrics.MEAN], min_value=0.0,
                         max_value=10.0)
        return combiners.MeanCombiner(count_spec, sum_spec, params,
                                      list(metrics))

    def test_create_accumulator_normalizes_to_middle(self):
        c = self._combiner()
        count, nsum = c.create_accumulator([1.0, 5.0])
        assert count == 2
        assert nsum == pytest.approx((1.0 - 5.0) + (5.0 - 5.0))

    def test_create_accumulator_clips(self):
        _, nsum = self._combiner().create_accumulator([100.0])
        assert nsum == pytest.approx(5.0)  # clip to 10, normalize -5

    def test_merge(self):
        assert self._combiner().merge_accumulators((2, 1.0),
                                                   (3, -0.5)) == (5, 0.5)

    def test_compute_metrics_no_noise(self):
        got = self._combiner(metrics=("mean", "count", "sum"))
        res = got.compute_metrics((4, -8.0))  # values average 5 - 2 = 3
        assert res["mean"] == pytest.approx(3.0, abs=1e-2)
        assert res["count"] == pytest.approx(4, abs=1e-2)
        assert res["sum"] == pytest.approx(12.0, abs=0.1)

    def test_requires_mean_in_metrics(self):
        with pytest.raises(ValueError, match="mean"):
            self._combiner(metrics=("count",))

    def test_rejects_duplicates(self):
        with pytest.raises(ValueError):
            self._combiner(metrics=("mean", "mean"))

    def test_rejects_unknown_metric(self):
        with pytest.raises(ValueError):
            self._combiner(metrics=("mean", "variance"))


class TestVarianceCombiner:

    def _combiner(self, eps=HUGE_EPS, metrics=("variance",)):
        params = _params(metrics=[pdp.Metrics.VARIANCE], min_value=0.0,
                         max_value=12.0, max_contributions_per_partition=5)
        return combiners.VarianceCombiner(
            combiners.CombinerParams(_spec(eps=eps), params), list(metrics))

    def test_create_accumulator(self):
        count, nsum, nsum2 = self._combiner().create_accumulator([2.0, 8.0])
        assert count == 2
        assert nsum == pytest.approx((2 - 6) + (8 - 6))
        assert nsum2 == pytest.approx(16 + 4)

    def test_merge(self):
        got = self._combiner().merge_accumulators((1, 2.0, 4.0),
                                                  (2, -1.0, 1.0))
        assert got == (3, 1.0, 5.0)

    def test_compute_metrics_no_noise(self):
        c = self._combiner(metrics=("variance", "mean", "count", "sum"))
        values = np.array([2.0, 4.0, 6.0, 8.0])
        acc = c.create_accumulator(values)
        res = c.compute_metrics(acc)
        assert res["count"] == pytest.approx(4, abs=1e-2)
        assert res["mean"] == pytest.approx(values.mean(), abs=1e-2)
        assert res["variance"] == pytest.approx(values.var(), abs=0.3)

    def test_requires_variance_in_metrics(self):
        with pytest.raises(ValueError, match="variance"):
            self._combiner(metrics=("mean",))


class TestQuantileCombiner:

    def _combiner(self, percentiles=(50,), eps=HUGE_EPS):
        params = _params(metrics=[pdp.Metrics.PERCENTILE(p)
                                  for p in percentiles],
                         min_value=0.0, max_value=100.0)
        return combiners.QuantileCombiner(
            combiners.CombinerParams(_spec(eps=eps), params),
            list(percentiles))

    def test_accumulator_is_serialized_bytes(self):
        acc = self._combiner().create_accumulator([1.0, 2.0])
        assert isinstance(acc, bytes)

    def test_merge_is_tree_merge(self):
        c = self._combiner()
        left = c.create_accumulator([10.0] * 50)
        right = c.create_accumulator([90.0] * 50)
        merged = c.merge_accumulators(left, right)
        res = c.compute_metrics(merged)
        assert 10.0 <= res["percentile_50"] <= 90.0

    def test_compute_metrics_no_noise(self):
        c = self._combiner(percentiles=(25, 75))
        acc = c.create_accumulator(list(np.linspace(0, 100, 1000)))
        res = c.compute_metrics(acc)
        assert res["percentile_25"] == pytest.approx(25.0, abs=2.0)
        assert res["percentile_75"] == pytest.approx(75.0, abs=2.0)

    def test_metrics_names_formatting(self):
        c = self._combiner(percentiles=(25, 99.9))
        assert c.metrics_names() == ["percentile_25", "percentile_99_9"]

    def test_pickles_across_worker_boundary(self):
        c = self._combiner()
        acc = c.create_accumulator([50.0] * 100)
        c2 = pickle.loads(pickle.dumps(c))
        res = c2.compute_metrics(acc)
        assert res["percentile_50"] == pytest.approx(50.0, abs=2.0)


class TestVectorSumCombiner:

    def _combiner(self, eps=HUGE_EPS):
        params = _params(metrics=[pdp.Metrics.VECTOR_SUM],
                         min_value=None, max_value=None,
                         max_contributions_per_partition=10,
                         vector_norm_kind=pdp.NormKind.Linf,
                         vector_max_norm=100.0, vector_size=2)
        return combiners.VectorSumCombiner(
            combiners.CombinerParams(_spec(eps=eps), params))

    def test_create_accumulator(self):
        got = self._combiner().create_accumulator(
            [np.array([1.0, 2.0]), np.array([3.0, 4.0])])
        np.testing.assert_allclose(got, [4.0, 6.0])

    def test_create_accumulator_empty(self):
        np.testing.assert_allclose(self._combiner().create_accumulator([]),
                                   [0.0, 0.0])

    def test_shape_mismatch_raises(self):
        with pytest.raises(TypeError, match="Shape mismatch"):
            self._combiner().create_accumulator([np.array([1.0, 2.0, 3.0])])

    def test_merge(self):
        got = self._combiner().merge_accumulators(np.array([1.0, 1.0]),
                                                  np.array([2.0, 3.0]))
        np.testing.assert_allclose(got, [3.0, 4.0])

    def test_compute_metrics_no_noise(self):
        res = self._combiner().compute_metrics(np.array([5.0, -2.0]))
        np.testing.assert_allclose(res["vector_sum"], [5.0, -2.0], atol=0.1)


class TestCompoundCombiner:

    def _compound(self, eps=HUGE_EPS):
        params = _params(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM])
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                               total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, accountant)
        accountant.compute_budgets()
        return compound

    def test_accumulator_carries_row_count(self):
        compound = self._compound()
        acc = compound.create_accumulator([1.0, 2.0])
        assert acc[0] == 1
        count_acc, sum_acc = acc[1]
        assert count_acc == 2 and sum_acc == pytest.approx(3.0)

    def test_merge_sums_row_count_and_children(self):
        compound = self._compound()
        a = compound.create_accumulator([1.0])
        b = compound.create_accumulator([2.0, 3.0])
        row_count, (count_acc, sum_acc) = compound.merge_accumulators(a, b)
        assert row_count == 2
        assert count_acc == 3 and sum_acc == pytest.approx(6.0)

    def test_compute_metrics_returns_named_tuple(self):
        compound = self._compound()
        acc = compound.create_accumulator([1.0, 4.0])
        res = compound.compute_metrics(acc)
        assert res._fields == ("count", "sum")
        assert res.count == pytest.approx(2, abs=1e-2)
        assert res.sum == pytest.approx(5.0, abs=1e-2)

    def test_named_tuple_pickles(self):
        compound = self._compound()
        res = compound.compute_metrics(compound.create_accumulator([1.0]))
        res2 = pickle.loads(pickle.dumps(res))
        assert res2 == res

    def test_duplicate_metric_names_rejected(self):
        params = _params()
        specs = _spec(n_specs=2)
        dup = [combiners.CountCombiner(specs[0], params),
               combiners.CountCombiner(specs[1], params)]
        with pytest.raises(ValueError):
            combiners.CompoundCombiner(dup, return_named_tuple=True)


class TestCreateCompoundCombiner:

    CASES = [
        ([pdp.Metrics.COUNT], [combiners.CountCombiner], 1),
        ([pdp.Metrics.SUM], [combiners.SumCombiner], 1),
        ([pdp.Metrics.PRIVACY_ID_COUNT],
         [combiners.PrivacyIdCountCombiner], 1),
        ([pdp.Metrics.COUNT, pdp.Metrics.SUM],
         [combiners.CountCombiner, combiners.SumCombiner], 2),
        ([pdp.Metrics.MEAN], [combiners.MeanCombiner], 2),
        # MEAN folds COUNT and SUM into one mechanism pair.
        ([pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
         [combiners.MeanCombiner], 2),
        ([pdp.Metrics.VARIANCE], [combiners.VarianceCombiner], 1),
        # VARIANCE subsumes all of mean/count/sum.
        ([pdp.Metrics.VARIANCE, pdp.Metrics.MEAN, pdp.Metrics.COUNT],
         [combiners.VarianceCombiner], 1),
        ([pdp.Metrics.COUNT, pdp.Metrics.PRIVACY_ID_COUNT],
         [combiners.CountCombiner, combiners.PrivacyIdCountCombiner], 2),
        # All percentiles share one QuantileCombiner and one budget.
        ([pdp.Metrics.PERCENTILE(10), pdp.Metrics.PERCENTILE(90)],
         [combiners.QuantileCombiner], 1),
    ]

    @pytest.mark.parametrize("metrics,expected_types,expected_requests",
                             CASES)
    def test_metric_to_combiner_mapping(self, metrics, expected_types,
                                        expected_requests):
        params = _params(metrics=metrics)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, accountant)
        assert [type(c) for c in compound.combiners] == expected_types
        assert len(accountant._mechanisms) == expected_requests
        accountant.compute_budgets()

    def test_vector_sum_mapping(self):
        params = _params(metrics=[pdp.Metrics.VECTOR_SUM],
                         min_value=None, max_value=None,
                         vector_norm_kind=pdp.NormKind.L2,
                         vector_max_norm=10.0, vector_size=3)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, accountant)
        assert [type(c) for c in compound.combiners
                ] == [combiners.VectorSumCombiner]
        accountant.compute_budgets()


class TestCustomCombiners:

    class SumOfSquares(combiners.CustomCombiner):

        def create_accumulator(self, values):
            return float(sum(v**2 for v in values))

        def merge_accumulators(self, a, b):
            return a + b

        def compute_metrics(self, acc):
            return {"sum_squares": acc}

        def explain_computation(self):
            return lambda: "sum of squares"

        def request_budget(self, budget_accountant):
            self._budget = budget_accountant.request_budget(
                MechanismType.LAPLACE)

        def metrics_names(self):
            return ["sum_squares"]

    def test_custom_compound_plain_tuple_output(self):
        params = _params(custom_combiners=[])
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        custom = self.SumOfSquares()
        compound = combiners.create_compound_combiner_with_custom_combiners(
            params, accountant, [custom])
        accountant.compute_budgets()
        acc = compound.create_accumulator([2.0, 3.0])
        assert acc[1][0] == pytest.approx(13.0)
        res = compound.compute_metrics(acc)
        assert res == ({"sum_squares": 13.0},)

    def test_custom_combiner_receives_params_and_budget(self):
        params = _params(custom_combiners=[])
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        custom = self.SumOfSquares()
        combiners.create_compound_combiner_with_custom_combiners(
            params, accountant, [custom])
        accountant.compute_budgets()
        assert custom._budget.eps == pytest.approx(1.0)
        assert custom._aggregate_params is not None
