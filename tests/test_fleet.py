"""Fleet operations: elastic scale-UP, journal-based job migration,
and the zero-loss rolling-restart drill.

The contracts under test:

  * **Scale-UP bit-identity** — a run that admits joining devices at a
    block boundary (retry.run_with_mesh_elasticity) releases outputs
    bit-identical to the fixed-geometry run: block keys are
    fold_in(final_key, b), pure functions of the run key and block
    index, independent of mesh size — growing is a re-plan, never a
    re-release.
  * **Join-failure abort** — a joiner that fails its admission probe
    (injected host_join_failure) aborts the grow back onto the OLD
    mesh; the run completes bit-identically and the ticket is spent.
  * **Drain-and-migrate** — an interrupted journaled run's records and
    odometer trail, adopted into a different controller scope
    (BlockJournal.adopt_job), resume at a DIFFERENT geometry with
    bit-identical outputs and the same mechanism trail — the tenant
    ledger's idempotent charge makes the carried-over trail impossible
    to double-spend.
  * **Mid-persist restart** — a kill between the ledger fsync and the
    rename (restart_during_persist) leaves the prior on-disk trail
    intact and the new record absent: crash-atomicity of the ledger of
    record.
  * **The rolling-restart drill** — a sustained submit loop survives
    every service instance being bounced in turn, including one job
    killed mid-persist: zero lost jobs, every tenant's disk spend
    reconciling bit-exactly, no epsilon double-spend.
"""

import collections

import numpy as np
import pytest

import jax

import pipelinedp_tpu as pdp
from pipelinedp_tpu.parallel import large_p, make_mesh
from pipelinedp_tpu.runtime import BlockJournal
from pipelinedp_tpu.runtime import drill as drill_lib
from pipelinedp_tpu.runtime import faults
from pipelinedp_tpu.runtime import health as health_lib
from pipelinedp_tpu.runtime import journal as journal_lib
from pipelinedp_tpu.runtime import observability as obs
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.service import JobSpec, TenantLedger

from test_elastic import (FAST, _blocked_agg_runner,
                          _blocked_select_runner)

pytestmark = pytest.mark.fleet


@pytest.fixture(autouse=True)
def _fleet_isolation():
    """Join tickets are process-global; a test that leaves one pending
    would grow the NEXT elastic test's mesh."""
    retry_lib.clear_joins()
    yield
    retry_lib.clear_joins()


GROW_DRIVERS = [
    ("blocked_aggregate", _blocked_agg_runner),
    ("blocked_select", _blocked_select_runner),
]


class TestScaleUp:

    @pytest.mark.parametrize("name,runner", GROW_DRIVERS,
                             ids=[d[0] for d in GROW_DRIVERS])
    def test_grow_mid_run_bit_identical(self, name, runner):
        """4 -> 8 devices at the block-2 boundary: outputs bit-equal to
        the fixed 4-device run (and, by test_elastic's cross-D pins, to
        the fixed 8-device run), expansion counted, gauge set, the
        job's record annotated REJOINING."""
        key = jax.random.PRNGKey(61)
        base = runner(make_mesh(n_devices=4), key)
        job = f"grow-{name}"
        before = telemetry.snapshot()
        retry_lib.announce_join(n_devices=8, block=2)
        got = runner(make_mesh(n_devices=4), key, retry=FAST,
                     elastic_grow=True, job_id=job)
        assert retry_lib.pending_joins() == 0  # ticket consumed
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        delta = telemetry.delta(before)
        assert delta.get("mesh_expansions") == 1, delta
        assert delta.get("mesh_degradations", 0) == 0, delta
        gauges = telemetry.gauge_snapshot().get("mesh_target_devices", {})
        assert 8.0 in gauges.values(), gauges
        snap = health_lib.for_job(job).snapshot()
        kinds = [e["kind"] for e in snap["fleet_events"]]
        assert "REJOINING" in kinds, snap["fleet_events"]

    def test_grow_with_journal_replays_consumed_blocks(self, tmp_path):
        """Blocks drained before the boundary are NOT re-dispatched on
        the grown mesh — the journal replays them, exactly as it does
        for a shrink."""
        key = jax.random.PRNGKey(67)
        base = _blocked_agg_runner(make_mesh(n_devices=4), key)
        journal = BlockJournal(str(tmp_path))
        before = telemetry.snapshot()
        retry_lib.announce_join(n_devices=8, block=2)
        got = _blocked_agg_runner(make_mesh(n_devices=4), key,
                                  journal=journal, retry=FAST,
                                  elastic_grow=True, job_id="grow-replay")
        assert retry_lib.pending_joins() == 0
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        delta = telemetry.delta(before)
        assert delta.get("mesh_expansions") == 1, delta
        assert delta.get("journal_replays", 0) >= 1, delta

    def test_join_failure_aborts_back_to_old_mesh(self):
        """An injected host_join_failure during admission: the grow
        aborts, the run CONTINUES on the old mesh bit-identically, the
        ticket is spent (no retry storm), no expansion is counted."""
        key = jax.random.PRNGKey(71)
        base = _blocked_agg_runner(make_mesh(n_devices=4), key)
        sched = faults.FaultSchedule([faults.Fault("host_join_failure")])
        before = telemetry.snapshot()
        retry_lib.announce_join(n_devices=8, block=2)
        with faults.inject(sched):
            got = _blocked_agg_runner(make_mesh(n_devices=4), key,
                                      retry=FAST, elastic_grow=True,
                                      job_id="grow-abort")
        assert sched.pending() == 0
        assert retry_lib.pending_joins() == 0  # spent, not retried
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        delta = telemetry.delta(before)
        assert delta.get("mesh_expansions", 0) == 0, delta
        assert delta.get("injected_faults", 0) >= 1, delta
        snap = health_lib.for_job("grow-abort").snapshot()
        assert any(e["kind"] == "REJOINING" and "abort" in e["detail"]
                   for e in snap["fleet_events"]), snap["fleet_events"]

    def test_announce_ignored_without_elastic_grow(self):
        """Growth is opt-in per driver invocation: a pending ticket must
        not perturb a plain run (or a shrink-only elastic run), and
        must still be pending afterwards."""
        key = jax.random.PRNGKey(73)
        base = _blocked_agg_runner(make_mesh(n_devices=4), key)
        retry_lib.announce_join(n_devices=8, block=2)
        got = _blocked_agg_runner(make_mesh(n_devices=4), key)
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        assert retry_lib.pending_joins() == 1
        got = _blocked_agg_runner(make_mesh(n_devices=4), key,
                                  retry=FAST, elastic=True)
        assert np.array_equal(base[1], got[1])
        assert retry_lib.pending_joins() == 1


MIGRATE_DRIVERS = [
    ("blocked_aggregate", _blocked_agg_runner),
    ("blocked_select", _blocked_select_runner),
]


class TestMigration:

    @pytest.mark.parametrize("name,runner", MIGRATE_DRIVERS,
                             ids=[d[0] for d in MIGRATE_DRIVERS])
    @pytest.mark.parametrize("resume_devices", [2, 8])
    def test_resume_at_new_geometry_bit_identical(
            self, name, runner, resume_devices, tmp_path):
        """The migration matrix: a journaled run interrupted at block 2
        on a 4-device mesh, its records + odometer trail adopted into a
        fresh controller scope, resumed at 2 and at 8 devices — outputs
        bit-identical to the clean fixed-geometry run, mechanism trail
        equal, migration counted."""
        key = jax.random.PRNGKey(79)
        job = f"migrate-{name}-{resume_devices}"
        base = runner(make_mesh(n_devices=4), key)
        # Pod A's controller journals blocks under ITS process scope
        # (what runtime/entry auto-scoping does on a real pod) and
        # persists its odometer trail there before exiting.
        source = BlockJournal(str(tmp_path)).scoped_to_process(0)
        sched = faults.FaultSchedule([faults.Fault("fatal", block=2)])
        with faults.inject(sched):
            with pytest.raises(faults.InjectedFatalError):
                runner(make_mesh(n_devices=4), key, journal=source,
                       retry=FAST, job_id=job)
        assert sched.pending() == 0
        obs.persist_odometer(source, job)
        # Pod B: a DIFFERENT controller scope over the same directory
        # adopts the trail, then resumes at a different geometry.
        target = BlockJournal(str(tmp_path)).scoped_to_process(1)
        before = telemetry.snapshot()
        adopted = target.adopt_job(job)
        assert adopted >= 1, "nothing migrated"
        carried = obs.load_odometer(target, job)
        assert len(carried) >= 1, "odometer trail did not carry over"
        got = runner(make_mesh(n_devices=resume_devices), key,
                     journal=target, retry=FAST, job_id=job)
        assert np.array_equal(base[0], got[0])
        assert np.array_equal(base[1], got[1])
        delta = telemetry.delta(before)
        assert delta.get("job_migrations") == 1, delta
        assert delta.get("journal_replays", 0) >= 1, delta

    def test_migrated_trail_mechanism_counts_match_clean_run(
            self, tmp_path):
        """The resumed job's persisted mechanism trail (per-kind counts
        for THIS job) equals a clean fixed-geometry run's — migration
        neither drops nor duplicates ledger mechanisms."""
        key = jax.random.PRNGKey(83)

        def _job_kinds(journal, job):
            trail = obs.load_odometer(journal, job)
            return collections.Counter(
                r["mechanism_kind"] for r in trail
                if r["job_id"] == job)

        clean_dir = tmp_path / "clean"
        clean_dir.mkdir()
        clean = BlockJournal(str(clean_dir))
        # The runners build their accountant inside the call; the job
        # scope stamps those mechanism registrations with the job id the
        # persisted trail is audited under.
        with health_lib.job_scope("mig-clean"):
            _blocked_agg_runner(make_mesh(n_devices=4), key,
                                journal=clean, job_id="mig-clean")
        want = _job_kinds(clean, "mig-clean")
        assert sum(want.values()) >= 1

        mig_dir = tmp_path / "mig"
        mig_dir.mkdir()
        source = BlockJournal(str(mig_dir)).scoped_to_process(0)
        sched = faults.FaultSchedule([faults.Fault("fatal", block=2)])
        with faults.inject(sched):
            with pytest.raises(faults.InjectedFatalError):
                with health_lib.job_scope("mig-moved"):
                    _blocked_agg_runner(make_mesh(n_devices=4), key,
                                        journal=source, retry=FAST,
                                        job_id="mig-moved")
        obs.persist_odometer(source, "mig-moved")
        # The resume runs on pod B — a fresh process whose in-memory
        # trail starts empty. Model that here, or the in-process resume
        # would stack a second registration set on the source's.
        obs.prune_odometer(job_id="mig-moved")
        target = BlockJournal(str(mig_dir)).scoped_to_process(1)
        assert target.adopt_job("mig-moved") >= 1
        with health_lib.job_scope("mig-moved"):
            _blocked_agg_runner(make_mesh(n_devices=2), key,
                                journal=target, retry=FAST,
                                job_id="mig-moved")
        # The resume's teardown re-persisted the trail under the target
        # scope; the job's own mechanism counts must match the clean run.
        assert _job_kinds(target, "mig-moved") == want

    def test_adopt_job_imports_foreign_scope_once(self, tmp_path):
        """Unit: records written under p0 become visible under p1 after
        adopt_job; a second adopt is a no-op (records present are this
        controller's own truth); the migration is counted and annotated
        on the job's health record."""
        journal = BlockJournal(str(tmp_path))
        src = journal.scoped_to_process(0)
        record = journal_lib.BlockRecord(
            ids=np.arange(4, dtype=np.int64),
            outputs={"sum": np.ones(4)})
        src.put("adopt-job", "b0__g1", record)
        dst = BlockJournal(str(tmp_path)).scoped_to_process(1)
        assert dst.get("adopt-job", "b0__g1") is None
        before = telemetry.snapshot()
        assert dst.adopt_job("adopt-job") == 1
        got = dst.get("adopt-job", "b0__g1")
        assert got is not None
        assert np.array_equal(got.ids, record.ids)
        assert telemetry.delta(before).get("job_migrations") == 1
        assert dst.adopt_job("adopt-job") == 0  # idempotent
        snap = health_lib.for_job("adopt-job").snapshot()
        assert any(e["kind"] == "MIGRATING"
                   for e in snap["fleet_events"]), snap["fleet_events"]

    def test_adopt_job_with_nothing_to_migrate(self, tmp_path):
        journal = BlockJournal(str(tmp_path)).scoped_to_process(1)
        before = telemetry.snapshot()
        assert journal.adopt_job("ghost-job") == 0
        assert "job_migrations" not in telemetry.delta(before)

    def test_adopt_job_requires_directory(self):
        with pytest.raises(ValueError, match="directory-backed"):
            BlockJournal().adopt_job("any-job")


class TestRestartDuringPersist:

    def test_point_validation(self):
        faults.Fault("restart_during_persist", point="odometer")
        faults.Fault("restart_during_persist", point="block")
        with pytest.raises(ValueError):
            faults.Fault("restart_during_persist", point="dispatch")

    def test_kill_between_fsync_and_rename_keeps_prior_trail(
            self, tmp_path):
        """The drill's signature window: the new trail's temp file is
        fsync'd but never renamed — the PRIOR persisted trail stays the
        on-disk truth, and no half-written record exists."""
        journal = BlockJournal(str(tmp_path))
        obs.persist_odometer(journal, "persist-job", records=[{
            "seq": 0, "job_id": "persist-job", "metric": "count",
            "mechanism_kind": "laplace", "weight": 1.0,
            "sensitivity": 2.0, "count": 1, "process_index": 0,
            "eps": 0.5, "delta": 0.0}])
        prior = obs.load_odometer(journal, "persist-job")
        assert len(prior) == 1
        sched = faults.FaultSchedule([
            faults.Fault("restart_during_persist", point="odometer")])
        with faults.inject(sched):
            with pytest.raises(faults.InjectedRestartError):
                obs.persist_odometer(journal, "persist-job", records=[{
                    "seq": 1, "job_id": "persist-job", "metric": "sum",
                    "mechanism_kind": "laplace", "weight": 1.0,
                    "sensitivity": 2.0, "count": 1, "process_index": 0,
                    "eps": 0.25, "delta": 0.0}])
        assert sched.pending() == 0
        # A fresh journal over the same directory (the restarted
        # process) sees the prior trail, bit-exact, and nothing else.
        reread = obs.load_odometer(BlockJournal(str(tmp_path)),
                                   "persist-job")
        assert reread == prior

    def test_odometer_point_does_not_hit_block_writes(self, tmp_path):
        """point="odometer" scopes the kill to the ledger trail — block
        record persists keep landing (and vice versa: a pending
        "block"-point fault must not fire on an odometer persist)."""
        journal = BlockJournal(str(tmp_path))
        record = journal_lib.BlockRecord(ids=np.arange(2, dtype=np.int64),
                                         outputs={"sum": np.ones(2)})
        sched = faults.FaultSchedule([
            faults.Fault("restart_during_persist", point="odometer")])
        with faults.inject(sched):
            journal.put("scope-job", "b0__g1", record)  # unharmed
            assert sched.pending() == 1
        assert journal.get("scope-job", "b0__g1") is not None
        block_sched = faults.FaultSchedule([
            faults.Fault("restart_during_persist", point="block")])
        with faults.inject(block_sched):
            obs.persist_odometer(journal, "scope-job", records=[])
            assert block_sched.pending() == 1
            with pytest.raises(faults.InjectedRestartError):
                journal.put("scope-job", "b1__g1", record)
        # The killed writer's in-memory cache dies with the process; the
        # restarted view (a fresh journal over the directory) must not
        # see the never-renamed record.
        assert BlockJournal(str(tmp_path)).get("scope-job",
                                               "b1__g1") is None


class TestTenantLedgerIdempotentCharge:

    ROWS = [{"seq": 0, "job_id": None, "metric": "count",
             "mechanism_kind": "laplace", "weight": 1.0,
             "sensitivity": 2.0, "count": 1, "process_index": 0,
             "eps": 0.5, "delta": 0.0},
            {"seq": 1, "job_id": None, "metric": "sum",
             "mechanism_kind": "laplace", "weight": 1.0,
             "sensitivity": 2.0, "count": 1, "process_index": 0,
             "eps": 0.25, "delta": 0.0}]

    def test_charge_is_idempotent_per_job(self, tmp_path):
        """A migrated job re-charging its carried-over trail on the
        target pod (or a restarted service replaying a persisted
        completion) records each job EXACTLY once — same returned
        spend, no trail growth, no double-spend on disk."""
        journal = BlockJournal(str(tmp_path))
        ledger = TenantLedger("acme", 10.0, journal)
        ledger.reserve("job-1", 1.0)
        spent = ledger.charge("job-1", self.ROWS)
        assert spent == 0.75
        trail_len = len(ledger.records())
        again = ledger.charge("job-1", self.ROWS)
        assert again == spent
        assert len(ledger.records()) == trail_len
        assert ledger.spent_epsilon() == spent
        # The restarted-service view agrees: one job, one trail.
        reloaded = TenantLedger("acme", 10.0,
                                BlockJournal(str(tmp_path)))
        assert reloaded.spent_epsilon() == spent
        seqs = [r["seq"] for r in reloaded.records()]
        assert len(seqs) == len(set(seqs)) == trail_len

    def test_distinct_jobs_still_accumulate(self, tmp_path):
        ledger = TenantLedger("acme", 10.0, BlockJournal(str(tmp_path)))
        ledger.reserve("job-1", 1.0)
        ledger.charge("job-1", self.ROWS)
        ledger.reserve("job-2", 1.0)
        ledger.charge("job-2", self.ROWS)
        assert ledger.spent_epsilon() == 1.5
        assert ledger.job_spent_epsilon("job-2") == 0.75


def _drill_params():
    return pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                        pdp.Metrics.SUM],
                               max_partitions_contributed=2,
                               max_contributions_per_partition=3,
                               min_value=0.0,
                               max_value=5.0)


def _drill_jobs():
    rows_a = [("u1", "A", 1.0), ("u1", "B", 2.0), ("u2", "A", 1.0),
              ("u3", "B", 3.0)]
    rows_b = [("v1", "X", 4.0), ("v2", "X", 2.0), ("v2", "Y", 2.0)]
    ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                             partition_extractor=lambda r: r[1],
                             value_extractor=lambda r: r[2])

    def spec(seed, public):
        return JobSpec(params=_drill_params(), epsilon=1.0, delta=1e-6,
                       data_extractors=ext, noise_seed=seed,
                       public_partitions=public)

    return [
        drill_lib.LogicalJob("acme-j1", "acme", spec(11, ["A", "B"]),
                             rows_a),
        drill_lib.LogicalJob("acme-j2", "acme", spec(13, ["A", "B"]),
                             rows_a),
        drill_lib.LogicalJob("beta-j1", "beta", spec(17, ["X", "Y"]),
                             rows_b),
        drill_lib.LogicalJob("beta-j2", "beta", spec(19, ["X", "Y"]),
                             rows_b),
    ]


class TestRollingRestartDrill:

    def test_zero_loss_with_mid_persist_kill(self, tmp_path):
        """The drill end-to-end: 4 logical jobs across 2 tenants survive
        3 service bounces, one job killed between its ledger's fsync and
        rename. Gates (enforced inside the drill, re-asserted here):
        nothing lost, nothing double-charged, disk reconciles."""
        before = telemetry.snapshot()
        report = drill_lib.rolling_restart_drill(
            _drill_jobs(), str(tmp_path), waves=3)
        assert report["zero_loss"] is True
        assert report["injected_failures"] == 1
        assert report["resubmissions"] >= 1  # the killed job came back
        assert not report["unexpected_failures"]
        assert set(report["completed"]) == {"acme-j1", "acme-j2",
                                            "beta-j1", "beta-j2"}
        assert report["bounces"] >= report["waves"]
        # Disk spend per tenant == the handles' bit-exact sums.
        by_tenant = collections.defaultdict(float)
        for entry in report["completed"].values():
            by_tenant[entry["tenant_id"]] += entry["spent_epsilon"]
        assert report["disk_spend_epsilon"] == dict(by_tenant)
        assert telemetry.delta(before).get("rolling_restarts", 0) >= \
            report["bounces"]

    def test_drill_validates_its_inputs(self, tmp_path):
        with pytest.raises(ValueError, match="waves"):
            drill_lib.rolling_restart_drill(_drill_jobs(),
                                            str(tmp_path), waves=1)
        dup = _drill_jobs()
        dup[1] = dataclasses_replace_name(dup[1], dup[0].name)
        with pytest.raises(ValueError, match="unique"):
            drill_lib.rolling_restart_drill(dup, str(tmp_path))


def dataclasses_replace_name(job, name):
    import dataclasses
    return dataclasses.replace(job, name=name)
