"""End-to-end DPEngine tests on LocalBackend and the fused TPU path.

Follows the reference test strategy (SURVEY.md §4): huge-epsilon determinism
for value checks, backend-parameterized identical test bodies, mocked
partition selection for deterministic private-partition tests.
"""

import math
from unittest import mock

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import dp_computations


def make_backend(name):
    if name == "local":
        return pdp.LocalBackend(seed=42)
    return pdp.TPUBackend(noise_seed=42)


BACKENDS = ["local", "tpu"]

HUGE_EPS = 1e7


def run_aggregate(backend_name,
                  rows,
                  params,
                  public_partitions=None,
                  total_epsilon=HUGE_EPS,
                  total_delta=1e-5,
                  extractors=None):
    backend = make_backend(backend_name)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=total_epsilon,
                                           total_delta=total_delta)
    engine = pdp.DPEngine(accountant, backend)
    if extractors is None:
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
    result = engine.aggregate(rows, params, extractors, public_partitions)
    accountant.compute_budgets()
    return dict(result), engine


# rows: (privacy_id, partition, value)
SIMPLE_ROWS = [
    ("u1", "A", 1.0),
    ("u1", "A", 2.0),
    ("u1", "B", 3.0),
    ("u2", "A", 4.0),
    ("u2", "B", 1.0),
    ("u3", "A", 2.0),
]


class TestAggregatePublicPartitions:

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_count_sum_exact_with_huge_eps(self, backend_name):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=2,
            max_contributions_per_partition=2,
            min_value=0.0,
            max_value=5.0)
        result, _ = run_aggregate(backend_name, SIMPLE_ROWS, params,
                                  public_partitions=["A", "B", "C"])
        assert set(result) == {"A", "B", "C"}
        # A: u1 (2 contributions), u2, u3 -> count 4, sum 1+2+4+2 = 9
        assert result["A"].count == pytest.approx(4, abs=1e-2)
        assert result["A"].sum == pytest.approx(9.0, abs=1e-2)
        # B: u1, u2 -> count 2, sum 4
        assert result["B"].count == pytest.approx(2, abs=1e-2)
        assert result["B"].sum == pytest.approx(4.0, abs=1e-2)
        # C: empty public partition is present with ~0s.
        assert result["C"].count == pytest.approx(0, abs=1e-2)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_value_clipping(self, backend_name):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=3,
                                     min_value=0.0,
                                     max_value=1.0)
        result, _ = run_aggregate(backend_name, SIMPLE_ROWS, params,
                                  public_partitions=["A", "B"])
        # A: values 1,2,4,2 clipped to 1,1,1,1 -> 4; B: 3,1 -> 1+1 = 2
        assert result["A"].sum == pytest.approx(4.0, abs=1e-2)
        assert result["B"].sum == pytest.approx(2.0, abs=1e-2)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_partition_sum_clipping(self, backend_name):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=5,
                                     min_sum_per_partition=0.0,
                                     max_sum_per_partition=2.5)
        result, _ = run_aggregate(backend_name, SIMPLE_ROWS, params,
                                  public_partitions=["A", "B"])
        # A: u1 sum 3 -> clipped 2.5; u2 sum 4 -> 2.5; u3 2 -> 2. total 7
        assert result["A"].sum == pytest.approx(7.0, abs=1e-2)
        # B: u1 3 -> 2.5, u2 1 -> 1. total 3.5
        assert result["B"].sum == pytest.approx(3.5, abs=1e-2)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_privacy_id_count(self, backend_name):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=2,
            max_contributions_per_partition=2)
        result, _ = run_aggregate(backend_name, SIMPLE_ROWS, params,
                                  public_partitions=["A", "B"])
        assert result["A"].privacy_id_count == pytest.approx(3, abs=1e-2)
        assert result["B"].privacy_id_count == pytest.approx(2, abs=1e-2)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_mean(self, backend_name):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.MEAN, pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=2,
            max_contributions_per_partition=3,
            min_value=0.0,
            max_value=5.0)
        result, _ = run_aggregate(backend_name, SIMPLE_ROWS, params,
                                  public_partitions=["A", "B"])
        assert result["A"].mean == pytest.approx(9.0 / 4, abs=1e-2)
        assert result["A"].count == pytest.approx(4, abs=1e-2)
        assert result["A"].sum == pytest.approx(9.0, abs=0.05)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_variance(self, backend_name):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VARIANCE, pdp.Metrics.MEAN],
            max_partitions_contributed=2,
            max_contributions_per_partition=3,
            min_value=0.0,
            max_value=5.0)
        result, _ = run_aggregate(backend_name, SIMPLE_ROWS, params,
                                  public_partitions=["A"])
        values_a = [1.0, 2.0, 4.0, 2.0]
        assert result["A"].variance == pytest.approx(np.var(values_a),
                                                     abs=0.05)
        assert result["A"].mean == pytest.approx(np.mean(values_a), abs=0.05)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_linf_bounding_caps_contributions(self, backend_name):
        rows = [("u1", "A", 1.0)] * 10  # one user, 10 contributions
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=3)
        result, _ = run_aggregate(backend_name, rows, params,
                                  public_partitions=["A"])
        assert result["A"].count == pytest.approx(3, abs=1e-2)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_l0_bounding_caps_partitions(self, backend_name):
        rows = [("u1", pk, 1.0) for pk in "ABCDEFGH"]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=1)
        result, _ = run_aggregate(backend_name, rows, params,
                                  public_partitions=list("ABCDEFGH"))
        total = sum(result[pk].count for pk in "ABCDEFGH")
        assert total == pytest.approx(3, abs=0.05)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_max_contributions_total_bound(self, backend_name):
        rows = [("u1", "A", 1.0)] * 6 + [("u1", "B", 1.0)] * 6
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_contributions=4)
        result, _ = run_aggregate(backend_name, rows, params,
                                  public_partitions=["A", "B"])
        total = result["A"].count + result["B"].count
        assert total == pytest.approx(4, abs=0.05)

    def test_max_contributions_total_bound_blocked_routes(self):
        # The total per-user bound must hold through the blocked large-P
        # route too (single-device and meshed): _bound_compact_trace runs
        # the same bounded_row_columns total-bound pass.
        from pipelinedp_tpu.parallel import make_mesh
        rows = [("u1", "A", 1.0)] * 6 + [("u1", "B", 1.0)] * 6
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_contributions=4)
        for backend in (
                pdp.TPUBackend(noise_seed=1, large_partition_threshold=1),
                pdp.TPUBackend(noise_seed=1, large_partition_threshold=1,
                               mesh=make_mesh()),
        ):
            accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                                   total_delta=1e-5)
            engine = pdp.DPEngine(accountant, backend)
            extractors = pdp.DataExtractors(
                privacy_id_extractor=lambda r: r[0],
                partition_extractor=lambda r: r[1],
                value_extractor=lambda r: r[2])
            result = engine.aggregate(rows, params, extractors, ["A", "B"])
            accountant.compute_budgets()
            result = dict(result)
            total = result["A"].count + result["B"].count
            assert total == pytest.approx(4, abs=0.05)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_contribution_bounds_already_enforced(self, backend_name):
        rows = [("A", 1.0), ("A", 2.0), ("B", 3.0)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0.0,
                                     max_value=5.0,
                                     contribution_bounds_already_enforced=True)
        extractors = pdp.DataExtractors(
            privacy_id_extractor=None,
            partition_extractor=lambda r: r[0],
            value_extractor=lambda r: r[1])
        result, _ = run_aggregate(backend_name, rows, params,
                                  public_partitions=["A", "B"],
                                  extractors=extractors)
        assert result["A"].count == pytest.approx(2, abs=1e-2)
        assert result["A"].sum == pytest.approx(3.0, abs=1e-2)
        assert result["B"].sum == pytest.approx(3.0, abs=1e-2)

    def test_percentile_local(self):
        rows = [("u%d" % i, "A", float(i % 10)) for i in range(100)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.PERCENTILE(90)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=10.0)
        result, _ = run_aggregate("local", rows, params,
                                  public_partitions=["A"])
        assert result["A"].percentile_50 == pytest.approx(4.5, abs=1.0)
        assert result["A"].percentile_90 == pytest.approx(9.0, abs=1.0)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_percentile_parity(self, backend_name):
        # Identical data through the generic combiner path and the fused
        # device tree; huge eps makes both converge to the true quantiles.
        rows = [("u%d" % i, "pk%d" % (i % 3), float(i % 100))
                for i in range(600)]
        params = pdp.AggregateParams(metrics=[
            pdp.Metrics.PERCENTILE(10),
            pdp.Metrics.PERCENTILE(50),
            pdp.Metrics.PERCENTILE(90),
        ],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0.0,
                                     max_value=100.0)
        result, _ = run_aggregate(backend_name, rows, params,
                                  public_partitions=["pk0", "pk1", "pk2"])
        for pk in result:
            r = result[pk]
            assert r.percentile_10 == pytest.approx(10.0, abs=2.0)
            assert r.percentile_50 == pytest.approx(50.0, abs=2.0)
            assert r.percentile_90 == pytest.approx(90.0, abs=2.0)
            assert r.percentile_10 <= r.percentile_50 <= r.percentile_90

    def test_percentile_with_sum_and_private_selection_tpu(self):
        rows = [("u%d" % i, "big", float(i % 10)) for i in range(1000)]
        rows += [("lonely", "small", 3.0)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50), pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=10.0)
        result, _ = run_aggregate("tpu", rows, params, total_delta=1e-5)
        assert "small" not in result
        assert result["big"].percentile_50 == pytest.approx(4.5, abs=1.0)
        assert result["big"].sum == pytest.approx(4500.0, abs=1.0)

    def test_percentile_degenerate_range_raises_tpu(self):
        rows = [("u1", "A", 1.0)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.PERCENTILE(50)],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=1.0,
                                     max_value=1.0)
        with pytest.raises(ValueError, match="max_value must be > min_value"):
            run_aggregate("tpu", rows, params, public_partitions=["A"])

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_vector_sum(self, backend_name):
        rows = [("u1", "A", np.array([1.0, 2.0])),
                ("u2", "A", np.array([3.0, 4.0]))]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     vector_norm_kind=pdp.NormKind.Linf,
                                     vector_max_norm=10.0,
                                     vector_size=2)
        result, _ = run_aggregate(backend_name, rows, params,
                                  public_partitions=["A"])
        np.testing.assert_allclose(result["A"].vector_sum, [4.0, 6.0],
                                   atol=0.1)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    @pytest.mark.parametrize("norm_kind,expected", [
        (pdp.NormKind.Linf, [2.0, -2.0]),
        (pdp.NormKind.L1, [5.0 * 4 / 10, -5.0 * 6 / 10]),
        (pdp.NormKind.L2, [5.0 * 4 / math.sqrt(52), -5.0 * 6 / math.sqrt(52)]),
    ])
    def test_vector_sum_norm_clipping(self, backend_name, norm_kind, expected):
        # The final per-partition vector [4, -6] exceeds every ball of
        # radius 5/2 and must be projected (reference combiners.py:742-788:
        # clipping applies to the aggregated vector).
        rows = [("u1", "A", np.array([1.0, -2.0])),
                ("u2", "A", np.array([3.0, -4.0]))]
        max_norm = 2.0 if norm_kind == pdp.NormKind.Linf else 5.0
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     vector_norm_kind=norm_kind,
                                     vector_max_norm=max_norm,
                                     vector_size=2)
        result, _ = run_aggregate(backend_name, rows, params,
                                  public_partitions=["A"])
        np.testing.assert_allclose(result["A"].vector_sum, expected, atol=0.1)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_vector_sum_with_count_and_private_selection(self, backend_name):
        rows = [(f"u{i}", "big", np.array([1.0, 2.0, 3.0]))
                for i in range(1000)]
        rows += [("lonely", "small", np.array([1.0, 1.0, 1.0]))]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.VECTOR_SUM, pdp.Metrics.COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            vector_norm_kind=pdp.NormKind.Linf,
            vector_max_norm=5000.0,
            vector_size=3)
        result, _ = run_aggregate(backend_name, rows, params,
                                  total_delta=1e-5)
        assert "small" not in result
        np.testing.assert_allclose(result["big"].vector_sum,
                                   [1000.0, 2000.0, 3000.0], rtol=1e-3)
        assert result["big"].count == pytest.approx(1000, abs=0.1)

    def test_vector_sum_shape_mismatch_tpu(self):
        rows = [("u1", "A", np.array([1.0, 2.0, 3.0]))]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     vector_norm_kind=pdp.NormKind.Linf,
                                     vector_max_norm=10.0,
                                     vector_size=2)
        with pytest.raises(TypeError, match="Shape mismatch"):
            run_aggregate("tpu", rows, params, public_partitions=["A"])


class TestPrivatePartitionSelection:

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_small_partitions_dropped_large_kept(self, backend_name):
        # 1-user partition almost surely dropped; 1000-user partition almost
        # surely kept (with delta=1e-5).
        rows = [("lonely", "small", 1.0)]
        rows += [(f"u{i}", "big", 1.0) for i in range(1000)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        result, _ = run_aggregate(backend_name, rows, params,
                                  total_epsilon=HUGE_EPS, total_delta=1e-5)
        assert "big" in result
        assert "small" not in result
        assert result["big"].count == pytest.approx(1000, abs=0.1)

    def test_mocked_selection_wiring_local(self):
        # Graph-shape test in the reference style: patch the selection factory
        # and assert the exact (strategy, eps, delta, l0, pre_threshold)
        # wiring (dp_engine_test.py:614-683).
        rows = [(f"u{i}", "A", 1.0) for i in range(5)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=3,
            max_contributions_per_partition=1,
            partition_selection_strategy=(
                pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING),
            pre_threshold=2)
        backend = pdp.LocalBackend(seed=0)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])

        class FakeSelector:

            def should_keep(self, n):
                return True

        with mock.patch(
                "pipelinedp_tpu.partition_selection."
                "create_partition_selection_strategy",
                return_value=FakeSelector()) as mock_create:
            result = engine.aggregate(rows, params, extractors)
            accountant.compute_budgets()
            result = dict(result)
            assert "A" in result
            args = mock_create.call_args[0]
            assert args[0] == pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING
            assert args[1] == pytest.approx(0.5)  # eps: split with count
            assert args[2] == pytest.approx(1e-5)  # all delta (Laplace count)
            assert args[3] == 3
            assert args[4] == 2


class TestSelectPartitions:

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_select_partitions(self, backend_name):
        rows = [(f"u{i}", "big", 0) for i in range(1000)]
        rows += [("solo", "small", 0)]
        backend = make_backend(backend_name)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        params = pdp.SelectPartitionsParams(max_partitions_contributed=2)
        result = engine.select_partitions(rows, params, extractors)
        accountant.compute_budgets()
        result = list(result)
        assert "big" in result
        assert "small" not in result

    @pytest.mark.parametrize(
        "strategy", [
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC,
            pdp.PartitionSelectionStrategy.LAPLACE_THRESHOLDING,
            pdp.PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING,
        ])
    def test_select_partitions_tpu_strategies(self, strategy):
        rows = [(f"u{i}", "big", 0) for i in range(1000)]
        rows += [("solo", "small", 0)]
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, pdp.TPUBackend(noise_seed=7))
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        params = pdp.SelectPartitionsParams(max_partitions_contributed=2,
                                            partition_selection_strategy=
                                            strategy)
        result = engine.select_partitions(rows, params, extractors)
        accountant.compute_budgets()
        result = list(result)
        assert "big" in result
        assert "small" not in result

    def test_select_partitions_tpu_pre_threshold(self):
        rows = [(f"u{i}", "big", 0) for i in range(1000)]
        rows += [(f"m{i}", "mid", 0) for i in range(15)]
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, pdp.TPUBackend(noise_seed=7))
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        params = pdp.SelectPartitionsParams(max_partitions_contributed=1,
                                            pre_threshold=20)
        result = engine.select_partitions(rows, params, extractors)
        accountant.compute_budgets()
        result = list(result)
        assert "big" in result
        assert "mid" not in result  # 15 users < pre_threshold

    def test_select_partitions_local_tpu_parity(self):
        rng = np.random.default_rng(3)
        rows = [(f"u{i % 90}", f"pk{k}", 0)
                for i, k in enumerate(rng.integers(0, 25, size=3000))]

        def run(backend):
            accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                                   total_delta=1e-5)
            engine = pdp.DPEngine(accountant, backend)
            extractors = pdp.DataExtractors(
                privacy_id_extractor=lambda r: r[0],
                partition_extractor=lambda r: r[1],
                value_extractor=lambda r: r[2])
            params = pdp.SelectPartitionsParams(max_partitions_contributed=30)
            result = engine.select_partitions(rows, params, extractors)
            accountant.compute_budgets()
            return set(result)

        # Every partition has many distinct users and l0 does not bind, so
        # huge-eps selection is deterministic on both paths.
        assert run(pdp.LocalBackend(seed=0)) == run(
            pdp.TPUBackend(noise_seed=0))

    def test_select_partitions_blocked_route_parity(self):
        # large_partition_threshold below the partition count routes the
        # standalone selection through the O(kept) blocked path
        # (parallel/large_p.select_partitions_blocked); at huge eps the
        # result must match LocalBackend exactly.
        rng = np.random.default_rng(3)
        rows = [(f"u{i % 90}", f"pk{k}", 0)
                for i, k in enumerate(rng.integers(0, 25, size=3000))]

        def run(backend):
            accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                                   total_delta=1e-5)
            engine = pdp.DPEngine(accountant, backend)
            extractors = pdp.DataExtractors(
                privacy_id_extractor=lambda r: r[0],
                partition_extractor=lambda r: r[1],
                value_extractor=lambda r: r[2])
            params = pdp.SelectPartitionsParams(max_partitions_contributed=30)
            result = engine.select_partitions(rows, params, extractors)
            accountant.compute_budgets()
            return set(result)

        assert run(pdp.LocalBackend(seed=0)) == run(
            pdp.TPUBackend(noise_seed=0, large_partition_threshold=8))

    def test_select_partitions_tpu_static_width_reuse(self):
        rows = [(f"u{i}", f"pk{i % 3}", 0) for i in range(300)]
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        backend = pdp.TPUBackend(noise_seed=7, max_partitions=64)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        params = pdp.SelectPartitionsParams(max_partitions_contributed=3)
        result = engine.select_partitions(rows, params, extractors)
        accountant.compute_budgets()
        assert sorted(result) == ["pk0", "pk1", "pk2"]

    def test_select_partitions_tpu_max_partitions_too_small(self):
        rows = [(f"u{i}", f"pk{i}", 0) for i in range(10)]
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        backend = pdp.TPUBackend(noise_seed=7, max_partitions=4)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        params = pdp.SelectPartitionsParams(max_partitions_contributed=1)
        result = engine.select_partitions(rows, params, extractors)
        accountant.compute_budgets()
        with pytest.raises(ValueError, match="max_partitions"):
            list(result)


class TestSelectPartitionsKernel:
    """Deterministic unit tests of the device kernel: a Laplace-thresholding
    SelectionParams with a near-zero scale makes keep == (count >= t)."""

    @staticmethod
    def _run(pid, pk, n_partitions, l0, threshold):
        import jax
        from pipelinedp_tpu import executor
        from pipelinedp_tpu.ops import selection_ops
        selection = selection_ops.SelectionParams(kind=1,
                                                  pre_shift=0,
                                                  threshold=threshold,
                                                  scale=1e-12)
        pid = np.asarray(pid, np.int32)
        pk = np.asarray(pk, np.int32)
        keep = executor.select_partitions_kernel(pid, pk,
                                                 np.ones(len(pid), bool),
                                                 jax.random.PRNGKey(0), l0,
                                                 n_partitions, selection)
        return np.asarray(keep)

    def test_duplicate_rows_count_once(self):
        # Partition 0: 10 distinct single-row users + one user with 50
        # duplicate rows -> privacy-id count must be 11, not 60.
        pid = list(range(10)) + [100] * 50
        pk = [0] * 60
        assert self._run(pid, pk, 1, 4, threshold=10.5).tolist() == [True]
        assert self._run(pid, pk, 1, 4, threshold=11.5).tolist() == [False]
        assert self._run(pid, pk, 1, 4, threshold=59.5).tolist() == [False]

    def test_l0_sampling_bounds_cross_partition_count(self):
        # User 100 contributes to all 3 partitions but l0=2: exactly two
        # partitions see 11 users (kept at t=10.5), one sees 10 (dropped).
        pid, pk = [], []
        for p in range(3):
            pid += list(range(p * 10, p * 10 + 10)) + [100]
            pk += [p] * 11
        keep = self._run(pid, pk, 3, 2, threshold=10.5)
        assert keep.sum() == 2

    def test_invalid_rows_ignored(self):
        import jax
        from pipelinedp_tpu import executor
        from pipelinedp_tpu.ops import selection_ops
        selection = selection_ops.SelectionParams(kind=1,
                                                  pre_shift=0,
                                                  threshold=1.5,
                                                  scale=1e-12)
        pid = np.asarray([1, 2, 3, 4], np.int32)
        pk = np.asarray([0, 0, 1, 1], np.int32)
        valid = np.asarray([True, True, False, False])
        keep = executor.select_partitions_kernel(pid, pk, valid,
                                                 jax.random.PRNGKey(0), 2, 2,
                                                 selection)
        assert np.asarray(keep).tolist() == [True, False]


class TestExplainComputation:

    def test_report_contains_stages_and_budget(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=1)
        report = pdp.ExplainComputationReport()
        backend = pdp.LocalBackend(seed=0)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(SIMPLE_ROWS, params, extractors,
                                  out_explain_computation_report=report)
        accountant.compute_budgets()
        list(result)
        text = report.text()
        assert "DPEngine method: aggregate" in text
        assert "Private Partition selection" in text
        assert "Computed DP count" in text
        assert "eps=0.5" in text

    def test_report_on_tpu_path(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=1)
        report = pdp.ExplainComputationReport()
        backend = pdp.TPUBackend(noise_seed=0)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(SIMPLE_ROWS, params, extractors,
                                  out_explain_computation_report=report)
        accountant.compute_budgets()
        list(result)
        text = report.text()
        assert "Private Partition selection" in text
        assert "Cross-partition contribution bounding" in text


class TestValidation:

    def test_empty_col_raises(self):
        accountant = pdp.NaiveBudgetAccountant(1.0, 0)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        with pytest.raises(ValueError, match="non-empty"):
            engine.aggregate([], params, pdp.DataExtractors())

    def test_wrong_params_type(self):
        accountant = pdp.NaiveBudgetAccountant(1.0, 0)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        with pytest.raises(TypeError):
            engine.aggregate([1], "not params", pdp.DataExtractors())

    def test_pld_accountant_unsupported_metric_raises(self):
        accountant = pdp.PLDBudgetAccountant(1.0, 1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VARIANCE],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0.0,
                                     max_value=1.0)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r,
                                        partition_extractor=lambda r: r,
                                        value_extractor=lambda r: 0)
        with pytest.raises(NotImplementedError, match="PLD"):
            engine.aggregate([1], params, extractors)


class TestPLDAccountingEndToEnd:

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_sum_with_pld_budget(self, backend_name):
        backend = make_backend(backend_name)
        accountant = pdp.PLDBudgetAccountant(total_epsilon=1e5,
                                             total_delta=1e-6,
                                             pld_discretization=1e-3)
        engine = pdp.DPEngine(accountant, backend)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.SUM],
                                     noise_kind=pdp.NoiseKind.GAUSSIAN,
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=2,
                                     min_value=0.0,
                                     max_value=5.0)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(SIMPLE_ROWS, params, extractors,
                                  public_partitions=["A", "B"])
        accountant.compute_budgets()
        result = dict(result)
        assert result["A"].sum == pytest.approx(9.0, abs=0.5)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_private_partition_selection_under_pld(self, backend_name):
        # The reference forbids private selection under PLD
        # (/root/reference/pipeline_dp/dp_engine.py:511-521); here the
        # GENERIC selection mechanism composes through the PLD, so crowded
        # partitions are kept and sparse ones dropped.
        backend = make_backend(backend_name)
        accountant = pdp.PLDBudgetAccountant(total_epsilon=1e4,
                                             total_delta=1e-4,
                                             pld_discretization=1e-3)
        engine = pdp.DPEngine(accountant, backend)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        rows = [(f"u{i}", "crowded", 1.0) for i in range(500)]
        rows += [("solo", "sparse", 1.0)]
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        result = dict(result)
        assert "crowded" in result
        assert "sparse" not in result
        assert result["crowded"].count == pytest.approx(500, rel=0.05)

    def test_private_selection_under_pld_true_composition_path(self):
        # total_epsilon below the naive-fallback threshold: this exercises
        # the real PLD binary search with the GENERIC selection mechanism
        # composed through _compose_distributions (not the fallback split).
        accountant = pdp.PLDBudgetAccountant(total_epsilon=5.0,
                                             total_delta=1e-5,
                                             pld_discretization=1e-3)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend(seed=0))
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        rows = [(f"u{i}", "crowded", 1.0) for i in range(2000)]
        rows += [("solo", "sparse", 1.0)]
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        # The GENERIC spec received eps/delta from the PLD search, and the
        # count mechanism received a noise std.
        assert accountant.minimum_noise_std > 0
        result = dict(result)
        assert "crowded" in result
        assert "sparse" not in result
        assert result["crowded"].count == pytest.approx(2000, rel=0.05)

    def test_select_partitions_under_pld(self):
        accountant = pdp.PLDBudgetAccountant(total_epsilon=1e4,
                                             total_delta=1e-4,
                                             pld_discretization=1e-3)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend(seed=0))
        rows = [(f"u{i}", "big") for i in range(500)]
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: 0)
        params = pdp.SelectPartitionsParams(max_partitions_contributed=1)
        result = engine.select_partitions(rows, params, extractors)
        accountant.compute_budgets()
        assert list(result) == ["big"]


class TestPublicPartitionHandling:

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_non_public_dropped_and_missing_public_added_empty(
            self, backend_name):
        # Data lives in A and B; public = [B, C]. A must be dropped
        # (never released), C must appear as a pure-noise (≈0 at huge eps)
        # partition even though no row touched it.
        rows = [("u1", "A", 1.0), ("u2", "A", 2.0), ("u3", "B", 3.0)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        result, _ = run_aggregate(backend_name,
                                  rows,
                                  params,
                                  public_partitions=["B", "C"])
        assert set(result) == {"B", "C"}
        assert result["B"].count == pytest.approx(1, abs=1e-2)
        assert result["C"].count == pytest.approx(0, abs=1e-2)

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_empty_public_partition_carries_all_metrics(self, backend_name):
        rows = [("u1", "A", 2.0)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0.0,
                                     max_value=5.0)
        result, _ = run_aggregate(backend_name,
                                  rows,
                                  params,
                                  public_partitions=["A", "Z"])
        assert result["Z"].count == pytest.approx(0, abs=1e-2)
        assert result["Z"].sum == pytest.approx(0.0, abs=1e-1)
        assert result["A"].sum == pytest.approx(2.0, abs=1e-1)


class TestAnnotatorHook:

    def test_engine_annotates_with_params_and_budget(self):
        from pipelinedp_tpu import pipeline_backend

        calls = []

        class Recorder(pipeline_backend.Annotator):

            def annotate(self, col, backend, stage_name, **kwargs):
                calls.append((stage_name, kwargs))
                return col

        pipeline_backend.register_annotator(Recorder())
        try:
            params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                         max_partitions_contributed=1,
                                         max_contributions_per_partition=1)
            # The per-aggregation Budget is only computable when the
            # accountant knows the expected aggregation count upfront
            # (same contract as the reference annotator).
            accountant = pdp.NaiveBudgetAccountant(total_epsilon=2.0,
                                                   total_delta=1e-6,
                                                   num_aggregations=1)
            engine = pdp.DPEngine(accountant, pdp.LocalBackend(seed=0))
            extractors = pdp.DataExtractors(
                privacy_id_extractor=lambda r: r[0],
                partition_extractor=lambda r: r[1],
                value_extractor=lambda r: r[2])
            result = engine.aggregate(SIMPLE_ROWS, params, extractors,
                                      ["A", "B"])
            accountant.compute_budgets()
            list(result)
        finally:
            pipeline_backend._annotators.clear()
        assert len(calls) == 1
        stage_name, kwargs = calls[0]
        assert "params" in kwargs and "budget" in kwargs
        assert kwargs["params"].metrics == [pdp.Metrics.COUNT]
        assert kwargs["budget"].epsilon == pytest.approx(2.0)
        assert kwargs["budget"].delta == pytest.approx(1e-6)


class TestCustomCombinersThroughEngine:

    class SumOfSquares(pdp.CustomCombiner):

        def create_accumulator(self, values):
            return float(sum(v**2 for v in values))

        def merge_accumulators(self, a, b):
            return a + b

        def compute_metrics(self, acc):
            return {"sum_squares": acc}

        def explain_computation(self):
            return lambda: "sum of squares"

        def request_budget(self, budget_accountant):
            self._budget = budget_accountant.request_budget(
                pdp.MechanismType.LAPLACE)

        def metrics_names(self):
            return ["sum_squares"]

    def test_custom_combiner_e2e_local(self):
        rows = [("u1", "A", 2.0), ("u2", "A", 3.0), ("u3", "B", 4.0)]
        params = pdp.AggregateParams(metrics=None,
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     custom_combiners=[self.SumOfSquares()])
        result, _ = run_aggregate("local",
                                  rows,
                                  params,
                                  public_partitions=["A", "B"])
        assert result["A"] == ({"sum_squares": 13.0},)
        assert result["B"] == ({"sum_squares": 16.0},)


class TestPreThresholdEndToEnd:

    @pytest.mark.parametrize("backend_name", BACKENDS)
    def test_pre_threshold_gates_small_partitions(self, backend_name):
        # Partitions with 2 / 4 / 8 distinct users; pre_threshold=4 shifts
        # the effective id count down by pre_threshold - 1, so "small"
        # (below the threshold) is impossible, "mid" behaves like a 1-user
        # partition (delta-bounded keep probability ~ 0 even at huge eps),
        # and only "big" (effective count 5) survives.
        rows = ([(f"a{i}", "small", 1.0) for i in range(2)] +
                [(f"b{i}", "mid", 1.0) for i in range(4)] +
                [(f"c{i}", "big", 1.0) for i in range(8)])
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            partition_selection_strategy=(
                pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC),
            pre_threshold=4)
        result, _ = run_aggregate(backend_name, rows, params)
        assert set(result) == {"big"}
        assert result["big"].count == pytest.approx(8, abs=0.05)


class TestLargePartitionRouting:
    """TPUBackend routes past the dense kernel above the threshold."""

    def _rows(self):
        # 40 partitions, each with 2-3 users contributing once.
        rows = []
        for p in range(40):
            for u in range(2 + p % 2):
                rows.append((f"u{p}_{u}", f"pk{p:03d}", float(1 + p % 4)))
        return rows

    def test_public_partitions_match_local(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1,
                                     min_value=0.0,
                                     max_value=5.0)
        rows = self._rows()
        public = sorted({r[1] for r in rows}) + ["pk_empty"]
        expected, _ = run_aggregate("local", rows, params,
                                    public_partitions=public)
        backend = pdp.TPUBackend(noise_seed=3, large_partition_threshold=8)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(rows, params, extractors, public)
        accountant.compute_budgets()
        result = dict(result)
        assert set(result) == set(expected)
        for pk in expected:
            assert result[pk].count == pytest.approx(expected[pk].count,
                                                     abs=0.05)
            assert result[pk].sum == pytest.approx(expected[pk].sum,
                                                   abs=0.05)

    def test_percentile_routes_through_blocked_path(self):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT,
                     pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=20,
            max_contributions_per_partition=8,
            min_value=0.0,
            max_value=5.0)
        rows = self._rows()
        public = sorted({r[1] for r in rows})
        expected, _ = run_aggregate("local", rows, params,
                                    public_partitions=public)
        backend = pdp.TPUBackend(noise_seed=3, large_partition_threshold=8)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(rows, params, extractors, public)
        accountant.compute_budgets()
        result = dict(result)
        assert set(result) == set(expected)
        for pk in expected:
            # Tree quantiles are leaf-quantized: compare within a few
            # leaf widths of the local (exact-algorithm) result.
            assert result[pk].percentile_50 == pytest.approx(
                expected[pk].percentile_50, abs=0.05)

    def test_vector_sum_routes_through_blocked_path(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=3,
                                     vector_norm_kind=pdp.NormKind.Linf,
                                     vector_max_norm=5.0,
                                     vector_size=3)
        rows = [(u, "pk_%d" % (u % 11), np.array([1.0, 2.0, -1.0]))
                for u in range(220)]
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        expected, _ = run_aggregate("local", rows, params,
                                    extractors=extractors)
        backend = pdp.TPUBackend(noise_seed=3, large_partition_threshold=8)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        result = dict(result)
        assert set(result) == set(expected)
        for pk in expected:
            np.testing.assert_allclose(np.asarray(result[pk].vector_sum),
                                       np.asarray(expected[pk].vector_sum),
                                       atol=0.05)

    def test_private_selection_match_local(self):
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        rows = self._rows() + [("lone", "pk_single", 1.0)]
        expected, _ = run_aggregate("local", rows, params)
        backend = pdp.TPUBackend(noise_seed=3, large_partition_threshold=8)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: r[2])
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        result = dict(result)
        # Data is within bounds, so the kept set is deterministic at huge
        # eps: multi-user partitions survive, the 1-user partition drops.
        assert set(result) == set(expected)
        assert "pk_single" not in result
        for pk in expected:
            assert result[pk].count == pytest.approx(expected[pk].count,
                                                     abs=0.05)
