"""Numeric armor: overflow-safe accumulation, the fail-closed release
sentinel, floating-point-safe discrete noise, and the extreme_values
fault kind.

The contracts under test:

  * **The release sentinel** — every released column is scanned on
    device (one scalar reduction) for NaN/Inf/saturation before any
    decode or journal write; a trip raises a typed
    ReleaseIntegrityError (NumericOverflowError for overflow in safe
    mode), records release_sentinel_trips, and releases NOTHING.
    Unkept slots never trip it.
  * **Compensated accumulation** — numeric_mode="safe" runs the fused
    segment sums through a TwoSum (hi/lo) associative scan: exact for
    integer-valued f32 workloads far past the 2**24 naive-f32 cliff,
    matching a float64 oracle bit-for-bit; "fast" (the default) keeps
    the historical bit-identical path and the two modes agree wherever
    f32 was already exact.
  * **Extreme inputs through the drivers** — clip-bound-magnitude
    values (~3e38) overflow the f32 prefix sums and fail CLOSED with a
    typed error on the dense, meshed and blocked drivers; denormal
    inputs (1e-40) release finite values without tripping anything.
  * **Fail-closed budget discipline** — an overflow abort registers no
    new mechanisms (the two-phase budget protocol already froze the
    graph) and yields zero released partitions.
  * **The extreme_values fault kind** — validated modes (nan |
    magnitude), one-partition poisoning at every driver ingest seam,
    pinned trials proving the sentinel trips and the service converts
    the abort into a typed shed.
  * **Discrete/snapped mechanisms** — geometric noise for counts is
    exactly integer-valued; snapped Laplace/Gaussian land exactly on
    their declared power-of-two grid with the Delta + g widened
    calibration; threefry-keyed draws replay bit-identically;
    distribution parity (moments + CDF) against the continuous
    mechanisms within grid tolerance.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import budget_accounting as ba
from pipelinedp_tpu import dp_computations as dp
from pipelinedp_tpu import numeric as rt_numeric
from pipelinedp_tpu import pipeline_backend
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.ops import segment_ops
from pipelinedp_tpu.runtime import faults
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.parallel import make_mesh
from pipelinedp_tpu.service import DPAggregationService, JobSpec, JobStatus

pytestmark = pytest.mark.numeric_armor

F32_SAT = rt_numeric.SATURATION_LIMIT  # finfo(f32).max / 2


@pytest.fixture
def f32_compute():
    """Run the engine at TPU-native f32 precision.

    The test harness forces jax_enable_x64 on (tests/conftest.py), which
    widens executor._ftype() to f64 — the very cliff/overflow behavior
    this PR armors against disappears. These tests flip the flag off for
    their duration (the same discipline benchmarks/profile_kernel.py
    uses) so the accumulators behave exactly as on device."""
    old = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", old)


def _cols(**arrays):
    return {k: jnp.asarray(v, dtype=jnp.float32) for k, v in arrays.items()}


class TestReleaseSentinel:

    def test_clean_columns_pass_both_modes(self):
        cols = _cols(count=[1.0, 2.0, 3.0, 0.0])
        for mode in ("fast", "safe"):
            rt_numeric.check_release(cols, n_kept=jnp.int32(3),
                                     numeric_mode=mode)

    def test_nan_in_kept_rows_trips_fast_mode(self):
        cols = _cols(count=[1.0, np.nan, 3.0, 0.0])
        before = telemetry.snapshot()
        with pytest.raises(rt_numeric.ReleaseIntegrityError, match="NaN"):
            rt_numeric.check_release(cols, n_kept=jnp.int32(3),
                                     numeric_mode="fast")
        assert telemetry.delta(before).get("release_sentinel_trips") == 1

    def test_nan_in_unkept_rows_is_ignored(self):
        cols = _cols(count=[1.0, 2.0, np.nan, np.nan])
        rt_numeric.check_release(cols, n_kept=jnp.int32(2),
                                 numeric_mode="safe")

    def test_mask_variant_gates_like_kept_prefix(self):
        cols = _cols(s=[np.nan, 2.0, np.nan, 4.0])
        keep = np.array([False, True, False, True])
        rt_numeric.check_release(cols, keep=keep, numeric_mode="safe")
        with pytest.raises(rt_numeric.ReleaseIntegrityError):
            rt_numeric.check_release(
                cols, keep=np.array([True, True, False, False]),
                numeric_mode="safe")

    def test_overflow_is_typed_in_safe_mode_advisory_in_fast(self):
        """Inf (and finite saturation) without NaN classifies as
        NumericOverflowError in safe mode; fast mode treats finite
        saturation as advisory (no raise — bit-identity preserved) but
        still refuses Inf."""
        sat = _cols(s=[F32_SAT * 1.5, 1.0])
        rt_numeric.check_release(sat, n_kept=jnp.int32(2),
                                 numeric_mode="fast")  # advisory only
        before = telemetry.snapshot()
        with pytest.raises(rt_numeric.NumericOverflowError):
            rt_numeric.check_release(sat, n_kept=jnp.int32(2),
                                     numeric_mode="safe")
        d = telemetry.delta(before)
        assert d.get("numeric_overflows") == 1
        assert d.get("release_sentinel_trips") == 1
        inf = _cols(s=[np.inf, 1.0])
        with pytest.raises(rt_numeric.ReleaseIntegrityError):
            rt_numeric.check_release(inf, n_kept=jnp.int32(2),
                                     numeric_mode="fast")

    def test_overflow_error_is_a_release_integrity_error(self):
        assert issubclass(rt_numeric.NumericOverflowError,
                          rt_numeric.ReleaseIntegrityError)

    def test_integer_columns_are_exempt(self):
        cols = {"ids": jnp.asarray([2**30, 5], dtype=jnp.int32)}
        rt_numeric.check_release(cols, n_kept=jnp.int32(2),
                                 numeric_mode="safe")

    def test_2d_columns_gate_on_rows(self):
        col = np.ones((4, 3), np.float32)
        col[3, 1] = np.nan
        rt_numeric.check_release({"q": jnp.asarray(col)},
                                 n_kept=jnp.int32(3), numeric_mode="safe")
        with pytest.raises(rt_numeric.ReleaseIntegrityError):
            rt_numeric.check_release({"q": jnp.asarray(col)},
                                     n_kept=jnp.int32(4),
                                     numeric_mode="safe")


# An integer-valued f32 stream a naive f32 cumsum gets WRONG: after the
# 2**24 prefix, +1.0 increments vanish (f32 spacing there is 2.0).
_CLIFF = float(1 << 24)


class TestCompensatedAccumulation:

    def test_compensated_scan_matches_f64_oracle_past_the_cliff(self):
        x = np.ones(64, np.float32)
        x[0] = _CLIFF
        hi, lo = segment_ops.compensated_cumsum(jnp.asarray(x))
        starts = jnp.asarray([0, 64], dtype=jnp.int32)
        safe = np.asarray(segment_ops.compensated_segment_diff(
            hi, lo, starts))
        oracle = np.cumsum(x.astype(np.float64))[-1]
        # Correctly rounded: the f32 nearest to the exact f64 sum
        # (2**24 + 63 itself is odd, below f32 resolution there).
        assert float(safe[0]) == float(np.float32(oracle))
        naive = float(np.asarray(jnp.cumsum(jnp.asarray(x),
                                            dtype=jnp.float32))[-1])
        assert naive != float(np.float32(oracle))  # the cliff is real

    def test_integer_and_f64_inputs_pass_through_exactly(self):
        xi = jnp.asarray([5, 7, 9], dtype=jnp.int32)
        hi, lo = segment_ops.compensated_cumsum(xi)
        assert np.array_equal(np.asarray(hi), [5, 12, 21])
        assert not np.asarray(lo).any()

    def test_kernel_config_numeric_mode_is_static_and_defaults_fast(self):
        from pipelinedp_tpu import combiners, executor
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=1,
            max_contributions_per_partition=1, min_value=0.0,
            max_value=1.0)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, accountant)
        cfg = executor.make_kernel_config(params, compound, 8, False, None)
        assert cfg.numeric_mode == "fast"
        cfg2 = executor.make_kernel_config(params, compound, 8, False,
                                           None, numeric_mode="safe")
        assert cfg2.numeric_mode == "safe"


# Engine-level workloads. Epsilon 1e12 makes the Laplace noise scale
# sub-integer for the released magnitudes below, so round() recovers
# the exact aggregate regardless of whether the residual host-side f64
# noise survives the release dtype.
_EXACT_EPS = 1e12


def _run_engine(backend, rows, params, public, total_epsilon=_EXACT_EPS):
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=total_epsilon,
                                           total_delta=1e-5)
    engine = pdp.DPEngine(accountant, backend)
    ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                             partition_extractor=lambda r: r[1],
                             value_extractor=lambda r: r[2])
    result = engine.aggregate(rows, params, ext, public)
    accountant.compute_budgets()
    return dict(result), accountant


def _cliff_params():
    return pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=1,
        max_contributions_per_partition=3,
        min_value=0.0, max_value=_CLIFF)


# One partition whose exact sum (2**24 + 2) is unreachable by a naive
# f32 accumulation (it rounds to 2**24).
_CLIFF_ROWS = [("u1", "A", _CLIFF), ("u2", "A", 1.0), ("u3", "A", 1.0)]
_CLIFF_ORACLE = _CLIFF + 2.0


def _backends(numeric_mode):
    """The driver matrix: dense solo, dense meshed, blocked solo,
    blocked meshed."""
    mesh = make_mesh(n_devices=8)
    return {
        "dense": pdp.TPUBackend(noise_seed=5, numeric_mode=numeric_mode),
        "meshed": pdp.TPUBackend(noise_seed=5, mesh=mesh,
                                 numeric_mode=numeric_mode),
        "blocked": pdp.TPUBackend(noise_seed=5,
                                  large_partition_threshold=1,
                                  block_partitions=8,
                                  numeric_mode=numeric_mode),
        "blocked-meshed": pdp.TPUBackend(noise_seed=5, mesh=mesh,
                                         large_partition_threshold=1,
                                         block_partitions=8,
                                         numeric_mode=numeric_mode),
    }


class TestNumericModeThroughDrivers:

    @pytest.mark.parametrize("driver", ["dense", "meshed", "blocked",
                                        "blocked-meshed"])
    def test_safe_mode_matches_f64_oracle_on_integer_workload(
            self, driver, f32_compute):
        backend = _backends("safe")[driver]
        result, _ = _run_engine(backend, _CLIFF_ROWS, _cliff_params(),
                                ["A"])
        assert round(result["A"].sum) == _CLIFF_ORACLE
        assert round(result["A"].count) == 3

    @pytest.mark.parametrize("driver", ["dense", "blocked"])
    def test_fast_mode_documents_the_f32_error(self, driver, f32_compute):
        """The historical path loses the +2 past the cliff — the exact
        error class safe mode exists to remove."""
        backend = _backends("fast")[driver]
        result, _ = _run_engine(backend, _CLIFF_ROWS, _cliff_params(),
                                ["A"])
        assert round(result["A"].sum) == _CLIFF  # wrong by exactly 2
        assert round(result["A"].count) == 3

    @pytest.mark.parametrize("driver", ["dense", "meshed", "blocked",
                                        "blocked-meshed"])
    def test_fast_and_safe_agree_where_f32_is_exact(self, driver):
        rows = [("u1", "A", 3.0), ("u2", "A", 1.0), ("u2", "B", 2.0),
                ("u3", "B", 4.0)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=2,
            max_contributions_per_partition=2,
            min_value=0.0, max_value=5.0)
        fast, _ = _run_engine(_backends("fast")[driver], rows, params,
                              ["A", "B"])
        safe, _ = _run_engine(_backends("safe")[driver], rows, params,
                              ["A", "B"])
        for p in ("A", "B"):
            assert fast[p].count == safe[p].count
            assert fast[p].sum == safe[p].sum

    def test_default_mode_releases_are_bit_stable(self):
        """numeric_mode never entered KernelConfig before this PR; the
        default must compile the identical program — two default-mode
        runs (and an explicit fast run) release identical bits."""
        params = _cliff_params()
        a, _ = _run_engine(pdp.TPUBackend(noise_seed=5), _CLIFF_ROWS,
                           params, ["A"])
        b, _ = _run_engine(pdp.TPUBackend(noise_seed=5), _CLIFF_ROWS,
                           params, ["A"])
        c, _ = _run_engine(pdp.TPUBackend(noise_seed=5,
                                          numeric_mode="fast"),
                           _CLIFF_ROWS, params, ["A"])
        assert a["A"].sum == b["A"].sum == c["A"].sum
        assert a["A"].count == b["A"].count == c["A"].count


_F32_MAX = float(np.finfo(np.float32).max)


class TestExtremeInputs:

    @pytest.mark.parametrize("driver", ["dense", "meshed", "blocked",
                                        "blocked-meshed"])
    def test_clip_bound_magnitude_inputs_fail_closed(self, driver,
                                                     f32_compute):
        """Rows at ~3e38 under a clip bound that admits them: the f32
        prefix sums overflow, and every driver refuses the release with
        a typed error instead of publishing Inf/NaN."""
        rows = [(f"u{i}", "A" if i % 2 else "B", 3e38) for i in range(12)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=_F32_MAX)
        backend = _backends("safe")[driver]
        before = telemetry.snapshot()
        with pytest.raises(rt_numeric.ReleaseIntegrityError):
            _run_engine(backend, rows, params, ["A", "B"])
        assert telemetry.delta(before).get("release_sentinel_trips",
                                           0) >= 1

    def test_overflow_in_safe_mode_is_numeric_overflow_no_partial_release(
            self, f32_compute):
        """Safe mode classifies the trip as NumericOverflowError; zero
        partitions are released and zero mechanisms register beyond the
        graph-time set (no duplicate budget registrations)."""
        rows = [("u1", "A", 3e38), ("u2", "A", 3e38), ("u3", "A", 3e38)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=_F32_MAX)
        backend = pdp.TPUBackend(noise_seed=5, numeric_mode="safe")
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=_EXACT_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        result = engine.aggregate(rows, params, ext, ["A"])
        accountant.compute_budgets()
        registered = accountant.mechanism_count
        released = []
        before = telemetry.snapshot()
        with pytest.raises(rt_numeric.NumericOverflowError):
            for item in result:
                released.append(item)
        assert released == []  # fail closed: nothing escaped
        assert accountant.mechanism_count == registered
        d = telemetry.delta(before)
        assert d.get("numeric_overflows") == 1
        assert d.get("release_sentinel_trips") == 1

    def test_denormal_inputs_release_finite_values(self, f32_compute):
        rows = [("u1", "A", 1e-40), ("u2", "A", 1e-40)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=1.0)
        for mode in ("fast", "safe"):
            result, _ = _run_engine(
                pdp.TPUBackend(noise_seed=5, numeric_mode=mode), rows,
                params, ["A"])
            assert math.isfinite(result["A"].sum)
            assert abs(result["A"].sum) < 1e-6  # denormals don't explode
            assert round(result["A"].count) == 2


class TestExtremeValuesFaultKind:

    def test_mode_vocabulary_is_validated(self):
        assert faults.Fault("extreme_values").mode == "nan"
        assert faults.Fault("extreme_values",
                            mode="magnitude").mode == "magnitude"
        with pytest.raises(ValueError, match="mode"):
            faults.Fault("extreme_values", mode="truncate")
        with pytest.raises(ValueError, match="mode"):
            faults.Fault("corrupt", mode="nan")

    def test_maybe_extreme_rows_poisons_one_partition(self):
        values = np.ones(16, np.float64)
        pk = np.array([3, 7] * 8, np.int32)
        assert faults.maybe_extreme_rows(values, pk) is None  # no schedule
        sched = faults.FaultSchedule([faults.Fault("extreme_values")])
        before = telemetry.snapshot()
        with faults.inject(sched):
            poisoned = faults.maybe_extreme_rows(values, pk)
            again = faults.maybe_extreme_rows(values, pk)
        assert again is None  # one firing, consumed
        assert telemetry.delta(before).get("injected_faults") == 1
        nan_rows = np.isnan(poisoned)
        assert nan_rows[pk == 3].all() and not nan_rows[pk == 7].any()
        assert (values == 1.0).all()  # caller's array untouched

    def test_pinned_driver_trial_magnitude_trips_the_sentinel(
            self, f32_compute):
        """The reproducer trial: an extreme_values magnitude fault at
        the blocked driver's ingest, wide clip bounds so the pattern
        survives bounding — the poisoned block must die PRE-JOURNAL
        with a typed error, never become a durable record."""
        from pipelinedp_tpu import combiners, executor
        from pipelinedp_tpu.parallel import large_p
        P, n = 64, 4096
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=8,
            min_value=-_F32_MAX, max_value=_F32_MAX)
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        compound = combiners.create_compound_combiner(params, accountant)
        accountant.compute_budgets()
        cfg = executor.make_kernel_config(params, compound, P, False, None)
        stds = np.asarray(executor.compute_noise_stds(compound, params))
        rng = np.random.default_rng(11)
        pid = rng.integers(0, 128, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        values = rng.uniform(0, 5, n)
        min_v, max_v, min_s, max_s, mid = executor.kernel_scalars(params)
        sched = faults.FaultSchedule(
            [faults.Fault("extreme_values", mode="magnitude")])
        before = telemetry.snapshot()
        with faults.inject(sched):
            with pytest.raises(rt_numeric.ReleaseIntegrityError):
                large_p.aggregate_blocked(
                    pid, pk, values, np.ones(n, bool), min_v, max_v,
                    min_s, max_s, mid, stds, jax.random.PRNGKey(23),
                    cfg, block_partitions=16)
        d = telemetry.delta(before)
        assert d.get("release_sentinel_trips", 0) >= 1
        assert d.get("injected_faults") == 1

    def test_pinned_service_trial_sheds_with_typed_error(self):
        """The service half: a NaN-mode extreme_values fault during a
        job's run converts into a typed SHED (not a wedged worker, not
        a silent FAILED) and counts service_jobs_shed."""
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0, max_value=5.0)
        ext = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                 partition_extractor=lambda r: r[1],
                                 value_extractor=lambda r: r[2])
        spec = JobSpec(params=params, epsilon=1.0, delta=1e-6,
                       data_extractors=ext, noise_seed=29,
                       public_partitions=["A"])
        rows = [("u1", "A", 1.0), ("u2", "A", 2.0)]
        sched = faults.FaultSchedule([faults.Fault("extreme_values")])
        before = telemetry.snapshot()
        with faults.inject(sched, scope="process"):
            with DPAggregationService(pdp.TPUBackend()) as svc:
                handle = svc.submit("tenant-nx", spec, rows)
                with pytest.raises(rt_numeric.ReleaseIntegrityError):
                    handle.result(timeout=120)
                assert handle.status == JobStatus.SHED
        d = telemetry.delta(before)
        assert d.get("service_jobs_shed") == 1
        assert d.get("release_sentinel_trips", 0) >= 1


KEY = jax.random.PRNGKey(77)


class TestDiscreteMechanisms:

    def test_geometric_releases_are_integers_and_deterministic(self):
        a = dp.GeometricMechanism(0.7, 2, key=KEY)
        b = dp.GeometricMechanism(0.7, 2, key=KEY)
        draws_a = [a.add_noise(10) for _ in range(32)]
        draws_b = [b.add_noise(10) for _ in range(32)]
        assert draws_a == draws_b
        assert all(v == int(v) for v in draws_a)
        assert len(set(draws_a)) > 1  # the counter advances per draw

    def test_geometric_moment_parity_with_laplace(self):
        """The discrete Laplace tracks the continuous one: mean ~0 and
        std within a grid-step tolerance of the declared std."""
        m = dp.GeometricMechanism(0.4, 1, key=KEY)
        draws = np.array([m.add_noise(0) for _ in range(4000)])
        assert abs(draws.mean()) < 4 * m.std / math.sqrt(len(draws))
        assert abs(draws.std() - m.std) < 0.1 * m.std + 1.0

    @pytest.mark.parametrize("mech_cls,args", [
        (dp.SnappedLaplaceMechanism, (1.0, 4.0)),
        (dp.SnappedGaussianMechanism, (1.0, 1e-6, 4.0)),
    ])
    def test_snapped_releases_land_exactly_on_the_grid(self, mech_cls,
                                                       args):
        m = mech_cls(*args, snap_grid_bits=-6, key=KEY)
        g = m.grid
        assert g >= 2.0 ** -6 and math.log2(g) == int(math.log2(g))
        for i in range(64):
            v = m.add_noise(100.0 + i / 7.0)
            assert v == round(v / g) * g  # exactly on the grid

    def test_snap_widens_sensitivity_never_budget(self):
        m = dp.SnappedLaplaceMechanism(2.0, 8.0, key=KEY)
        assert m.sensitivity == 8.0 + m.grid
        assert m.epsilon == 2.0  # the granted budget is unchanged
        # Widened scale: b = (Delta + g) / eps > Delta / eps.
        assert m.noise_parameter == m.sensitivity / 2.0

    def test_snapped_cdf_parity_with_continuous(self):
        """KS-style check: snapped Laplace draws against the continuous
        Laplace CDF, tolerance one grid step plus sampling error."""
        m = dp.SnappedLaplaceMechanism(1.0, 1.0, key=KEY)
        n = 4000
        draws = np.sort([m.add_noise(0.0) for _ in range(n)])
        b = m.noise_parameter
        cdf = np.where(draws < 0, 0.5 * np.exp(draws / b),
                       1.0 - 0.5 * np.exp(-draws / b))
        empirical = (np.arange(n) + 0.5) / n
        ks = np.max(np.abs(cdf - empirical))
        assert ks < 1.7 / math.sqrt(n) + m.grid / b

    def test_create_discrete_mechanism_dispatch(self):
        sens = dp.Sensitivities(l0=2, linf=3.0)
        lap = ba.MechanismSpec(MechanismType.LAPLACE)
        lap.set_eps_delta(1.0, 0.0)
        gau = ba.MechanismSpec(MechanismType.GAUSSIAN)
        gau.set_eps_delta(1.0, 1e-6)
        m = dp.create_discrete_mechanism(lap, sens, value_is_integer=True,
                                         key=KEY)
        assert isinstance(m, dp.GeometricMechanism)
        m = dp.create_discrete_mechanism(lap, sens, key=KEY)
        assert isinstance(m, dp.SnappedLaplaceMechanism)
        m = dp.create_discrete_mechanism(gau, sens, snap_grid_bits=-4,
                                         key=KEY)
        assert isinstance(m, dp.SnappedGaussianMechanism)
        assert m.grid >= 2.0 ** -4

    def test_discrete_draws_record_snapped_releases(self):
        before = telemetry.snapshot()
        dp.GeometricMechanism(1.0, 1, key=KEY).add_noise(3)
        dp.SnappedLaplaceMechanism(1.0, 1.0, key=KEY).add_noise(3.0)
        assert telemetry.delta(before).get("snapped_releases") == 2

    def test_snap_grid_bits_floors_the_secure_noise_tables(self):
        from pipelinedp_tpu.aggregate_params import NoiseKind
        from pipelinedp_tpu.ops import secure_noise
        _, _, g_default = secure_noise.build_table(2.0, NoiseKind.LAPLACE,
                                                   sensitivity=1.0)
        _, _, g_floored = secure_noise.build_table(
            2.0, NoiseKind.LAPLACE, sensitivity=1.0, grid_floor=0.25)
        assert g_floored >= 0.25 >= g_default
        assert math.log2(g_floored) == int(math.log2(g_floored))


class TestKnobs:

    def test_backend_rejects_bad_numeric_knobs(self):
        with pytest.raises(ValueError, match="numeric_mode"):
            pipeline_backend.TPUBackend(numeric_mode="fancy")
        with pytest.raises(ValueError, match="snap_grid_bits"):
            pipeline_backend.TPUBackend(snap_grid_bits=1.5)
        with pytest.raises(ValueError, match="snap_grid_bits"):
            pipeline_backend.TPUBackend(snap_grid_bits=65)
        with pytest.raises(ValueError, match="snap_grid_bits"):
            pipeline_backend.TPUBackend(snap_grid_bits=True)

    def test_boundary_values_are_accepted_and_threaded(self):
        b = pipeline_backend.TPUBackend(numeric_mode="safe",
                                        snap_grid_bits=-64)
        view = b.for_job(job_id="j1")
        assert view.numeric_mode == "safe"
        assert view.snap_grid_bits == -64
        assert pipeline_backend.TPUBackend().numeric_mode == "fast"
        assert pipeline_backend.TPUBackend().snap_grid_bits is None
