"""Unit tests for the device quantile kernel (executor.quantile_outputs)
against the host DenseQuantileTree on identical data.

Uses a small tree (branching 4, height 2 -> 16 leaves) so the multi-chunk
lax.map path is exercised with a handful of partitions.
"""

import jax.numpy as jnp
import jax.random
import numpy as np
import pytest

from pipelinedp_tpu import executor
from pipelinedp_tpu.aggregate_params import NoiseKind
from pipelinedp_tpu.ops import quantile_tree


def _make_cfg(n_partitions, quantiles, chunk, branching=4, height=2):
    plan = (executor.MetricPlanEntry('quantiles',
                                     tuple(f"q{i}"
                                           for i in range(len(quantiles))),
                                     1),)
    return executor.KernelConfig(n_partitions=n_partitions,
                                 linf=0,
                                 l0=0,
                                 total_bound=0,
                                 sample_per_partition=False,
                                 clip_per_value=False,
                                 clip_pair_sum=False,
                                 bounds_enforced=True,
                                 noise_kind=NoiseKind.LAPLACE,
                                 private_selection=False,
                                 selection=None,
                                 max_rows_per_privacy_id=1,
                                 plan=plan,
                                 degenerate_range=False,
                                 quantiles=tuple(quantiles),
                                 tree_height=height,
                                 branching=branching,
                                 quantile_chunk=chunk)


MIN_V, MAX_V = 0.0, 16.0


def _device_quantiles(values_per_partition, quantiles, chunk):
    P = len(values_per_partition)
    pks, leaves = [], []
    for p, vals in enumerate(values_per_partition):
        for v in vals:
            pks.append(p)
            leaves.append(v)
    cfg = _make_cfg(P, quantiles, chunk)
    n_leaves = cfg.branching**cfg.tree_height
    leaf_idx = np.clip(
        ((np.asarray(leaves, dtype=np.float64) - MIN_V) / (MAX_V - MIN_V) *
         n_leaves).astype(np.int32), 0, n_leaves - 1)
    qrows = (jnp.asarray(pks, dtype=jnp.int32), jnp.asarray(leaf_idx),
             jnp.ones(len(pks), dtype=bool))
    stds = jnp.asarray([1e-9])
    out = executor.quantile_outputs(qrows, MIN_V, MAX_V, stds,
                                    jax.random.PRNGKey(0), cfg)
    return np.stack(
        [np.asarray(out[f"q{i}"]) for i in range(len(quantiles))], axis=1)


def _host_quantiles(values, quantiles):
    tree = quantile_tree.DenseQuantileTree(MIN_V, MAX_V, height=2,
                                           branching_factor=4)
    tree.add_entries(values)
    return tree.compute_quantiles(1e9, 1e-5, 1, 1, list(quantiles),
                                  NoiseKind.LAPLACE,
                                  rng=np.random.default_rng(0))


# Note: bimodal counts are deliberately unbalanced (9 vs 11) — an exact tie
# at a subtree boundary makes the descent direction noise-driven on both the
# host and the device, which is correct DP behavior but untestable.
PARTITIONS = [
    [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0],
    [0.5] * 9 + [15.5] * 11,
    [10.0],
    list(np.linspace(0.1, 15.9, 100)),
    [3.3] * 7,
]


@pytest.mark.parametrize("chunk", [1, 2, 5])
def test_matches_host_tree(chunk):
    qs = [0.1, 0.5, 0.9]
    device = _device_quantiles(PARTITIONS, qs, chunk)
    for p, vals in enumerate(PARTITIONS):
        host = _host_quantiles(vals, qs)
        np.testing.assert_allclose(device[p], host, atol=1e-3,
                                   err_msg=f"partition {p}")


def test_chunked_equals_unchunked():
    qs = [0.25, 0.75]
    np.testing.assert_allclose(_device_quantiles(PARTITIONS, qs, 2),
                               _device_quantiles(PARTITIONS, qs, 5),
                               atol=1e-3)


def test_empty_partition_stays_in_range():
    # An empty tree's quantile is noise-driven (like the host path); it must
    # still be a finite value inside [min, max] and not disturb neighbors.
    device = _device_quantiles([[], [5.0] * 20], [0.5], 2)
    assert MIN_V <= device[0][0] <= MAX_V
    assert np.isfinite(device[0][0])
    assert device[1][0] == pytest.approx(5.5, abs=0.2)


def test_monotone_across_unsorted_quantiles():
    device = _device_quantiles(PARTITIONS, [0.9, 0.1, 0.5], 5)
    for p in range(len(PARTITIONS)):
        assert device[p][1] <= device[p][2] <= device[p][0]


def test_lazy_descent_many_partitions():
    # P >> quantile_chunk routes to the lazy path: per-level [P, B] counts
    # instead of chunked dense histograms. Parity with the host tree must
    # hold across a few hundred random partitions — except where a target
    # lands exactly on a subtree boundary, where the descent direction is
    # legitimately noise-driven (same caveat as the curated PARTITIONS); so
    # we require exact host agreement on >=90% of partitions and
    # leaf-resolution agreement with the true quantile everywhere.
    rng = np.random.default_rng(0)
    partitions = [
        list(rng.uniform(0.5, 15.5, size=rng.integers(5, 40)))
        for _ in range(300)
    ]
    qs = [0.25, 0.5, 0.9]
    device = _device_quantiles(partitions, qs, chunk=8)
    leaf_width = (MAX_V - MIN_V) / 16
    exact = 0
    for p, vals in enumerate(partitions):
        host = _host_quantiles(vals, qs)
        if np.allclose(device[p], host, atol=1e-3):
            exact += 1
        # The tree's value must land (to leaf resolution) between the
        # order statistic at q and the next one — exact boundary ties can
        # legitimately resolve to either side.
        svals = np.sort(vals)
        for qi, q in enumerate(qs):
            k = min(int(np.ceil(q * len(svals))) - 1, len(svals) - 1)
            lo = svals[max(k, 0)] - 2.5 * leaf_width
            hi = svals[min(k + 1, len(svals) - 1)] + 2.5 * leaf_width
            assert lo <= device[p][qi] <= hi, (p, q, device[p][qi], lo, hi)
    assert exact >= 270, f"only {exact}/300 partitions matched host exactly"


def test_lazy_descent_secure_noise():
    # The lazy path's per-node noise goes through the snapped table sampler
    # in secure mode; at tiny std the released quantiles still match.
    import dataclasses
    import jax
    from pipelinedp_tpu.ops import secure_noise

    cfg = _make_cfg(len(PARTITIONS), (0.5,), chunk=2)
    cfg = dataclasses.replace(cfg, secure=True)
    n_leaves = cfg.branching**cfg.tree_height
    pks, leaves = [], []
    for p, vals in enumerate(PARTITIONS):
        for v in vals:
            pks.append(p)
            leaves.append(
                min(int((v - MIN_V) / (MAX_V - MIN_V) * n_leaves),
                    n_leaves - 1))
    qrows = (jnp.asarray(pks, dtype=jnp.int32),
             jnp.asarray(leaves, dtype=jnp.int32),
             jnp.ones(len(pks), dtype=bool))
    stds = np.asarray([1e-6])
    thr_hi, thr_lo, gran = secure_noise.build_tables(stds, NoiseKind.LAPLACE)
    out = executor.quantile_outputs(
        qrows, MIN_V, MAX_V, jnp.asarray(stds), jax.random.PRNGKey(0), cfg,
        secure_tables=(jnp.asarray(thr_hi), jnp.asarray(thr_lo),
                       jnp.asarray(gran)))
    for p, vals in enumerate(PARTITIONS):
        host = _host_quantiles(vals, [0.5])
        assert np.asarray(out["q0"])[p] == pytest.approx(host[0], abs=0.05)


def test_noise_std_shared_with_host():
    # The kernel's std comes from the same helper the host tree uses.
    std = quantile_tree.per_level_noise_std(2.0, 1e-6, 3, 4, 4,
                                            NoiseKind.LAPLACE)
    assert std == pytest.approx(np.sqrt(2.0) * (3 * 4) / (2.0 / 4))
