"""Tests for the blocked large-partition-space path (parallel/large_p.py)."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, executor
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.ops import selection_ops
from pipelinedp_tpu.parallel import large_p

import jax


def _spec(n_partitions, private=True, metrics_list=None, l0=4, linf=8,
          eps=1.0, full=False):
    params = pdp.AggregateParams(
        metrics=metrics_list or [pdp.Metrics.COUNT, pdp.Metrics.SUM],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=l0,
        max_contributions_per_partition=linf,
        min_value=0.0,
        max_value=5.0)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    selection = None
    if private:
        budget = accountant.request_budget(MechanismType.GENERIC)
    accountant.compute_budgets()
    if private:
        selection = selection_ops.selection_params_from_host(
            params.partition_selection_strategy, budget.eps, budget.delta,
            params.max_partitions_contributed, None)
    cfg = executor.make_kernel_config(params, compound, n_partitions,
                                      private_selection=private,
                                      selection_params=selection)
    stds = executor.compute_noise_stds(compound, params)
    scalars = executor.kernel_scalars(params)
    if full:
        return cfg, stds, scalars, params, compound
    return cfg, stds, scalars


class TestRoundCapacity:

    def test_slack_bounded(self):
        for x in [1, 7, 8, 9, 100, 1000, 12345, 1 << 20, (1 << 20) + 1]:
            cap = large_p.round_capacity(x)
            assert cap >= max(x, 8)
            assert cap <= max(x, 8) * 1.125 + 8


class TestBlockedAggregation:

    def _data(self, n, n_ids, P, seed=0):
        rng = np.random.default_rng(seed)
        pid = rng.integers(0, n_ids, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        values = rng.uniform(0, 5, n)
        valid = np.ones(n, dtype=bool)
        return pid, pk, values, valid

    @pytest.mark.parametrize("block_partitions", [128, 32])
    def test_matches_dense_kernel_public_noise_free(self, block_partitions):
        # Public (no selection), zero noise, loose bounds -> blocked result
        # must EXACTLY match the dense kernel and the raw aggregate.
        # block_partitions=32 -> 32 blocks >> the 8-block dispatch window,
        # so _StagedDrain must flush older block groups mid-loop (bounding
        # staged HBM residency) without disturbing per-target append order.
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P,
                                                            private=False,
                                                            l0=P,
                                                            linf=64)
        stds = np.zeros_like(np.asarray(stds))
        pid, pk, values, valid = self._data(20_000, 500, P)
        key = jax.random.PRNGKey(0)
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  values,
                                                  valid,
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  stds,
                                                  key,
                                                  cfg,
                                                  block_partitions=block_partitions,
                                                  row_chunk=4096)
        assert list(kept) == list(range(P))
        expected_count = np.bincount(pk, minlength=P)
        expected_sum = np.bincount(pk,
                                   weights=np.clip(values, 0, 5),
                                   minlength=P)
        np.testing.assert_allclose(outputs["count"], expected_count,
                                   atol=1e-4)
        np.testing.assert_allclose(outputs["sum"], expected_sum, rtol=1e-5)

    def test_private_selection_blocked(self):
        # Partitions with many ids are kept, single-id partitions dropped —
        # across block boundaries.
        P = 300
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P, l0=20,
                                                             linf=4, eps=30)
        stds = np.zeros_like(np.asarray(stds))
        # Dense partitions 0..9 and 290..299 (first and last block); sparse
        # singles elsewhere.
        rows = []
        for p in list(range(10)) + list(range(290, 300)):
            for u in range(200):
                rows.append((u, p))
        for p in range(100, 110):
            rows.append((10_000 + p, p))
        pid = np.array([r[0] for r in rows], dtype=np.int32)
        pk = np.array([r[1] for r in rows], dtype=np.int32)
        values = np.ones(len(rows))
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  values,
                                                  np.ones(len(rows), bool),
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  stds,
                                                  jax.random.PRNGKey(1),
                                                  cfg,
                                                  block_partitions=64,
                                                  row_chunk=2048)
        kept = set(kept.tolist())
        assert set(range(10)).issubset(kept)
        assert set(range(290, 300)).issubset(kept)
        assert not kept & set(range(100, 110))

    def test_bounding_is_global_across_blocks(self):
        # One privacy id contributing to many partitions must be l0-bounded
        # globally even though its partitions land in different blocks.
        P = 256
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(
            P, private=False, l0=4, linf=1, metrics_list=[pdp.Metrics.COUNT])
        stds = np.zeros_like(np.asarray(stds))
        pid = np.zeros(P, dtype=np.int32)
        pk = np.arange(P, dtype=np.int32)
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  np.ones(P),
                                                  np.ones(P, bool),
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  stds,
                                                  jax.random.PRNGKey(2),
                                                  cfg,
                                                  block_partitions=32,
                                                  row_chunk=10_000)
        assert outputs["count"].sum() == pytest.approx(4.0, abs=1e-6)

    def test_ten_million_partitions_smoke(self):
        # P = 10^7 with tiny blocks of data: bounded memory, only kept
        # partitions returned.
        P = 10_000_000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P, l0=20,
                                                             linf=8, eps=30)
        rng = np.random.default_rng(7)
        n = 50_000
        pid = rng.integers(0, 2000, n).astype(np.int32)
        # Rows concentrated on 20 partitions spread across the huge space.
        hot = rng.integers(0, P, 20)
        pk = hot[rng.integers(0, 20, n)].astype(np.int32)
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  rng.uniform(0, 5, n),
                                                  np.ones(n, bool),
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  np.asarray(stds),
                                                  jax.random.PRNGKey(3),
                                                  cfg,
                                                  block_partitions=1 << 20)
        assert set(kept.tolist()).issubset(set(hot.tolist()))
        assert len(kept) > 0
        assert len(outputs["count"]) == len(kept)

    def test_mean_variance_blocked(self):
        # MEAN/VARIANCE exercise the nsum/nsum2 reduce columns through the
        # blocked path; noise-free public run must match the dense kernel.
        P = 500
        cfg, stds, scalars = _spec(P,
                                   private=False,
                                   metrics_list=[
                                       pdp.Metrics.MEAN, pdp.Metrics.VARIANCE
                                   ],
                                   l0=P,
                                   linf=64)
        min_v, max_v, min_s, max_s, mid = scalars
        stds = np.zeros_like(np.asarray(stds))
        pid, pk, values, valid = self._data(30_000, 400, P, seed=5)
        import jax.numpy as jnp
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  values,
                                                  valid,
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  stds,
                                                  jax.random.PRNGKey(2),
                                                  cfg,
                                                  block_partitions=128,
                                                  row_chunk=8192)
        ref_outputs, ref_keep, _ = executor.aggregate_kernel(
            jnp.asarray(pid), jnp.asarray(pk), jnp.asarray(values),
            jnp.asarray(valid), min_v, max_v, min_s, max_s, mid,
            jnp.asarray(stds), jax.random.PRNGKey(2), cfg)
        for name in ("mean", "variance"):
            np.testing.assert_allclose(outputs[name],
                                       np.asarray(ref_outputs[name]),
                                       rtol=1e-5,
                                       atol=1e-6,
                                       err_msg=name)

    def test_secure_blocked(self):
        # Secure snapped release through the blocked path: outputs live on
        # the secure grid and match the raw aggregate to grid resolution.
        from pipelinedp_tpu.ops import secure_noise
        import dataclasses as dc
        import jax.numpy as jnp
        P = 300
        cfg, stds, (min_v, max_v, min_s, max_s,
                    mid), params, compound = _spec(P,
                                                   private=False,
                                                   l0=P,
                                                   linf=64,
                                                   eps=1e6,
                                                   full=True)
        cfg = dc.replace(cfg, secure=True)
        sens = executor.compute_noise_sensitivities(compound, params)
        thr_hi, thr_lo, gran = secure_noise.build_tables(
            np.asarray(stds), pdp.NoiseKind.LAPLACE, sensitivities=sens)
        tables = (jnp.asarray(thr_hi), jnp.asarray(thr_lo),
                  jnp.asarray(gran))
        pid, pk, values, valid = self._data(10_000, 300, P, seed=6)
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  values,
                                                  valid,
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  np.asarray(stds),
                                                  jax.random.PRNGKey(3),
                                                  cfg,
                                                  block_partitions=128,
                                                  secure_tables=tables)
        expected = np.bincount(pk, minlength=P)
        np.testing.assert_allclose(outputs["count"], expected, atol=0.5)
        g = float(gran[0])
        ratios = outputs["count"] / g
        np.testing.assert_allclose(ratios, np.round(ratios), atol=1e-3)

    def test_empty_input(self):
        # Zero rows (e.g. everything filtered upstream) must return empty
        # results, not crash on undiscovered metric columns.
        P = 300
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        kept, outputs = large_p.aggregate_blocked(
            np.zeros(0, np.int32), np.zeros(0, np.int32), np.zeros(0),
            np.zeros(0, bool), min_v, max_v, min_s, max_s, mid,
            np.asarray(stds), jax.random.PRNGKey(0), cfg,
            block_partitions=64)
        assert len(kept) == 0
        assert len(outputs["count"]) == 0
        assert len(outputs["sum"]) == 0

    def test_sparse_blocks_skipped_private(self):
        # Only blocks containing rows run device kernels in private mode.
        P = 1 << 22
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P, l0=2,
                                                             linf=4, eps=30)
        pid = np.repeat(np.arange(500, dtype=np.int32), 2)
        pk = np.where(np.arange(1000) % 2 == 0, 7, P - 3).astype(np.int32)
        kept, outputs = large_p.aggregate_blocked(
            pid, pk, np.ones(1000), np.ones(1000, bool), min_v, max_v,
            min_s, max_s, mid,
            np.zeros_like(np.asarray(stds)), jax.random.PRNGKey(1), cfg,
            block_partitions=1 << 16)
        assert set(kept.tolist()) == {7, P - 3}
        assert outputs["count"].sum() == pytest.approx(1000, abs=1e-6)

    def test_percentile_blocked_matches_dense(self):
        # Noise-free percentiles: the blocked path (multiple blocks, lazy
        # per-block descent) must agree with the dense kernel's quantiles.
        P = 3000
        metrics = [
            pdp.Metrics.COUNT,
            pdp.Metrics.PERCENTILE(25),
            pdp.Metrics.PERCENTILE(90),
        ]
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(
            P, private=False, metrics_list=metrics, l0=P, linf=64)
        stds = np.zeros_like(np.asarray(stds))
        pid, pk, values, valid = self._data(30_000, 400, P, seed=5)
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  values,
                                                  valid,
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  stds,
                                                  jax.random.PRNGKey(2),
                                                  cfg,
                                                  block_partitions=256)
        dense_out, dense_keep, _ = executor.aggregate_kernel(
            pid, pk, values, valid, min_v, max_v, min_s, max_s, mid, stds,
            jax.random.PRNGKey(7), cfg)
        assert list(kept) == list(range(P))
        for name in ("percentile_25", "percentile_90"):
            np.testing.assert_allclose(outputs[name],
                                       np.asarray(dense_out[name]),
                                       atol=(max_v - min_v) / 1e4)

    # `slow`: ~23s scale exercise. Blocked-percentile correctness stays
    # in tier-1 via test_percentile_blocked_matches_dense; this adds the
    # P=10^7 bounded-memory regime on top.
    @pytest.mark.slow
    def test_percentile_blocked_huge_p_bounded_memory(self):
        # P = 10^7 with rows concentrated in a few partitions: only
        # row-bearing blocks run; percentile values stay close to the true
        # per-partition quantiles at zero noise.
        P = 10_000_000
        metrics = [pdp.Metrics.PERCENTILE(50)]
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(
            P, private=True, metrics_list=metrics, l0=4, linf=64, eps=30)
        stds = np.zeros_like(np.asarray(stds))
        rng = np.random.default_rng(9)
        n = 4000
        pid = np.arange(n, dtype=np.int32) % 997
        # Two populated partitions far apart in the space.
        pk = np.where(np.arange(n) % 2 == 0, 12345, P - 77).astype(np.int32)
        values = rng.uniform(0, 5, n)
        kept, outputs = large_p.aggregate_blocked(pid,
                                                  pk,
                                                  values,
                                                  valid := np.ones(n, bool),
                                                  min_v,
                                                  max_v,
                                                  min_s,
                                                  max_s,
                                                  mid,
                                                  stds,
                                                  jax.random.PRNGKey(4),
                                                  cfg,
                                                  block_partitions=1 << 20)
        assert set(kept.tolist()) == {12345, P - 77}
        for j, pk_id in enumerate(kept.tolist()):
            true_median = np.median(values[pk == pk_id])
            # Tree quantiles quantize to leaf width; tolerance is a couple
            # of leaves.
            leaf = (max_v - min_v) / (cfg.branching**cfg.tree_height)
            assert abs(outputs["percentile_50"][j] -
                       true_median) < 3 * leaf + 0.05

class TestStagingRegimesAgree:

    def test_device_resident_and_host_staged_agree(self):
        """The two row-staging regimes (rows fit one chunk vs chunked host
        staging) must produce the same kept set and noise-free values on
        bounded data at huge epsilon — per-chunk RNG folding differs, so
        agreement must come from determinism of the bounded computation,
        not shared draws."""
        rng = np.random.default_rng(2)
        P = 1 << 12
        # Bounded by construction: each user in exactly l0=4 partitions,
        # 2 <= linf rows per pair; plus lone 1-user partitions that private
        # selection must deterministically drop.
        pid, pk, values = [], [], []
        for u in range(600):
            for j in range(4):
                target = (u % 30) * 4 + j
                for r in range(2):
                    pid.append(u)
                    pk.append(target)
                    values.append(float((u + j + r) % 5))
        for j in range(4):
            pid.append(601)
            pk.append(3000 + j)
            values.append(1.0)
        pid = np.asarray(pid, np.int32)
        pk = np.asarray(pk, np.int32)
        values = np.asarray(values)
        valid = np.ones(len(pid), bool)

        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(
            P,
            eps=1e7,
            metrics_list=[
                pdp.Metrics.COUNT, pdp.Metrics.SUM,
                pdp.Metrics.PERCENTILE(50)
            ])

        def run(row_chunk):
            return large_p.aggregate_blocked(pid, pk, values, valid, min_v,
                                             max_v, min_s, max_s, mid,
                                             np.asarray(stds),
                                             jax.random.PRNGKey(3), cfg,
                                             block_partitions=1 << 10,
                                             row_chunk=row_chunk)

        kept_fast, outs_fast = run(1 << 20)
        kept_host, outs_host = run(1024)
        assert np.array_equal(kept_fast, kept_host)
        assert len(kept_fast) == 120  # the 30*4 dense partitions
        assert np.all(np.diff(kept_fast) > 0)
        np.testing.assert_allclose(outs_fast["count"], outs_host["count"],
                                   atol=1e-2)
        np.testing.assert_allclose(outs_fast["sum"], outs_host["sum"],
                                   atol=1e-1)
        # Percentiles: leaf staging must survive the host-staged merge;
        # values are leaf-quantized and noise is negligible at huge eps.
        np.testing.assert_allclose(outs_fast["percentile_50"],
                                   outs_host["percentile_50"],
                                   atol=1e-2)


class TestPresortedReduceContract:

    def test_presorted_matches_sorted_reduce(self):
        """reduce_rows_to_partitions(presorted=True) must equal the sorting
        variant whenever rows arrive (kept-first, spk-ascending) — the
        exact order _bounded_compact_kernel emits."""
        import jax.numpy as jnp
        rng = np.random.default_rng(4)
        n, P = 4096, 64
        spk = np.sort(rng.integers(0, P, n)).astype(np.int32)
        keep = np.ones(n, bool)
        # Tail of dropped rows, as the compact kernel produces.
        keep[-128:] = False
        spk[-128:] = np.iinfo(np.int32).max
        pair = rng.random(n) < 0.3
        cols = {"sum": rng.random(n).astype(np.float32)}
        args = (jnp.asarray(spk), jnp.asarray(keep), jnp.asarray(pair),
                {k: jnp.asarray(v) for k, v in cols.items()})
        ref = executor.reduce_rows_to_partitions(*args, P, 0)
        fast = executor.reduce_rows_to_partitions(*args, P, 0,
                                                  presorted=True)
        for name in ref:
            np.testing.assert_allclose(np.asarray(fast[name]),
                                       np.asarray(ref[name]), atol=1e-5)


class TestBlockedSelection:
    """O(kept) standalone selection (large_p.select_partitions_blocked)."""

    def _mixed_data(self, P, dense_parts, n_users=60, l0=30, seed=0):
        # Dense partitions get n_users distinct ids each; every 7th other
        # partition gets exactly one id -> huge-eps selection decisions are
        # deterministic (keep prob 1 vs <= delta), so the blocked path's
        # different per-block RNG stream cannot change the outcome.
        rows = []
        for p in dense_parts:
            for u in range(n_users):
                rows.append((u * 100_003 + p, p))
        sparse = [p for p in range(P) if p not in set(dense_parts)][::7]
        for i, p in enumerate(sparse):
            rows.append((10_000_000 + i, p))
        pid = np.array([r[0] for r in rows], np.int64)
        pk = np.array([r[1] for r in rows], np.int32)
        valid = np.ones(len(rows), bool)
        return pid, pk, valid

    def _selection(self, l0):
        return selection_ops.selection_params_from_host(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1e7, 1e-5,
            l0, None)

    def test_matches_dense_kernel_across_blocks(self):
        import jax.numpy as jnp
        P, l0 = 300, 30
        dense_parts = list(range(10)) + [150] + list(range(290, 300))
        pid, pk, valid = self._mixed_data(P, dense_parts, l0=l0)
        sel = self._selection(l0)
        key = jax.random.PRNGKey(5)
        dense_keep = np.asarray(
            executor.select_partitions_kernel(jnp.asarray(pid), jnp.asarray(
                pk), jnp.asarray(valid), key, l0, P, sel))
        kept = large_p.select_partitions_blocked(pid,
                                                 pk,
                                                 valid,
                                                 key,
                                                 l0,
                                                 P,
                                                 sel,
                                                 block_partitions=64)
        np.testing.assert_array_equal(kept, np.nonzero(dense_keep)[0])
        assert kept.dtype == np.int64
        # 19 blocks >> the 8-block window: the staged-drain flush path
        # must leave the kept set and ascending order unchanged.
        kept_small = large_p.select_partitions_blocked(pid,
                                                       pk,
                                                       valid,
                                                       key,
                                                       l0,
                                                       P,
                                                       sel,
                                                       block_partitions=16)
        np.testing.assert_array_equal(kept_small, np.nonzero(dense_keep)[0])

    def test_single_block_and_empty(self):
        P, l0 = 50, 10
        sel = self._selection(l0)
        key = jax.random.PRNGKey(9)
        pid, pk, valid = self._mixed_data(P, [3, 40], l0=l0)
        kept = large_p.select_partitions_blocked(pid, pk, valid, key, l0, P,
                                                 sel)
        assert set(kept) == {3, 40}
        # All rows invalid -> every block is empty and skipped.
        kept = large_p.select_partitions_blocked(pid, pk,
                                                 np.zeros_like(valid), key,
                                                 l0, P, sel)
        assert len(kept) == 0

    def test_l0_sampling_binds(self):
        # One privacy id spread over every partition with l0=2: at most 2
        # pair contributions survive, none reach keep-probability 1, and
        # with delta tiny every partition must be dropped.
        P = 96
        pid = np.zeros(P, np.int32)
        pk = np.arange(P, dtype=np.int32)
        valid = np.ones(P, bool)
        sel = self._selection(l0=2)
        kept = large_p.select_partitions_blocked(pid, pk, valid,
                                                 jax.random.PRNGKey(1), 2, P,
                                                 sel, block_partitions=32)
        assert len(kept) == 0
