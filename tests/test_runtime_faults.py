"""Fault-tolerant blocked execution: journaled resume, deterministic-noise
retry, graceful degradation — driven by the fault-injection harness
(pipelinedp_tpu/runtime/)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pipelinedp_tpu as pdp
from pipelinedp_tpu import combiners, executor, runtime
from pipelinedp_tpu.aggregate_params import MechanismType
from pipelinedp_tpu.ops import selection_ops
from pipelinedp_tpu.parallel import large_p, make_mesh
from pipelinedp_tpu.runtime import faults, journal as journal_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import telemetry

pytestmark = pytest.mark.faults


def _spec(P, eps=1.0, l0=4, linf=8):
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=l0,
                                 max_contributions_per_partition=linf,
                                 min_value=0.0,
                                 max_value=5.0)
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=1e-6)
    compound = combiners.create_compound_combiner(params, accountant)
    budget = accountant.request_budget(MechanismType.GENERIC)
    accountant.compute_budgets()
    selection = selection_ops.selection_params_from_host(
        params.partition_selection_strategy, budget.eps, budget.delta, l0,
        None)
    cfg = executor.make_kernel_config(params, compound, P,
                                      private_selection=True,
                                      selection_params=selection)
    stds = executor.compute_noise_stds(compound, params)
    return cfg, stds, executor.kernel_scalars(params)


def _data(n=20_000, n_ids=500, P=1000, seed=0):
    rng = np.random.default_rng(seed)
    pid = rng.integers(0, n_ids, n).astype(np.int32)
    pk = rng.integers(0, P, n).astype(np.int32)
    values = rng.uniform(0, 5, n)
    return pid, pk, values, np.ones(n, bool)


# A fast policy so retry/backoff tests don't sleep for real.
FAST = retry_lib.RetryPolicy(max_retries=3, base_delay=0.0, max_delay=0.0)


class TestFaultSchedule:

    def test_take_consumes_and_matches(self):
        sched = faults.FaultSchedule([
            faults.Fault("dispatch", block=2, times=2),
            faults.Fault("oom"),
        ])
        assert sched.take("dispatch", 0) is None  # wrong block
        assert sched.take("dispatch", 2) is not None
        assert sched.take("dispatch", 2) is not None
        assert sched.take("dispatch", 2) is None  # spent
        assert sched.take("oom", 7) is not None  # block=None matches any
        assert sched.pending() == 0

    def test_inject_scopes_and_raises(self):
        with faults.inject(faults.FaultSchedule([faults.Fault("oom")])):
            with pytest.raises(faults.InjectedOOMError):
                faults.maybe_fail("oom", 0)
        faults.maybe_fail("oom", 0)  # no active schedule outside the scope

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            faults.Fault("meteor")


class TestRetryClassification:

    def test_markers(self):
        assert retry_lib.is_oom(RuntimeError("RESOURCE_EXHAUSTED: hbm"))
        assert not retry_lib.is_transient(RuntimeError("RESOURCE_EXHAUSTED"))
        assert retry_lib.is_transient(RuntimeError("UNAVAILABLE: socket"))
        assert not retry_lib.is_transient(ValueError("shape mismatch"))
        assert retry_lib.is_oom(faults.InjectedOOMError("x"))
        assert not retry_lib.is_transient(faults.InjectedFatalError("x"))

    def test_retry_call_bounded(self):
        calls = []

        def fn():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE: flaky")

        with pytest.raises(RuntimeError):
            retry_lib.retry_call(fn, FAST, sleep=lambda _: None)
        assert len(calls) == FAST.max_retries + 1

    def test_no_new_mechanisms_guard(self):
        acc = pdp.NaiveBudgetAccountant(total_epsilon=1.0, total_delta=1e-6)
        with acc.no_new_mechanisms("test"):
            pass  # no registration: fine
        with pytest.raises(AssertionError, match="double-spend"):
            with acc.no_new_mechanisms("test"):
                acc.request_budget(MechanismType.LAPLACE)


class TestRetryDeterminism:
    """A retried block redraws bit-identical noise: the faulted run's
    outputs equal the fault-free run's exactly, noise included."""

    def _run(self, retry=FAST, **kwargs):
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data(P=P)
        return large_p.aggregate_blocked(pid, pk, values, valid, min_v,
                                         max_v, min_s, max_s, mid,
                                         np.asarray(stds),
                                         jax.random.PRNGKey(7), cfg,
                                         block_partitions=128, retry=retry,
                                         **kwargs)

    def test_killed_dispatches_bit_identical_with_noise(self):
        base_kept, base_out = self._run()
        before = telemetry.snapshot()
        sched = faults.FaultSchedule([
            faults.Fault("dispatch", block=0, times=2),
            faults.Fault("consume", block=2),
            faults.Fault("slow", block=3, delay=0.01),
        ])
        with faults.inject(sched):
            kept, out = self._run()
        assert sched.pending() == 0
        np.testing.assert_array_equal(base_kept, kept)
        for name in base_out:
            np.testing.assert_array_equal(base_out[name], out[name],
                                          err_msg=name)
        delta = telemetry.delta(before)
        assert delta.get("block_retries", 0) >= 3
        assert delta.get("injected_faults", 0) == 4

    def test_retries_exhaust_then_raise(self):
        sched = faults.FaultSchedule([
            faults.Fault("dispatch", block=1, times=FAST.max_retries + 1)
        ])
        with faults.inject(sched):
            with pytest.raises(faults.InjectedDispatchError):
                self._run()
        assert sched.pending() == 0


class TestOOMDegradation:
    """OOM on a block kernel halves the partition block capacity and
    re-plans instead of aborting; already-consumed blocks keep their
    results.

    Re-planned blocks legitimately draw FRESH selection/noise keys (their
    OOM'd dispatch released nothing), so the parity data must make every
    selection decision key-independent: dense partitions with 120 distinct
    ids (keep probability ~1 at eps=30) and single-id partitions (keep
    probability ~0), noise-free."""

    DENSE = ((np.arange(12) * 77 + 5) % 1000).astype(np.int64)

    def _run_noise_free(self, block_partitions=128):
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P, eps=30,
                                                             linf=64)
        n_per = 120
        pid = (np.repeat(np.arange(n_per), len(self.DENSE)) * 1003 +
               np.tile(np.arange(len(self.DENSE)), n_per)).astype(np.int32)
        pk = np.tile(self.DENSE, n_per).astype(np.int32)
        rng = np.random.default_rng(4)
        values = rng.uniform(0, 5, len(pk))
        pid = np.concatenate([pid, 900_000 + np.arange(5, dtype=np.int32)])
        pk = np.concatenate(
            [pk, ((np.arange(5) * 311 + 9) % P).astype(np.int32)])
        values = np.concatenate([values, np.ones(5)])
        valid = np.ones(len(pid), bool)
        return large_p.aggregate_blocked(pid, pk, values, valid, min_v,
                                         max_v, min_s, max_s, mid,
                                         np.zeros_like(np.asarray(stds)),
                                         jax.random.PRNGKey(5), cfg,
                                         block_partitions=block_partitions,
                                         retry=FAST)

    def test_oom_halves_capacity_and_completes(self):
        base_kept, base_out = self._run_noise_free()
        np.testing.assert_array_equal(base_kept, np.sort(self.DENSE))
        before = telemetry.snapshot()
        with faults.inject(
                faults.FaultSchedule([faults.Fault("oom", block=3)])):
            kept, out = self._run_noise_free()
        np.testing.assert_array_equal(base_kept, kept)
        np.testing.assert_allclose(base_out["count"], out["count"],
                                   atol=1e-9)
        np.testing.assert_allclose(base_out["sum"], out["sum"], rtol=1e-6)
        assert telemetry.delta(before).get("block_oom_degradations") == 1

    def test_repeated_oom_keeps_halving(self):
        before = telemetry.snapshot()
        with faults.inject(
                faults.FaultSchedule([
                    faults.Fault("oom", block=2),
                    faults.Fault("oom", block=0),
                ])):
            kept, _ = self._run_noise_free()
        assert telemetry.delta(before).get("block_oom_degradations") == 2
        base_kept, _ = self._run_noise_free()
        np.testing.assert_array_equal(base_kept, kept)

    def test_oom_below_floor_propagates(self):
        # A schedule that OOMs every generation's first block until the
        # capacity floor: the driver must stop degrading and raise.
        with faults.inject(
                faults.FaultSchedule(
                    [faults.Fault("oom", times=64)])):
            with pytest.raises(retry_lib.BlockOOMError):
                self._run_noise_free(block_partitions=16)


class TestJournalResume:

    def _run(self, key=7, **kwargs):
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data(P=P)
        return large_p.aggregate_blocked(pid, pk, values, valid, min_v,
                                         max_v, min_s, max_s, mid,
                                         np.asarray(stds),
                                         jax.random.PRNGKey(key), cfg,
                                         block_partitions=128, retry=FAST,
                                         **kwargs)

    def test_fatal_crash_then_resume_bit_identical(self):
        base_kept, base_out = self._run()
        journal = runtime.BlockJournal()
        with faults.inject(
                faults.FaultSchedule([faults.Fault("fatal", block=5)])):
            with pytest.raises(faults.InjectedFatalError):
                self._run(journal=journal, job_id="job-resume")
        consumed = list(journal.keys("job-resume"))
        assert 0 < len(consumed) < 8  # partial progress was journaled
        before = telemetry.snapshot()
        kept, out = self._run(journal=journal, job_id="job-resume")
        np.testing.assert_array_equal(base_kept, kept)
        for name in base_out:
            np.testing.assert_array_equal(base_out[name], out[name],
                                          err_msg=name)
        assert telemetry.delta(before).get("journal_replays") == \
            len(consumed)

    def test_resume_is_per_job(self):
        journal = runtime.BlockJournal()
        kept_a, _ = self._run(journal=journal, job_id="job-a")
        # A different job id must not replay job-a's blocks.
        before = telemetry.snapshot()
        kept_b, _ = self._run(key=8, journal=journal, job_id="job-b")
        assert telemetry.delta(before).get("journal_replays") is None
        assert list(journal.keys("job-a")) == list(journal.keys("job-b"))
        np.testing.assert_array_equal(kept_a, np.asarray(kept_a))
        del kept_b

    def test_directory_persistence_across_instances(self, tmp_path):
        journal = runtime.BlockJournal(str(tmp_path))
        record = journal_lib.BlockRecord(
            ids=np.arange(5, dtype=np.int64),
            outputs={"count": np.full(5, 2.0)})
        journal.put("jobX", journal_lib.block_key(0, 64), record)
        fresh = runtime.BlockJournal(str(tmp_path))
        loaded = fresh.get("jobX", journal_lib.block_key(0, 64))
        np.testing.assert_array_equal(loaded.ids, record.ids)
        np.testing.assert_array_equal(loaded.outputs["count"],
                                      record.outputs["count"])
        fresh.clear("jobX")
        assert runtime.BlockJournal(str(tmp_path)).get(
            "jobX", journal_lib.block_key(0, 64)) is None

    def test_crash_resume_across_journal_directory(self, tmp_path):
        """Process-crash model: the resume uses a FRESH BlockJournal over
        the same directory (nothing survives in memory)."""
        base_kept, base_out = self._run()
        with faults.inject(
                faults.FaultSchedule([faults.Fault("fatal", block=4)])):
            with pytest.raises(faults.InjectedFatalError):
                self._run(journal=runtime.BlockJournal(str(tmp_path)),
                          job_id="j")
        kept, out = self._run(journal=runtime.BlockJournal(str(tmp_path)),
                              job_id="j")
        np.testing.assert_array_equal(base_kept, kept)
        for name in base_out:
            np.testing.assert_array_equal(base_out[name], out[name],
                                          err_msg=name)


def _flip_middle_byte(path):
    import os
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))


class TestJournalIntegrity:
    """Corrupt/truncated records are quarantined — renamed aside, never
    replayed — and the resumed run recomputes them bit-identically."""

    def _record(self):
        return journal_lib.BlockRecord(ids=np.arange(7, dtype=np.int64),
                                       outputs={"count": np.full(7, 3.0)})

    def test_flipped_byte_quarantined(self, tmp_path):
        j = runtime.BlockJournal(str(tmp_path))
        key = journal_lib.block_key(0, 64)
        j.put("jq", key, self._record())
        _flip_middle_byte(j._path("jq", key))
        fresh = runtime.BlockJournal(str(tmp_path))
        before = telemetry.snapshot()
        assert fresh.get("jq", key) is None
        assert telemetry.delta(before).get("journal_quarantined") == 1
        # Renamed aside: no longer listed, and a second get stays None
        # without re-counting.
        assert list(fresh.keys("jq")) == []
        assert fresh.get("jq", key) is None
        quarantined = [
            p.name for p in tmp_path.iterdir() if ".corrupt" in p.name
        ]
        assert len(quarantined) == 1

    def test_truncated_record_quarantined(self, tmp_path):
        import os
        j = runtime.BlockJournal(str(tmp_path))
        key = journal_lib.block_key(64, 64)
        j.put("jq", key, self._record())
        path = j._path("jq", key)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        assert runtime.BlockJournal(str(tmp_path)).get("jq", key) is None

    def test_missing_checksum_never_replayed(self, tmp_path):
        # A record written without a checksum (e.g. by a pre-integrity
        # build) is unverifiable and must not be replayed as released
        # truth.
        j = runtime.BlockJournal(str(tmp_path))
        key = journal_lib.block_key(128, 64)
        path = j._path("jq", key)
        np.savez(path, ids=np.arange(3, dtype=np.int64))
        assert runtime.BlockJournal(str(tmp_path)).get("jq", key) is None

    def test_stale_tmp_files_swept(self, tmp_path):
        (tmp_path / "orphanXYZ.tmp").write_bytes(b"half-written")
        runtime.BlockJournal(str(tmp_path))
        assert not (tmp_path / "orphanXYZ.tmp").exists()

    def test_good_records_round_trip_with_checksum(self, tmp_path):
        j = runtime.BlockJournal(str(tmp_path))
        key = journal_lib.block_key(0, 32)
        record = self._record()
        j.put("ok", key, record)
        loaded = runtime.BlockJournal(str(tmp_path)).get("ok", key)
        np.testing.assert_array_equal(loaded.ids, record.ids)
        np.testing.assert_array_equal(loaded.outputs["count"],
                                      record.outputs["count"])

    def test_compact_drops_superseded_geometries(self, tmp_path):
        j = runtime.BlockJournal(str(tmp_path))
        # Plan: [0, 128) at C=128 (gen 0), then re-planned to C=64 from
        # 128 (gen 1) — so C=128 records at base >= 128 are superseded.
        j.put(
            "jc", journal_lib.PLAN_KEY,
            journal_lib.BlockRecord(ids=np.asarray(
                [0, 128, 0, 128, 64, 1], dtype=np.int64),
                                    outputs={}))
        j.put("jc", journal_lib.block_key(0, 128), self._record())
        j.put("jc", journal_lib.block_key(128, 128), self._record())
        j.put("jc", journal_lib.block_key(128, 64), self._record())
        j.put("jc", journal_lib.block_key(192, 64), self._record())
        before = telemetry.snapshot()
        dropped = j.compact("jc", n_partitions=256)
        assert dropped == 1
        assert telemetry.delta(before).get("journal_compacted") == 1
        assert j.get("jc", journal_lib.block_key(128, 128)) is None
        for live in (journal_lib.block_key(0, 128),
                     journal_lib.block_key(128, 64),
                     journal_lib.block_key(192, 64)):
            assert j.get("jc", live) is not None
        # Idempotent, and a fresh instance over the directory agrees.
        assert j.compact("jc", n_partitions=256) == 0
        assert runtime.BlockJournal(str(tmp_path)).compact(
            "jc", n_partitions=256) == 0

    def test_compact_without_plan_is_noop(self, tmp_path):
        j = runtime.BlockJournal(str(tmp_path))
        j.put("jn", journal_lib.block_key(0, 128), self._record())
        assert j.compact("jn") == 0
        assert j.get("jn", journal_lib.block_key(0, 128)) is not None


class TestQuarantineResumeAllDrivers:
    """Crash -> corrupt one journal record on disk -> resume with a fresh
    journal instance: the corrupt record is quarantined (never replayed),
    the block recomputes under the same key, and the final outputs are
    bit-identical to the fault-free run — across all four blocked/sharded
    drivers."""

    def _corrupt_one_record(self, tmp_path, job):
        import os
        records = sorted(p for p in os.listdir(str(tmp_path))
                         if p.startswith(job + "__") and
                         p.endswith(".npz") and "__plan__" not in p)
        assert records, "crashed run journaled nothing"
        _flip_middle_byte(str(tmp_path / records[0]))

    def _check(self, tmp_path, job, run):
        base = run(None)
        with faults.inject(
                faults.FaultSchedule([faults.Fault("fatal", block=3)])):
            with pytest.raises(faults.InjectedFatalError):
                run(runtime.BlockJournal(str(tmp_path)))
        self._corrupt_one_record(tmp_path, job)
        before = telemetry.snapshot()
        resumed = run(runtime.BlockJournal(str(tmp_path)))
        delta = telemetry.delta(before)
        assert delta.get("journal_quarantined") == 1, delta
        assert delta.get("journal_replays", 0) >= 1, delta
        if isinstance(base, tuple):
            kept, out = base
            kept_r, out_r = resumed
            np.testing.assert_array_equal(kept, kept_r)
            for name in out:
                np.testing.assert_array_equal(out[name], out_r[name],
                                              err_msg=name)
        else:
            np.testing.assert_array_equal(base, resumed)
        snap = runtime.health.for_job(job).snapshot()
        assert snap["journal_quarantined"] >= 1
        assert snap["state"] == "DEGRADED"

    def test_aggregate_blocked(self, tmp_path):
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data(P=P)

        def run(journal):
            return large_p.aggregate_blocked(
                pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
                np.asarray(stds), jax.random.PRNGKey(7), cfg,
                block_partitions=128, retry=FAST, journal=journal,
                job_id="qa-agg")

        self._check(tmp_path, "qa-agg", run)

    def test_select_partitions_blocked(self, tmp_path):
        P, l0 = 1000, 30
        selection = selection_ops.selection_params_from_host(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1e7, 1e-5,
            l0, None)
        rows = []
        for p in range(0, P, 7):
            for u in range(40):
                rows.append((u * 100_003 + p, p))
        pid = np.array([r[0] for r in rows], np.int64)
        pk = np.array([r[1] for r in rows], np.int32)
        valid = np.ones(len(rows), bool)

        def run(journal):
            return large_p.select_partitions_blocked(
                pid, pk, valid, jax.random.PRNGKey(5), l0, P, selection,
                block_partitions=128, retry=FAST, journal=journal,
                job_id="qa-sel")

        self._check(tmp_path, "qa-sel", run)

    def test_aggregate_blocked_sharded(self, tmp_path):
        mesh = make_mesh(n_devices=8)
        P = 1 << 12
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data(P=P)
        pk = (pk.astype(np.int64) % P).astype(np.int32)

        def run(journal):
            return large_p.aggregate_blocked_sharded(
                mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s,
                mid, np.asarray(stds), jax.random.PRNGKey(7), cfg,
                block_partitions=1 << 9, retry=FAST, journal=journal,
                job_id="qa-agg-sh")

        self._check(tmp_path, "qa-agg-sh", run)

    def test_select_partitions_blocked_sharded(self, tmp_path):
        mesh = make_mesh(n_devices=8)
        P, l0 = 1 << 12, 30
        selection = selection_ops.selection_params_from_host(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1e7, 1e-5,
            l0, None)
        rows = []
        for p in range(0, P, 29):
            for u in range(40):
                rows.append((u * 100_003 + p, p))
        pid = np.array([r[0] for r in rows], np.int64)
        pk = np.array([r[1] for r in rows], np.int32)
        valid = np.ones(len(rows), bool)

        def run(journal):
            return large_p.select_partitions_blocked_sharded(
                mesh, pid, pk, valid, jax.random.PRNGKey(5), l0, P,
                selection, block_partitions=1 << 9, retry=FAST,
                journal=journal, job_id="qa-sel-sh")

        self._check(tmp_path, "qa-sel-sh", run)

    def test_corrupt_fault_kind_end_to_end(self, tmp_path):
        """The scripted 'corrupt' fault (vs. manual byte surgery above):
        a record poisoned the moment it is written is quarantined on the
        cross-process resume and the rerun is bit-identical."""
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = _spec(P)
        pid, pk, values, valid = _data(P=P)

        def run(journal):
            return large_p.aggregate_blocked(
                pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
                np.asarray(stds), jax.random.PRNGKey(7), cfg,
                block_partitions=128, retry=FAST, journal=journal,
                job_id="qa-corrupt")

        base = run(None)
        sched = faults.FaultSchedule([
            faults.Fault("corrupt", mode="truncate"),
            faults.Fault("fatal", block=5),
        ])
        with faults.inject(sched):
            with pytest.raises(faults.InjectedFatalError):
                run(runtime.BlockJournal(str(tmp_path)))
        assert sched.pending() == 0
        before = telemetry.snapshot()
        resumed = run(runtime.BlockJournal(str(tmp_path)))
        delta = telemetry.delta(before)
        assert delta.get("journal_quarantined") == 1, delta
        np.testing.assert_array_equal(base[0], resumed[0])
        for name in base[1]:
            np.testing.assert_array_equal(base[1][name], resumed[1][name],
                                          err_msg=name)


class TestBlockedSelectionFaults:

    def test_selection_faulted_matches(self):
        P, l0 = 300, 30
        selection = selection_ops.selection_params_from_host(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1e7, 1e-5,
            l0, None)
        rows = []
        for p in list(range(10)) + list(range(290, 300)):
            for u in range(200):
                rows.append((u * 100_003 + p, p))
        for p in range(100, 110):
            rows.append((10_000_000 + p, p))
        pid = np.array([r[0] for r in rows], np.int64)
        pk = np.array([r[1] for r in rows], np.int32)
        valid = np.ones(len(rows), bool)
        key = jax.random.PRNGKey(5)
        base = large_p.select_partitions_blocked(pid, pk, valid, key, l0, P,
                                                 selection,
                                                 block_partitions=64)
        journal = runtime.BlockJournal()
        with faults.inject(
                faults.FaultSchedule([
                    faults.Fault("dispatch", block=0),
                    faults.Fault("oom", block=2),
                ])):
            kept = large_p.select_partitions_blocked(
                pid, pk, valid, key, l0, P, selection, block_partitions=64,
                retry=FAST, journal=journal, job_id="sel")
        np.testing.assert_array_equal(base, kept)
        # Resume replays everything: zero new dispatches, same answer.
        before = telemetry.snapshot()
        kept2 = large_p.select_partitions_blocked(
            pid, pk, valid, key, l0, P, selection, block_partitions=64,
            retry=FAST, journal=journal, job_id="sel")
        np.testing.assert_array_equal(base, kept2)
        assert telemetry.delta(before).get("journal_replays", 0) > 0


class TestMeshedFaults:
    """Collective-failure fallback + the full fault schedule over the
    8-device mesh (conftest forces the virtual CPU mesh)."""

    def _mesh_spec(self):
        mesh = make_mesh(n_devices=8)
        P = 1 << 12
        cfg, stds, scalars = _spec(P, eps=30, linf=64)
        stds = np.zeros_like(np.asarray(stds))
        dense = (np.arange(12) * 331 + 17) % P
        n_per = 120
        pid = (np.repeat(np.arange(n_per), len(dense)) * 1003 +
               np.tile(np.arange(len(dense)), n_per)).astype(np.int32)
        pk = np.tile(dense, n_per).astype(np.int32)
        rng = np.random.default_rng(3)
        values = rng.uniform(0, 5, len(pk))
        pid = np.concatenate([pid, 900_000 + np.arange(5, dtype=np.int32)])
        pk = np.concatenate(
            [pk, ((np.arange(5) * 777 + 9) % P).astype(np.int32)])
        values = np.concatenate([values, np.ones(5)])
        valid = np.ones(len(pid), bool)
        return mesh, P, cfg, stds, scalars, (pid, pk, values, valid)

    def test_collective_failure_falls_back_to_host_reshard(self):
        mesh, P, cfg, stds, scalars, cols = self._mesh_spec()
        min_v, max_v, min_s, max_s, mid = scalars
        pid, pk, values, valid = cols
        key = jax.random.PRNGKey(11)
        dev = (jnp.asarray(pid), jnp.asarray(pk), jnp.asarray(values),
               jnp.asarray(valid))
        base_kept, base_out = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=1 << 9)
        before = telemetry.snapshot()
        with faults.inject(
                faults.FaultSchedule([faults.Fault("collective")])):
            kept, out = large_p.aggregate_blocked_sharded(
                mesh, *dev, min_v, max_v, min_s, max_s, mid, stds, key,
                cfg, block_partitions=1 << 9, retry=FAST)
        np.testing.assert_array_equal(base_kept, kept)
        np.testing.assert_allclose(base_out["count"], out["count"],
                                   atol=1e-9)
        np.testing.assert_allclose(base_out["sum"], out["sum"], rtol=1e-6,
                                   atol=1e-6)
        assert telemetry.delta(before).get("reshard_host_fallbacks") == 1

    def test_full_schedule_blocked_sharded(self):
        mesh, P, cfg, stds, scalars, cols = self._mesh_spec()
        min_v, max_v, min_s, max_s, mid = scalars
        pid, pk, values, valid = cols
        key = jax.random.PRNGKey(11)
        dev = (jnp.asarray(pid), jnp.asarray(pk), jnp.asarray(values),
               jnp.asarray(valid))
        base_kept, base_out = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=1 << 9)
        sched = faults.FaultSchedule([
            faults.Fault("collective"),
            faults.Fault("dispatch", block=0, times=2),
            faults.Fault("consume", block=1),
            faults.Fault("oom", block=3),
            faults.Fault("slow", block=4, delay=0.01),
        ])
        before = telemetry.snapshot()
        with faults.inject(sched):
            kept, out = large_p.aggregate_blocked_sharded(
                mesh, *dev, min_v, max_v, min_s, max_s, mid, stds, key,
                cfg, block_partitions=1 << 9, retry=FAST)
        assert sched.pending() == 0
        np.testing.assert_array_equal(base_kept, kept)
        np.testing.assert_allclose(base_out["count"], out["count"],
                                   atol=1e-9)
        np.testing.assert_allclose(base_out["sum"], out["sum"], rtol=1e-6,
                                   atol=1e-6)
        delta = telemetry.delta(before)
        assert delta.get("reshard_host_fallbacks") == 1
        assert delta.get("block_oom_degradations") == 1
        assert delta.get("block_retries", 0) >= 3


class TestEngineLevelInvariants:
    """Whole-engine faulted runs: identical results, zero duplicate
    mechanism registrations in the budget ledger."""

    def _aggregate(self, backend, rows):
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
            noise_kind=pdp.NoiseKind.LAPLACE,
            max_partitions_contributed=4,
            max_contributions_per_partition=8,
            min_value=0.0,
            max_value=5.0)
        extractors = pdp.DataExtractors(
            privacy_id_extractor=lambda r: r[0],
            partition_extractor=lambda r: r[1],
            value_extractor=lambda r: r[2])
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, backend)
        result = engine.aggregate(rows, params, extractors)
        accountant.compute_budgets()
        registered = accountant.mechanism_count
        out = dict(result)
        assert accountant.mechanism_count == registered
        return out, registered

    def test_blocked_engine_faulted_run_identical_ledger_stable(self):
        rng = np.random.default_rng(1)
        rows = list(
            zip(rng.integers(0, 300, 8000).tolist(),
                rng.integers(0, 3000, 8000).tolist(),
                rng.uniform(0, 5, 8000).tolist()))
        make = lambda: pdp.TPUBackend(noise_seed=13,
                                      large_partition_threshold=1 << 10,
                                      block_partitions=1 << 10,
                                      retry=FAST)
        base, n_base = self._aggregate(make(), rows)
        sched = faults.FaultSchedule([
            faults.Fault("dispatch", block=0, times=2),
            faults.Fault("consume", block=1),
        ])
        with faults.inject(sched):
            faulted, n_faulted = self._aggregate(make(), rows)
        assert sched.pending() == 0
        assert n_base == n_faulted  # zero duplicate registrations
        assert base.keys() == faulted.keys()
        for pk in base:
            assert base[pk] == faulted[pk], pk

    def test_engine_journal_resume(self, tmp_path):
        rng = np.random.default_rng(2)
        rows = list(
            zip(rng.integers(0, 300, 8000).tolist(),
                rng.integers(0, 3000, 8000).tolist(),
                rng.uniform(0, 5, 8000).tolist()))
        make = lambda journal=None: pdp.TPUBackend(
            noise_seed=13,
            large_partition_threshold=1 << 10,
            block_partitions=1 << 10,
            retry=FAST,
            journal=journal)
        base, _ = self._aggregate(make(), rows)
        with faults.inject(
                faults.FaultSchedule([faults.Fault("fatal", block=2)])):
            with pytest.raises(faults.InjectedFatalError):
                self._aggregate(make(runtime.BlockJournal(str(tmp_path))),
                                rows)
        before = telemetry.snapshot()
        resumed, _ = self._aggregate(
            make(runtime.BlockJournal(str(tmp_path))), rows)
        assert telemetry.delta(before).get("journal_replays", 0) > 0
        assert base == resumed

    def test_guard_rejects_execution_time_registration(self):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=1.0,
                                               total_delta=1e-6)
        engine = pdp.DPEngine(accountant, pdp.LocalBackend())

        class RogueCombiner(pdp.CustomCombiner):

            def create_accumulator(self, values):
                return len(values)

            def merge_accumulators(self, a, b):
                return a + b

            def compute_metrics(self, acc):
                # Budget request during EXECUTION — the double-spend bug
                # the guard exists to catch.
                accountant._finalized = False
                accountant.request_budget(MechanismType.LAPLACE)
                return {"rogue": acc}

            def explain_computation(self):
                return lambda: "rogue"

            def request_budget(self, budget_accountant):
                self._budget = budget_accountant.request_budget(
                    MechanismType.LAPLACE)

            def metrics_names(self):
                return ["rogue"]

        params = pdp.AggregateParams(metrics=None,
                                     custom_combiners=[RogueCombiner()],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=2)
        extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                        partition_extractor=lambda r: r[1],
                                        value_extractor=lambda r: 1.0)
        result = engine.aggregate([(1, "a"), (2, "a")], params, extractors,
                                  public_partitions=["a"])
        accountant.compute_budgets()
        with pytest.raises(AssertionError, match="double-spend"):
            list(result)
