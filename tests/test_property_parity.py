"""Property-based (hypothesis) parity tests for the fused TPU path.

Random small datasets at huge epsilon must satisfy, on the fused columnar
path: exact agreement with a brute-force numpy aggregation (and hence with
LocalBackend) when the data respects the contribution bounds, and the
bounding caps when it does not. Complements the example-based engine tests
with generated edge cases (empty partitions, negative values, single-user
partitions, value == clipping bound, etc.).
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis",
    reason="property tests need hypothesis (absent in some images)")
from hypothesis import given, settings, strategies as st  # noqa: E402

import pipelinedp_tpu as pdp  # noqa: E402

HUGE_EPS = 1e7
VOCAB = [f"pk{i}" for i in range(6)]

# Keep compile diversity bounded: the kernel pads rows to the next power of
# two and max_partitions pins the partition axis, so every example reuses a
# handful of compiled shapes.
MAX_PARTITIONS = 8


# Backend variants the properties run against: the dense fused kernel
# and the blocked partition-axis route (threshold below the partition
# count). Same assertions, so the two paths cannot silently diverge in
# what is verified.
BACKEND_VARIANTS = [{}, {"large_partition_threshold": 4}]
BACKEND_IDS = ["dense", "blocked"]


def run_tpu(rows, params, public, backend_kwargs=None):
    backend = pdp.TPUBackend(noise_seed=7, max_partitions=MAX_PARTITIONS,
                             **(backend_kwargs or {}))
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                           total_delta=1e-5)
    engine = pdp.DPEngine(accountant, backend)
    extractors = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                    partition_extractor=lambda r: r[1],
                                    value_extractor=lambda r: r[2])
    result = engine.aggregate(rows, params, extractors, public)
    accountant.compute_budgets()
    return dict(result)


# A bounded dataset: each user touches <= l0 partitions, <= linf values
# each, so contribution bounding drops nothing and results are exact.
@st.composite
def bounded_dataset(draw):
    l0 = draw(st.integers(1, 3))
    linf = draw(st.integers(1, 3))
    n_users = draw(st.integers(1, 5))
    rows = []
    for u in range(n_users):
        pks = draw(
            st.lists(st.sampled_from(VOCAB),
                     min_size=1,
                     max_size=l0,
                     unique=True))
        for pk in pks:
            n_vals = draw(st.integers(1, linf))
            for _ in range(n_vals):
                v = draw(
                    st.floats(-5.0, 5.0, allow_nan=False,
                              allow_infinity=False))
                rows.append((f"u{u}", pk, round(v, 2)))
    return l0, linf, rows


@st.composite
def unbounded_dataset(draw):
    l0 = draw(st.integers(1, 2))
    linf = draw(st.integers(1, 2))
    n_users = draw(st.integers(1, 4))
    rows = draw(
        st.lists(st.tuples(st.integers(0, n_users - 1),
                           st.sampled_from(VOCAB),
                           st.floats(-9.0, 9.0, allow_nan=False,
                                     allow_infinity=False)),
                 min_size=1,
                 max_size=40))
    rows = [(f"u{u}", pk, round(v, 2)) for u, pk, v in rows]
    return l0, linf, rows


@pytest.mark.parametrize("backend_kwargs", BACKEND_VARIANTS,
                         ids=BACKEND_IDS)
@settings(max_examples=20, deadline=None)
@given(bounded_dataset())
def test_bounded_data_matches_brute_force(backend_kwargs, data):
    l0, linf, rows = data
    min_v, max_v = -5.0, 5.0
    params = pdp.AggregateParams(
        metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                 pdp.Metrics.PRIVACY_ID_COUNT],
        noise_kind=pdp.NoiseKind.LAPLACE,
        max_partitions_contributed=l0,
        max_contributions_per_partition=linf,
        min_value=min_v,
        max_value=max_v)
    result = run_tpu(rows, params, public=VOCAB,
                     backend_kwargs=backend_kwargs)

    assert set(result) == set(VOCAB)
    for pk in VOCAB:
        in_pk = [(u, v) for u, p, v in rows if p == pk]
        count = len(in_pk)
        total = sum(np.clip(v, min_v, max_v) for _, v in in_pk)
        users = len({u for u, _ in in_pk})
        assert result[pk].count == pytest.approx(count, abs=0.01)
        assert result[pk].sum == pytest.approx(total, abs=0.02)
        assert result[pk].privacy_id_count == pytest.approx(users, abs=0.01)


@pytest.mark.parametrize("backend_kwargs", BACKEND_VARIANTS,
                         ids=BACKEND_IDS)
@settings(max_examples=20, deadline=None)
@given(unbounded_dataset())
def test_unbounded_data_respects_caps(backend_kwargs, data):
    l0, linf, rows = data
    params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM],
                                 noise_kind=pdp.NoiseKind.LAPLACE,
                                 max_partitions_contributed=l0,
                                 max_contributions_per_partition=linf,
                                 min_value=0.0,
                                 max_value=9.0)
    result = run_tpu(rows, params, public=VOCAB,
                     backend_kwargs=backend_kwargs)

    n_users = len({u for u, _, _ in rows})
    total_count = sum(result[pk].count for pk in VOCAB)
    # Each user contributes at most l0 * linf rows globally...
    assert total_count <= n_users * l0 * linf + 0.01
    for pk in VOCAB:
        users_pk = {u for u, p, _ in rows if p == pk}
        raw_count = sum(1 for _, p, _ in rows if p == pk)
        # ...at most linf rows within a partition, never more than raw...
        assert result[pk].count <= min(
            len(users_pk) * linf, raw_count) + 0.01
        # ...and sums are bounded by clip_max per surviving row.
        assert result[pk].sum <= result[pk].count * 9.0 + 0.02
        assert result[pk].sum >= -0.02
