"""Unit suite for the project call graph + dataflow engines.

Covers resolution (import aliasing, from-imports, method dispatch
through inheritance, nested defs, constructors), recursion convergence,
and the stated unknown-callee policies (taint passes through; lock
facts are only claimed for resolved callees).
"""

import pytest

from pipelinedp_tpu.staticcheck import dataflow
from pipelinedp_tpu.staticcheck import model
from pipelinedp_tpu.staticcheck.model import CallGraph

pytestmark = pytest.mark.staticcheck


def _graph(sources):
    return CallGraph([model.parse_source(rel, src)
                      for rel, src in sources.items()])


def _call_in(graph, rel, lineno=None):
    """First ast.Call in the module (optionally at a given line)."""
    import ast
    mod = graph.modules[rel]
    calls = [n for n in ast.walk(mod.tree) if isinstance(n, ast.Call)]
    if lineno is not None:
        calls = [c for c in calls if c.lineno == lineno]
    return mod, calls[0]


def _scope(graph, rel, qualname):
    return graph.functions[(rel, qualname)]


class TestResolution:

    def test_module_dotted(self):
        assert model.module_dotted("pipelinedp_tpu/runtime/telemetry.py") \
            == "pipelinedp_tpu.runtime.telemetry"
        assert model.module_dotted("pipelinedp_tpu/__init__.py") == \
            "pipelinedp_tpu"

    def test_import_alias_resolves(self):
        g = _graph({
            "pipelinedp_tpu/runtime/telemetry.py": (
                "def record(name):\n    pass\n"),
            "pipelinedp_tpu/user.py": (
                "import pipelinedp_tpu.runtime.telemetry as tele\n"
                "def f():\n"
                "    tele.record('x')\n"),
        })
        mod, call = _call_in(g, "pipelinedp_tpu/user.py")
        hit = g.resolve_call(mod, call, _scope(g, "pipelinedp_tpu/user.py",
                                               "f"))
        assert hit is not None
        assert hit.key == ("pipelinedp_tpu/runtime/telemetry.py",
                           "record")

    def test_from_import_resolves(self):
        g = _graph({
            "pipelinedp_tpu/runtime/telemetry.py": (
                "def record(name):\n    pass\n"),
            "pipelinedp_tpu/user.py": (
                "from pipelinedp_tpu.runtime.telemetry import record\n"
                "def f():\n"
                "    record('x')\n"),
        })
        mod, call = _call_in(g, "pipelinedp_tpu/user.py")
        hit = g.resolve_call(mod, call,
                             _scope(g, "pipelinedp_tpu/user.py", "f"))
        assert hit.key == ("pipelinedp_tpu/runtime/telemetry.py",
                           "record")

    def test_self_method_dispatch_through_base_class(self):
        g = _graph({
            "pipelinedp_tpu/base.py": (
                "class Base:\n"
                "    def helper(self):\n"
                "        pass\n"),
            "pipelinedp_tpu/impl.py": (
                "from pipelinedp_tpu.base import Base\n"
                "class Impl(Base):\n"
                "    def run(self):\n"
                "        self.helper()\n"),
        })
        mod, call = _call_in(g, "pipelinedp_tpu/impl.py")
        hit = g.resolve_call(mod, call,
                             _scope(g, "pipelinedp_tpu/impl.py",
                                    "Impl.run"))
        assert hit.key == ("pipelinedp_tpu/base.py", "Base.helper")

    def test_override_wins_over_base(self):
        g = _graph({
            "pipelinedp_tpu/m.py": (
                "class Base:\n"
                "    def helper(self):\n"
                "        pass\n"
                "class Impl(Base):\n"
                "    def helper(self):\n"
                "        pass\n"
                "    def run(self):\n"
                "        self.helper()\n"),
        })
        mod, call = _call_in(g, "pipelinedp_tpu/m.py")
        hit = g.resolve_call(mod, call,
                             _scope(g, "pipelinedp_tpu/m.py", "Impl.run"))
        assert hit.qualname == "Impl.helper"

    def test_nested_def_resolves_through_lexical_chain(self):
        g = _graph({
            "pipelinedp_tpu/m.py": (
                "def outer():\n"
                "    def inner():\n"
                "        pass\n"
                "    inner()\n"),
        })
        mod, call = _call_in(g, "pipelinedp_tpu/m.py", lineno=4)
        hit = g.resolve_call(mod, call,
                             _scope(g, "pipelinedp_tpu/m.py", "outer"))
        assert hit.qualname == "outer.inner"

    def test_constructor_resolves_to_init(self):
        g = _graph({
            "pipelinedp_tpu/m.py": (
                "class C:\n"
                "    def __init__(self, x):\n"
                "        self.x = x\n"
                "def f():\n"
                "    return C(1)\n"),
        })
        mod, call = _call_in(g, "pipelinedp_tpu/m.py", lineno=5)
        hit = g.resolve_call(mod, call,
                             _scope(g, "pipelinedp_tpu/m.py", "f"))
        assert hit.qualname == "C.__init__"

    def test_unknown_callee_returns_none(self):
        g = _graph({
            "pipelinedp_tpu/m.py": (
                "import numpy as np\n"
                "def f(x):\n"
                "    return np.asarray(x)\n"),
        })
        mod, call = _call_in(g, "pipelinedp_tpu/m.py")
        assert g.resolve_call(
            mod, call, _scope(g, "pipelinedp_tpu/m.py", "f")) is None


def _taint_cfg(sources=None, release=None):
    return dataflow.TaintConfig(
        sources=sources or {},
        sanitizers=set(),
        sanitizer_attrs=frozenset({"add_noise"}),
        sanitizer_dotted=frozenset(),
        declass_calls=frozenset({"len"}),
        declass_attrs=frozenset({"shape"}),
        release_funcs=release or set(),
        sink_args=lambda graph, mod, scope, call, callee: (
            [("sink", [kw.value for kw in call.keywords])]
            if getattr(call.func, "attr", "") == "sink_fn" else []),
    )


class TestTaintEngine:

    SRC = {"pipelinedp_tpu/src.py": "def raw():\n    return 1\n"}
    KEY = ("pipelinedp_tpu/src.py", "raw")

    def test_recursion_converges(self):
        g = _graph({
            **self.SRC,
            "pipelinedp_tpu/m.py": (
                "import out\n"
                "from pipelinedp_tpu.src import raw\n"
                "def rec(x, n):\n"
                "    if n == 0:\n"
                "        return x\n"
                "    return rec(x, n - 1)\n"
                "def f(n):\n"
                "    v = rec(raw(), n)\n"
                "    out.sink_fn(value=v)\n"),
        })
        findings = dataflow.run_taint(g, _taint_cfg({self.KEY: "raw"}))
        assert len(findings) == 1
        assert findings[0].origin.label == "raw"
        # The recursive hop shows in the path.
        assert "rec" in findings[0].origin.render_path()

    def test_mutual_recursion_converges(self):
        g = _graph({
            **self.SRC,
            "pipelinedp_tpu/m.py": (
                "from pipelinedp_tpu.src import raw\n"
                "def a(x):\n"
                "    return b(x)\n"
                "def b(x):\n"
                "    return a(x)\n"
                "def f():\n"
                "    return a(raw())\n"),
        })
        # Terminates (fixpoint round cap) without findings: no sink.
        assert dataflow.run_taint(g, _taint_cfg({self.KEY: "raw"})) == []

    def test_unknown_callee_is_pass_through(self):
        g = _graph({
            **self.SRC,
            "pipelinedp_tpu/m.py": (
                "import out, mystery\n"
                "from pipelinedp_tpu.src import raw\n"
                "def f():\n"
                "    v = mystery.blend(raw())\n"
                "    out.sink_fn(value=v)\n"),
        })
        findings = dataflow.run_taint(g, _taint_cfg({self.KEY: "raw"}))
        assert len(findings) == 1

    def test_sanitizer_attr_clears(self):
        g = _graph({
            **self.SRC,
            "pipelinedp_tpu/m.py": (
                "import out\n"
                "from pipelinedp_tpu.src import raw\n"
                "def f(mech):\n"
                "    v = mech.add_noise(raw())\n"
                "    out.sink_fn(value=v)\n"),
        })
        assert dataflow.run_taint(g, _taint_cfg({self.KEY: "raw"})) == []

    def test_declassifier_clears(self):
        g = _graph({
            **self.SRC,
            "pipelinedp_tpu/m.py": (
                "import out\n"
                "from pipelinedp_tpu.src import raw\n"
                "def f():\n"
                "    out.sink_fn(value=len(raw()), shape=raw().shape)\n"),
        })
        assert dataflow.run_taint(g, _taint_cfg({self.KEY: "raw"})) == []

    def test_reassignment_clears_taint(self):
        g = _graph({
            **self.SRC,
            "pipelinedp_tpu/m.py": (
                "import out\n"
                "from pipelinedp_tpu.src import raw\n"
                "def f():\n"
                "    v = raw()\n"
                "    v = 0\n"
                "    out.sink_fn(value=v)\n"),
        })
        assert dataflow.run_taint(g, _taint_cfg({self.KEY: "raw"})) == []


class TestLockEngine:

    def _cfg(self):
        return dataflow.LockConfig(
            declared={},
            blocking_attrs=frozenset({"join"}),
            blocking_dotted=frozenset({"time.sleep"}),
            blocking_funcs=set())

    def test_transitive_acquire_edge(self):
        g = _graph({
            "pipelinedp_tpu/m.py": (
                "import threading\n"
                "_lock_a = threading.Lock()\n"
                "_lock_b = threading.Lock()\n"
                "def inner():\n"
                "    with _lock_b:\n"
                "        pass\n"
                "def f():\n"
                "    with _lock_a:\n"
                "        inner()\n"),
        })
        report = dataflow.run_locks(g, self._cfg())
        a = ("pipelinedp_tpu/m.py", "", "_lock_a")
        b = ("pipelinedp_tpu/m.py", "", "_lock_b")
        assert (a, b) in report.edges
        assert dataflow.find_lock_cycles(report.edges) == []

    def test_unknown_callee_claims_no_lock_facts(self):
        g = _graph({
            "pipelinedp_tpu/m.py": (
                "import threading, mystery\n"
                "_lock = threading.Lock()\n"
                "def f():\n"
                "    with _lock:\n"
                "        mystery.do_something()\n"),
        })
        report = dataflow.run_locks(g, self._cfg())
        assert report.edges == {} and report.blocking == []

    def test_string_join_not_blocking(self):
        g = _graph({
            "pipelinedp_tpu/m.py": (
                "import threading\n"
                "_lock = threading.Lock()\n"
                "def f(parts):\n"
                "    with _lock:\n"
                "        return ','.join(parts)\n"),
        })
        assert dataflow.run_locks(g, self._cfg()).blocking == []

    def test_find_lock_cycles_three_way(self):
        a, b, c = ("m", "", "_lock_a"), ("m", "", "_lock_b"), \
            ("m", "", "_lock_c")
        edges = {(a, b): ("m", 1, "d"), (b, c): ("m", 2, "d"),
                 (c, a): ("m", 3, "d")}
        (cycle,) = dataflow.find_lock_cycles(edges)
        assert set(cycle) == {a, b, c}

    def test_self_loop_cycle(self):
        a = ("m", "", "_lock_a")
        (cycle,) = dataflow.find_lock_cycles({(a, a): ("m", 1, "d")})
        assert cycle == [a]
