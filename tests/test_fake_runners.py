"""Runs the Beam/Spark adapter stacks over in-memory fake runners.

apache_beam and pyspark cannot be installed here, so the adapters would
otherwise never execute (round-2 verdict gap). tests/fake_runners/ ships
minimal lazy in-memory implementations of both APIs; the driver scripts
execute the REAL BeamBackend / SparkRDDBackend / private_beam /
private_spark code end-to-end — op-semantics matrix vs LocalBackend, label
uniqueness, DPEngine aggregation parity, private transforms, and the
distributed utility-analysis path. Each runs in a subprocess so the fake
modules never leak into this interpreter's import state.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FAKES = os.path.join(REPO, "tests", "fake_runners")


def _run(script: str, marker: str):
    env = dict(os.environ)
    env["PYTHONPATH"] = FAKES + os.pathsep + REPO
    env["JAX_PLATFORMS"] = "cpu"
    result = subprocess.run([sys.executable,
                             os.path.join(FAKES, script)],
                            capture_output=True,
                            text=True,
                            timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert marker in result.stdout, result.stdout
    return result.stdout


def test_beam_adapter_executes_on_fake_runner():
    out = _run("run_beam_checks.py", "BEAM_CHECKS_PASSED")
    assert "ok: DPEngine.aggregate on BeamBackend" in out
    assert "ok: private_beam Count/Sum" in out
    assert "ok: private_beam FlatMap + Mean" in out
    assert "ok: private_beam Variance" in out
    assert "ok: private_beam PrivacyIdCount" in out
    assert "ok: duplicate label raises" in out
    assert "ok: utility analysis on BeamBackend" in out
    assert "ok: unserializable closure rejected at the worker boundary" in out
    assert "ok: workers mutate a shipped COPY, not the driver object" in out


def test_spark_adapter_executes_on_fake_runner():
    out = _run("run_spark_checks.py", "SPARK_CHECKS_PASSED")
    assert "ok: DPEngine.aggregate on SparkRDDBackend" in out
    assert "ok: PrivateRDD count/sum" in out
    assert "ok: PrivateRDD mean" in out
    assert "ok: PrivateRDD variance" in out
    assert "ok: utility analysis on SparkRDDBackend" in out
    assert ("ok: unserializable closure rejected at the executor boundary"
            in out)
    assert "ok: executors mutate a shipped COPY, not the driver object" in out
