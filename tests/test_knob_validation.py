"""Tooling guard: every public runtime knob is validated at the API
boundary through input_validators.

The runtime knobs grown across PRs 2-6 (retry=, journal=, timeout_s=,
watchdog=, elastic=, min_devices=, job_id=, trace=) are all validated in
exactly two places — TPUBackend.__init__ and the shared driver entry
(runtime/entry.py). Since PR 7 the discipline is enforced by
staticcheck's ``knob-validation`` rule (AST over the wrapper signature,
the driver defs and TPUBackend.__init__ — the source-scraping helpers
this file used to carry are gone); these tests pin the rule's verdict on
the real tree and prove BOTH drift directions still fail: a new knob
with no validator, a validator that is never invoked, a mapped validator
that does not exist, and a stale mapping whose knob went away.
"""

import pytest

from pipelinedp_tpu import pipeline_backend, staticcheck

pytestmark = pytest.mark.staticcheck


def _findings(sources):
    mods = [staticcheck.parse_source(rel, src)
            for rel, src in sources.items()]
    return staticcheck.analyze(mods,
                               only_rules=["knob-validation"]).active


def test_every_knob_on_the_real_tree_is_validated():
    """The shipped wrapper, all six drivers and TPUBackend: zero
    knob-validation findings (the analyzer's tree gate re-checks this,
    but the knob discipline deserves its own named failure)."""
    tree = staticcheck.load_tree(staticcheck.default_paths())
    assert staticcheck.analyze(
        tree, only_rules=["knob-validation"]).active == []


class TestDriftDirections:
    """Synthetic entry/backend modules prove each drift direction still
    produces a finding — the coverage the old grep tests had."""

    def test_new_wrapper_knob_without_mapping_is_flagged(self):
        found = _findings({
            "pipelinedp_tpu/runtime/entry.py": (
                "from pipelinedp_tpu import input_validators\n"
                "def runtime_entry(kind):\n"
                "    def deco(fn):\n"
                "        def wrapper(*args, timeout_s=None,\n"
                "                    new_knob=False, **kwargs):\n"
                "            input_validators.validate_timeout_s(\n"
                "                timeout_s, kind)\n"
                "            return fn(*args, **kwargs)\n"
                "        return wrapper\n"
                "    return deco\n"),
        })
        assert any("new_knob" in f.message and
                   "no validator mapping" in f.message for f in found)

    def test_mapped_validator_never_invoked_is_flagged(self):
        found = _findings({
            "pipelinedp_tpu/runtime/entry.py": (
                "def runtime_entry(kind):\n"
                "    def deco(fn):\n"
                "        def wrapper(*args, journal=None, **kwargs):\n"
                "            return fn(*args, **kwargs)\n"
                "        return wrapper\n"
                "    return deco\n"),
        })
        assert any("never invokes validate_journal" in f.message
                   for f in found)

    def test_mapped_validator_missing_from_input_validators(self):
        found = _findings({
            "pipelinedp_tpu/runtime/entry.py": (
                "from pipelinedp_tpu import input_validators\n"
                "def runtime_entry(kind):\n"
                "    def deco(fn):\n"
                "        def wrapper(*args, journal=None, **kwargs):\n"
                "            input_validators.validate_journal(\n"
                "                journal, kind)\n"
                "            return fn(*args, **kwargs)\n"
                "        return wrapper\n"
                "    return deco\n"),
            # A validators module WITHOUT validate_journal.
            "pipelinedp_tpu/input_validators.py": (
                "def validate_timeout_s(timeout_s, obj_name):\n"
                "    pass\n"),
        })
        assert any("does not exist" in f.message for f in found)

    def test_backend_knob_without_validation_is_flagged(self):
        found = _findings({
            "pipelinedp_tpu/pipeline_backend.py": (
                "class TPUBackend:\n"
                "    def __init__(self, mesh=None, new_backend_knob=0):\n"
                "        self.mesh = mesh\n"),
        })
        assert any("new_backend_knob" in f.message for f in found)

    def test_stale_mapping_is_flagged(self):
        """A KNOB_VALIDATORS entry whose knob exists nowhere (wrapper,
        drivers, backend) is dead configuration."""
        found = _findings({
            "pipelinedp_tpu/runtime/entry.py": (
                "from pipelinedp_tpu import input_validators\n"
                "def runtime_entry(kind):\n"
                "    def deco(fn):\n"
                "        def wrapper(*args, timeout_s=None, **kwargs):\n"
                "            input_validators.validate_timeout_s(\n"
                "                timeout_s, kind)\n"
                "            return fn(*args, **kwargs)\n"
                "        return wrapper\n"
                "    return deco\n"),
            "pipelinedp_tpu/pipeline_backend.py": (
                "class TPUBackend:\n"
                "    def __init__(self, mesh=None):\n"
                "        self.mesh = mesh\n"),
        })
        assert any("stale mapping" in f.message and "journal" in f.message
                   for f in found)


class TestKnobRejection:
    """The validators actually fire at both boundaries (runtime checks —
    the analyzer proves invocation, these prove behavior)."""

    def test_backend_rejects_bad_elastic_and_min_devices(self):
        with pytest.raises(ValueError, match="elastic"):
            pipeline_backend.TPUBackend(elastic="yes")
        with pytest.raises(ValueError, match="min_devices"):
            pipeline_backend.TPUBackend(min_devices=0)
        with pytest.raises(ValueError, match="journal"):
            pipeline_backend.TPUBackend(journal="/tmp/not-a-journal")
        with pytest.raises(ValueError, match="watchdog"):
            pipeline_backend.TPUBackend(watchdog=5.0)

    def test_backend_rejects_bad_elastic_grow(self):
        """The fleet-operations knobs ride the same discipline: a
        non-bool scale-UP switch and a bad drain window both die at
        the boundary."""
        with pytest.raises(ValueError, match="elastic_grow"):
            pipeline_backend.TPUBackend(elastic_grow="yes")
        with pytest.raises(ValueError, match="elastic_grow"):
            pipeline_backend.TPUBackend(elastic_grow=1)

    def test_service_rejects_bad_drain_timeout(self):
        from pipelinedp_tpu.service import DPAggregationService
        backend = pipeline_backend.TPUBackend()
        with pytest.raises(ValueError, match="drain_timeout_s"):
            DPAggregationService(backend, drain_timeout_s=-1.0)
        with pytest.raises(ValueError, match="drain_timeout_s"):
            DPAggregationService(backend, drain_timeout_s=float("nan"))
        with pytest.raises(ValueError, match="drain_timeout_s"):
            DPAggregationService(backend, drain_timeout_s=True)

    def test_service_rejects_bad_knobs(self):
        """The DPAggregationService boundary is under the same
        discipline: every service knob maps to an invoked validator
        (the rule proves invocation; this proves behavior)."""
        from pipelinedp_tpu.service import DPAggregationService
        backend = pipeline_backend.TPUBackend()
        with pytest.raises(ValueError, match="max_concurrent_jobs"):
            DPAggregationService(backend, max_concurrent_jobs=-1)
        with pytest.raises(ValueError, match="tenant_budget_epsilon"):
            DPAggregationService(backend, tenant_budget_epsilon=0)
        with pytest.raises(ValueError, match="queue_timeout_s"):
            DPAggregationService(backend, queue_timeout_s=float("inf"))
        with pytest.raises(ValueError, match="shed_watermark_fraction"):
            DPAggregationService(backend, shed_watermark_fraction=0.0)
        with pytest.raises(ValueError, match="batching"):
            DPAggregationService(backend, batching="on")
        with pytest.raises(ValueError, match="batch_window_ms"):
            DPAggregationService(backend, batch_window_ms=-5.0)
        with pytest.raises(ValueError, match="max_batch_jobs"):
            DPAggregationService(backend, max_batch_jobs=True)
        with pytest.raises(ValueError, match="tenant_accounting"):
            DPAggregationService(backend, tenant_accounting="exact")
        with pytest.raises(ValueError, match="pld_discretization"):
            DPAggregationService(backend, pld_discretization=0.0)
        with pytest.raises(ValueError, match="pld_discretization"):
            DPAggregationService(backend, pld_discretization=1.5)

    def test_service_knob_without_validation_is_flagged(self):
        """A new defaulted DPAggregationService.__init__ parameter with
        no validator mapping drifts loudly."""
        found = _findings({
            "pipelinedp_tpu/service/service.py": (
                "class DPAggregationService:\n"
                "    def __init__(self, backend, ledger_dir=None, *,\n"
                "                 brand_new_service_knob=1):\n"
                "        self._backend = backend\n"),
        })
        assert any("brand_new_service_knob" in f.message and
                   "no validator mapping" in f.message for f in found)

    def test_submit_rejects_bad_deadline(self):
        """deadline_s is vetted at its own boundary
        (DPAggregationService.submit) before the job is ever queued."""
        from pipelinedp_tpu.service import DPAggregationService, JobSpec
        import pipelinedp_tpu as pdp
        backend = pipeline_backend.TPUBackend()
        service = DPAggregationService(backend)
        spec = JobSpec(params=pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT], max_partitions_contributed=1,
            max_contributions_per_partition=1), epsilon=1.0, delta=1e-6)
        try:
            for bad in (0.0, -1.0, float("nan"), float("inf"), True,
                        "soon"):
                with pytest.raises(ValueError, match="deadline_s"):
                    service.submit("t", spec, [("u", "A", 1.0)],
                                   deadline_s=bad)
        finally:
            service.drain()

    def test_submit_knob_without_validation_is_flagged(self):
        """submit() is a second service boundary: a new keyword-only
        submit knob with no validator mapping drifts loudly."""
        found = _findings({
            "pipelinedp_tpu/service/service.py": (
                "class DPAggregationService:\n"
                "    def __init__(self, backend, ledger_dir=None):\n"
                "        self._backend = backend\n"
                "    def submit(self, tenant_id, spec, source, *,\n"
                "               brand_new_submit_knob=None):\n"
                "        return None\n"),
        })
        assert any("brand_new_submit_knob" in f.message and
                   "no validator mapping" in f.message for f in found)

    def test_driver_rejects_bad_elastic_and_min_devices(self):
        import numpy as np
        from pipelinedp_tpu.parallel import large_p, make_mesh, sharded
        args = (make_mesh(n_devices=1), np.zeros(4, np.int32),
                np.zeros(4, np.int32), np.ones(4, bool), None, 1, 8, None)
        with pytest.raises(ValueError, match="elastic"):
            sharded.sharded_select_partitions(*args, elastic=1)
        with pytest.raises(ValueError, match="min_devices"):
            sharded.sharded_select_partitions(*args, min_devices=-2)
        with pytest.raises(ValueError, match="elastic_grow"):
            sharded.sharded_select_partitions(*args, elastic_grow="on")
        with pytest.raises(ValueError, match="journal"):
            large_p.aggregate_blocked(np.zeros(4, np.int32),
                                      journal="/tmp/nope")
