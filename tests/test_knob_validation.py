"""Tooling guard: every public runtime knob is validated at the API
boundary through input_validators.

The runtime knobs grown across PRs 2-4 (retry=, journal=, timeout_s=,
watchdog=, elastic=, min_devices=, job_id=) are all validated in exactly
two places — TPUBackend.__init__ and the shared driver entry
(runtime/entry.py) — so a bad value fails with an actionable message
instead of misbehaving deep inside the journal, the watchdog monitor or
the elastic mesh loop. This test enforces the discipline structurally
(signature scan + source grep), so a NEW knob added to a driver or the
backend cannot ship without a validator: it either appears in the knob
-> validator map (and the validator must exist and be invoked at both
boundaries) or in the explicit exemption list of data-plane parameters.
"""

import inspect
import re

import pytest

from pipelinedp_tpu import input_validators, pipeline_backend
from pipelinedp_tpu.parallel import large_p, sharded
from pipelinedp_tpu.runtime import entry

# Runtime knob -> the input_validators function that must vet it.
KNOB_VALIDATORS = {
    "retry": "validate_retry_policy",
    "journal": "validate_journal",
    "timeout_s": "validate_timeout_s",
    "watchdog": "validate_watchdog",
    "elastic": "validate_elastic",
    "min_devices": "validate_min_devices",
    "job_id": "validate_job_id",
    "trace": "validate_trace",
}

# Data-plane parameters: configuration, not failure semantics — adding
# one here is a deliberate reviewed decision, not a default.
EXEMPT = {
    # driver data/geometry knobs
    "block_partitions", "row_chunk", "secure_tables", "reshard",
    "phase_times",
    # TPUBackend configuration
    "mesh", "max_partitions", "noise_seed", "secure_noise",
    "large_partition_threshold",
}

DRIVERS = [
    large_p.aggregate_blocked,
    large_p.aggregate_blocked_sharded,
    large_p.select_partitions_blocked,
    large_p.select_partitions_blocked_sharded,
    sharded.sharded_aggregate_arrays,
    sharded.sharded_select_partitions,
]


def _entry_wrapper_params():
    """Parameter names of the shared runtime-entry wrapper (the knobs it
    adds on top of each driver's own signature)."""
    src = inspect.getsource(entry)
    match = re.search(r"def wrapper\(\*args,(.*?)\*\*kwargs\):", src,
                      re.DOTALL)
    assert match, "runtime_entry wrapper signature not found"
    # One parameter per line ("name: ann = default" / "name=default"):
    # anchor on the line start so annotation types don't match.
    return set(re.findall(r"^\s*(\w+)\s*[:=]", match.group(1),
                          re.MULTILINE))


def _driver_knobs(fn):
    """Keyword(-only) knobs of one driver: its wrapped signature plus the
    shared wrapper's parameters."""
    sig = inspect.signature(fn)  # follows __wrapped__
    own = {
        name
        for name, p in sig.parameters.items()
        if p.kind is inspect.Parameter.KEYWORD_ONLY or (
            p.kind is inspect.Parameter.POSITIONAL_OR_KEYWORD and
            p.default is not inspect.Parameter.empty)
    }
    return own | _entry_wrapper_params()


@pytest.mark.parametrize("fn", DRIVERS, ids=lambda f: f.__name__)
def test_every_driver_knob_is_validated_or_exempt(fn):
    entry_src = inspect.getsource(entry)
    for knob in sorted(_driver_knobs(fn) - EXEMPT):
        assert knob in KNOB_VALIDATORS, (
            f"{fn.__name__} grew a runtime knob {knob!r} with no "
            f"input_validators.validate_{knob} mapping — add the "
            f"validator and invoke it in runtime/entry.py (or, if it is "
            f"a data-plane parameter, add it to EXEMPT deliberately).")
        validator = KNOB_VALIDATORS[knob]
        assert callable(getattr(input_validators, validator, None)), (
            f"input_validators.{validator} missing for knob {knob!r}")
        assert re.search(rf"\b{validator}\(", entry_src), (
            f"runtime/entry.py never invokes {validator} for {knob!r} — "
            f"the knob skips validation at the driver boundary.")


def test_every_backend_knob_is_validated_or_exempt():
    init = pipeline_backend.TPUBackend.__init__
    init_src = inspect.getsource(init)
    params = set(inspect.signature(init).parameters) - {"self"}
    for knob in sorted(params - EXEMPT):
        assert knob in KNOB_VALIDATORS, (
            f"TPUBackend grew a runtime knob {knob!r} with no validator "
            f"mapping — add input_validators.validate_{knob} and invoke "
            f"it in TPUBackend.__init__ (or exempt it deliberately).")
        validator = KNOB_VALIDATORS[knob]
        assert re.search(rf"\b{validator}\(", init_src), (
            f"TPUBackend.__init__ never invokes {validator} for "
            f"{knob!r} — the knob skips validation at the API boundary.")


def test_wrapper_knobs_all_have_validators():
    """The shared wrapper's own parameters are runtime knobs by
    construction; each must map to a validator."""
    for knob in sorted(_entry_wrapper_params()):
        assert knob in KNOB_VALIDATORS, (
            f"runtime_entry wrapper parameter {knob!r} has no validator")


class TestKnobRejection:
    """The validators actually fire at both boundaries."""

    def test_backend_rejects_bad_elastic_and_min_devices(self):
        with pytest.raises(ValueError, match="elastic"):
            pipeline_backend.TPUBackend(elastic="yes")
        with pytest.raises(ValueError, match="min_devices"):
            pipeline_backend.TPUBackend(min_devices=0)
        with pytest.raises(ValueError, match="journal"):
            pipeline_backend.TPUBackend(journal="/tmp/not-a-journal")
        with pytest.raises(ValueError, match="watchdog"):
            pipeline_backend.TPUBackend(watchdog=5.0)

    def test_driver_rejects_bad_elastic_and_min_devices(self):
        import numpy as np
        from pipelinedp_tpu.parallel import make_mesh
        args = (make_mesh(n_devices=1), np.zeros(4, np.int32),
                np.zeros(4, np.int32), np.ones(4, bool), None, 1, 8, None)
        with pytest.raises(ValueError, match="elastic"):
            sharded.sharded_select_partitions(*args, elastic=1)
        with pytest.raises(ValueError, match="min_devices"):
            sharded.sharded_select_partitions(*args, min_devices=-2)
        with pytest.raises(ValueError, match="journal"):
            large_p.aggregate_blocked(np.zeros(4, np.int32),
                                      journal="/tmp/nope")
