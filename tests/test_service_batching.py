"""Megabatched serving coverage (pipelinedp_tpu/service/batching.py).

The contracts under test:

  * **Per-lane bit-identity** — every job that executes as one lane of
    a coalesced vmapped launch releases EXACTLY the outputs, spent
    epsilon and ledger charge its solo (batching=False) run releases,
    across count/sum, mean-with-private-selection, and standalone
    partition selection. The lane keeps the job's own noise key; the
    vmap only stacks the launch.
  * **Fallthrough** — mixed specs never coalesce (their launch
    fingerprints differ), a window that expires with one lane runs the
    unchanged solo path, and neither case touches the batch counters.
  * **Admission semantics survive** — the priority queue still orders
    execution with batching on; stop() wakes a pending batch window so
    in-flight lanes dispatch (bit-identically) instead of waiting out
    the window during shutdown; ledgers reconcile bit-exactly under
    concurrent batched tenants.
  * **Warm path** — a repeated batch of the same (spec, row bucket,
    lane bucket) adds 0 AOT executable-cache misses: the lane-stacked
    kernel is cached per shape-class like every other entry point.
  * **Observability** — batch launches record the declared
    service_batch_launches / service_jobs_batched counters and the
    service_batch_occupancy gauge, all scrapeable through the strict
    Prometheus round-trip, and show up as batch_dispatch trace spans
    carrying a lanes= attribute.
"""

import time

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.runtime import aot as rt_aot
from pipelinedp_tpu.runtime import observability as obs
from pipelinedp_tpu.runtime import telemetry
from pipelinedp_tpu.runtime import trace
from pipelinedp_tpu.service import DPAggregationService, JobSpec, JobStatus

pytestmark = [pytest.mark.service, pytest.mark.batching]


@pytest.fixture(autouse=True)
def _batching_epoch():
    telemetry.reset()
    yield
    trace.disable()
    rt_aot.enable(False)
    telemetry.reset()


def _rows(seed, n=200):
    r = np.random.default_rng(seed)
    return [(int(r.integers(0, 40)), f"p{int(r.integers(0, 10))}",
             float(r.uniform(0, 5))) for _ in range(n)]


def _agg_spec(seed, metrics=None, priority=0):
    params = pdp.AggregateParams(
        metrics=metrics or [pdp.Metrics.COUNT, pdp.Metrics.SUM],
        max_partitions_contributed=2,
        max_contributions_per_partition=3,
        min_value=0.0, max_value=5.0)
    return JobSpec(params=params, epsilon=1.0, delta=1e-6,
                   noise_seed=seed, priority=priority)


def _select_spec(seed):
    params = pdp.SelectPartitionsParams(max_partitions_contributed=2)
    return JobSpec(params=params, epsilon=0.5, delta=1e-6,
                   noise_seed=seed)


def _run_service(specs_and_rows, batching, **service_kwargs):
    """Runs the given (tenant, spec, rows) jobs concurrently and returns
    per-job results in submission order plus the service's ledger
    verdict and spent epsilons."""
    kwargs = dict(max_concurrent_jobs=len(specs_and_rows),
                  batching=batching, batch_window_ms=2000.0,
                  max_batch_jobs=max(2, len(specs_and_rows)))
    kwargs.update(service_kwargs)
    with DPAggregationService(pdp.TPUBackend(), **kwargs) as svc:
        handles = [svc.submit(tenant, spec, rows)
                   for tenant, spec, rows in specs_and_rows]
        results = [h.result(timeout=300) for h in handles]
        spent = [h.spent_epsilon for h in handles]
        reconciled = svc.ledgers_reconciled()
    return results, spent, reconciled


def _batch_counters():
    snap = telemetry.snapshot()
    return (snap.get("service_batch_launches", 0),
            snap.get("service_jobs_batched", 0))


def _assert_same_release(solo, batched):
    assert set(solo) == set(batched)
    for part in solo:
        assert np.array_equal(
            np.asarray(solo[part], np.float64),
            np.asarray(batched[part], np.float64)), part


class TestBitIdentity:

    @pytest.mark.parametrize("metrics", [
        [pdp.Metrics.COUNT, pdp.Metrics.SUM],
        [pdp.Metrics.MEAN],
    ], ids=["count_sum", "mean"])
    def test_batched_lanes_bit_identical_to_solo(self, metrics):
        jobs = [(f"tenant{i}", _agg_spec(50 + i, metrics=metrics),
                 _rows(7 + i)) for i in range(4)]
        solo, solo_spent, ok_solo = _run_service(jobs, batching=False)
        l0, j0 = _batch_counters()
        assert (l0, j0) == (0, 0), "solo run must not batch"
        batched, bat_spent, ok_bat = _run_service(jobs, batching=True)
        launches, lanes = _batch_counters()
        assert launches >= 1, "4 identical specs must coalesce"
        assert lanes == 4
        assert ok_solo and ok_bat
        assert solo_spent == bat_spent
        for s, b in zip(solo, batched):
            _assert_same_release(s, b)

    def test_select_partitions_batched_bit_identical(self):
        jobs = [(f"tenant{i}", _select_spec(70 + i), _rows(19 + i))
                for i in range(4)]
        solo, solo_spent, ok_solo = _run_service(jobs, batching=False)
        batched, bat_spent, ok_bat = _run_service(jobs, batching=True)
        launches, lanes = _batch_counters()
        assert launches >= 1 and lanes == 4
        assert ok_solo and ok_bat
        assert solo_spent == bat_spent
        for s, b in zip(solo, batched):
            assert sorted(s) == sorted(b)

    def test_ledger_charges_match_solo_bit_exactly(self):
        jobs = [(f"tenant{i}", _agg_spec(90 + i), _rows(31 + i))
                for i in range(4)]
        with DPAggregationService(pdp.TPUBackend(), max_concurrent_jobs=4,
                                  batching=True, batch_window_ms=2000.0,
                                  max_batch_jobs=4) as svc:
            handles = [svc.submit(t, s, r) for t, s, r in jobs]
            for h in handles:
                h.result(timeout=300)
            assert svc.ledgers_reconciled()
            for h in handles:
                ledger = svc.tenant_ledger(h.tenant_id)
                assert ledger.job_spent_epsilon(
                    h.job_id) == h.spent_epsilon
        launches, lanes = _batch_counters()
        assert launches >= 1 and lanes == 4


class TestFallthrough:

    def test_mixed_specs_never_coalesce(self):
        jobs = [("ta", _agg_spec(1, metrics=[pdp.Metrics.COUNT]),
                 _rows(1)),
                ("tb", _agg_spec(2, metrics=[pdp.Metrics.SUM]),
                 _rows(2))]
        solo, _, _ = _run_service(jobs, batching=False,
                                  batch_window_ms=200.0)
        batched, _, ok = _run_service(jobs, batching=True,
                                      batch_window_ms=200.0)
        assert _batch_counters() == (0, 0)
        assert ok
        for s, b in zip(solo, batched):
            _assert_same_release(s, b)

    def test_lone_job_window_expiry_runs_solo(self):
        job = [("t0", _agg_spec(5), _rows(5))]
        solo, _, _ = _run_service(job, batching=False,
                                  batch_window_ms=100.0)
        batched, _, ok = _run_service(job, batching=True,
                                      batch_window_ms=100.0)
        assert _batch_counters() == (0, 0)
        assert ok
        _assert_same_release(solo[0], batched[0])


class TestAdmissionInteraction:

    def test_priority_ordering_preserved_with_batching(self):
        with DPAggregationService(pdp.TPUBackend(),
                                  max_concurrent_jobs=1, batching=True,
                                  batch_window_ms=50.0,
                                  max_batch_jobs=4,
                                  queue_timeout_s=300.0) as svc:
            # The single worker runs the first job while the rest queue;
            # the LOW-priority-value job queued last must still run
            # before the higher-value one queued first.
            first = svc.submit("t0", _agg_spec(10, priority=0), _rows(3))
            late = svc.submit("t1", _agg_spec(11, priority=5), _rows(3))
            urgent = svc.submit("t2", _agg_spec(12, priority=1),
                                _rows(3))
            for h in (first, late, urgent):
                h.result(timeout=300)
            assert urgent._started_at < late._started_at

    def test_stop_wakes_pending_batch_window(self):
        jobs = [(f"tenant{i}", _agg_spec(110 + i), _rows(41 + i))
                for i in range(2)]
        solo, _, _ = _run_service(jobs, batching=False)
        telemetry.reset()
        with DPAggregationService(pdp.TPUBackend(), max_concurrent_jobs=2,
                                  batching=True,
                                  # A window far beyond the test budget:
                                  # only stop()'s close() can release it.
                                  batch_window_ms=120_000.0,
                                  max_batch_jobs=8) as svc:
            handles = [svc.submit(t, s, r) for t, s, r in jobs]
            # Both lanes reach the rendezvous and wait for a third that
            # never comes; stop() must dispatch them NOW.
            deadline = time.monotonic() + 60.0
            while (not all(h.status == JobStatus.RUNNING
                           for h in handles)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            time.sleep(1.0)  # running -> parked in the batch window
            svc.stop()
            results = [h.result(timeout=300) for h in handles]
            assert all(h.status == JobStatus.DONE for h in handles)
            assert svc.ledgers_reconciled()
        launches, lanes = _batch_counters()
        assert launches == 1 and lanes == 2
        for s, b in zip(solo, results):
            _assert_same_release(s, b)


class TestWarmPath:

    def test_repeated_batch_shape_adds_zero_aot_retraces(self):
        rt_aot.global_cache().clear()
        jobs = [(f"tenant{i}", _agg_spec(130 + i), _rows(51 + i))
                for i in range(2)]

        def run():
            with DPAggregationService(pdp.TPUBackend(aot=True),
                                      max_concurrent_jobs=2,
                                      batching=True,
                                      batch_window_ms=2000.0,
                                      max_batch_jobs=2) as svc:
                handles = [svc.submit(t, s, r) for t, s, r in jobs]
                return [h.result(timeout=300) for h in handles]

        run()  # warms the lane-stacked executable for this shape-class
        before = telemetry.snapshot()
        run()
        after = telemetry.snapshot()
        assert after.get("aot_cache_misses", 0) == before.get(
            "aot_cache_misses", 0), \
            "a repeated (spec, row bucket, lane bucket) batch must " \
            "reuse the cached lane-stacked executable"
        assert after.get("aot_cache_hits", 0) > before.get(
            "aot_cache_hits", 0)
        launches, _ = _batch_counters()
        assert launches >= 2


class TestObservability:

    def test_batch_metrics_export_and_spans(self):
        trace.enable()
        jobs = [(f"tenant{i}", _agg_spec(150 + i), _rows(61 + i))
                for i in range(3)]
        _run_service(jobs, batching=True)
        launches, lanes = _batch_counters()
        assert launches >= 1 and lanes == 3
        occupancy = telemetry.gauge_snapshot()["service_batch_occupancy"]
        assert occupancy[""] == 3.0  # process-level: the last launch
        parsed = obs.parse_prometheus(obs.render_prometheus())
        assert parsed["pdp_service_batch_launches"]["type"] == "counter"
        assert parsed["pdp_service_batch_launches"]["samples"][""] >= 1.0
        assert parsed["pdp_service_jobs_batched"]["samples"][""] == 3.0
        assert parsed["pdp_service_batch_occupancy"]["type"] == "gauge"
        assert parsed["pdp_service_batch_occupancy"]["samples"][""] == 3.0
        spans = [e for e in trace.to_trace_events()["traceEvents"]
                 if e["name"] == "batch_dispatch"]
        assert spans, "batch launches must be visible as trace spans"
        assert any(e["args"].get("lanes") == 3 for e in spans)


class TestKnobs:

    def test_batching_knob_rejections(self):
        backend = pdp.TPUBackend()
        with pytest.raises(ValueError, match="batching must be a bool"):
            DPAggregationService(backend, batching=1)
        with pytest.raises(ValueError, match="batch_window_ms"):
            DPAggregationService(backend, batching=True,
                                 batch_window_ms=0)
        with pytest.raises(ValueError, match="batch_window_ms"):
            DPAggregationService(backend, batching=True,
                                 batch_window_ms=float("inf"))
        with pytest.raises(ValueError, match="max_batch_jobs"):
            DPAggregationService(backend, batching=True, max_batch_jobs=1)
        with pytest.raises(ValueError, match="max_batch_jobs"):
            DPAggregationService(backend, batching=True,
                                 max_batch_jobs=2.5)


class TestCollectiveSerialization:
    """The service must bracket its worker pool with collective-launch
    serialization: concurrent meshed programs from two host threads can
    interleave their per-device rendezvous on the CPU backend and hang
    forever, and the guard must stand down when no service is live so
    single-threaded meshed callers keep XLA's async dispatch
    pipelining."""

    def test_service_lifetime_brackets_serialization(self):
        from pipelinedp_tpu.parallel import sharded

        def depth():
            with sharded._COLLECTIVE_SERIALIZE_LOCK:
                return sharded._collective_serialize_depth

        base = depth()
        svc_a = DPAggregationService(pdp.TPUBackend())
        assert depth() == base + 1
        with DPAggregationService(pdp.TPUBackend()):
            assert depth() == base + 2  # refcounted across services
        assert depth() == base + 1
        svc_a.stop()
        assert depth() == base
        svc_a.stop()  # idempotent: a second stop must not double-drop
        assert depth() == base

    def test_unserialized_launch_skips_lock_and_drain(self):
        from pipelinedp_tpu.parallel import sharded

        calls = []
        with sharded._COLLECTIVE_SERIALIZE_LOCK:
            base = sharded._collective_serialize_depth
        assert base == 0, "no service live: the guard must stand down"
        assert sharded._collective_launch(lambda: calls.append(1) or 7) == 7
        assert calls == [1]
