"""Smoke tests: every example must run end to end."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    ["examples/movie_view_ratings/run_local.py", "--rows", "5000"],
    ["examples/restaurant_visits/run_private_api.py", "--rows", "1000"],
    ["examples/restaurant_visits/run_parameter_tuning.py", "--rows", "1000"],
]


@pytest.mark.parametrize("cmd", EXAMPLES, ids=lambda c: c[0])
def test_example_runs(cmd):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable] + cmd, cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.strip(), "example produced no output"
