"""Smoke tests: every example must run end to end.

Examples run CPU-pinned for determinism; additionally, when a healthy
accelerator is reachable, the movie-ratings example re-runs on the actual
device path (fused TPUBackend) with no platform pin.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    ["examples/movie_view_ratings/run_local.py", "--rows", "5000"],
    [
        "examples/movie_view_ratings/run_without_frameworks.py",
        "--generate_rows", "5000", "--local"
    ],
    [
        "examples/movie_view_ratings/run_without_frameworks.py",
        "--generate_rows", "5000", "--pld_accounting", "--local"
    ],
    ["examples/restaurant_visits/run_private_api.py", "--rows", "1000"],
    ["examples/restaurant_visits/run_parameter_tuning.py", "--rows", "1000"],
    ["examples/codelab/codelab.py"],
    [
        "examples/movie_view_ratings/run_multihost_ingest.py",
        "--generate_rows", "5000", "--hosts", "3"
    ],
    ["examples/experimental/custom_combiners.py", "--generate_rows", "5000"],
    ["examples/quickstart.py", "--rows", "2000"],
    ["examples/service_demo.py", "--rows", "1000"],
]


@pytest.mark.parametrize("cmd", EXAMPLES,
                         ids=lambda c: " ".join([c[0]] + c[3:]))
def test_example_runs(cmd):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable] + cmd, cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.strip(), "example produced no output"


FRAMEWORK_EXAMPLES = [
    ["examples/movie_view_ratings/run_on_beam.py", "--generate_rows", "5000"],
    [
        "examples/movie_view_ratings/run_on_spark.py", "--generate_rows",
        "5000"
    ],
    ["examples/experimental/beam_combine_fn.py", "--generate_rows", "5000"],
]

# Success marker each framework script prints (default: the shared
# count+sum line of the movie_view_ratings scripts).
FRAMEWORK_MARKERS = {
    "examples/experimental/beam_combine_fn.py": "movies; first 3:",
}


@pytest.mark.parametrize("cmd", FRAMEWORK_EXAMPLES, ids=lambda c: c[0])
def test_framework_example_runs(cmd):
    """Beam/Spark example scripts over the in-memory fake runners."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = os.path.join(REPO, "tests", "fake_runners")
    proc = subprocess.run([sys.executable] + cmd, cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    marker = FRAMEWORK_MARKERS.get(cmd[0], "computed DP count+sum")
    assert marker in proc.stdout


def _accelerator_platform():
    """Probes (in a killable subprocess) for a healthy non-CPU device."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=90, env=env)
    except subprocess.TimeoutExpired:
        return None
    if probe.returncode != 0 or not probe.stdout.strip():
        return None
    platform = probe.stdout.strip().splitlines()[-1]
    return platform if platform != "cpu" else None


@pytest.mark.slow
def test_movie_example_on_device():
    """The real-file-format example on the actual device path (TPU smoke).

    `slow`: on an accelerator-less tier-1 box the probe subprocess
    burns its full 90s timeout just to decide to skip; the example
    itself is covered on CPU by the `--local` parametrization above.
    """
    platform = _accelerator_platform()
    if platform is None:
        pytest.skip("no healthy accelerator reachable")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    proc = subprocess.run(
        [sys.executable,
         "examples/movie_view_ratings/run_without_frameworks.py",
         "--generate_rows", "20000"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=480)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "computed DP metrics" in proc.stdout
