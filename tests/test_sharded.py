"""Multi-chip sharding tests on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.parallel import make_mesh, shard_rows_by_pid

HUGE_EPS = 1e7

ROWS = [("u%d" % (i % 50), "pk%d" % (i % 7), float(i % 5))
        for i in range(1000)]

EXTRACTORS = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                partition_extractor=lambda r: r[1],
                                value_extractor=lambda r: r[2])


def _aggregate(backend, rows, params, public=None, eps=HUGE_EPS):
    accountant = pdp.NaiveBudgetAccountant(total_epsilon=eps,
                                           total_delta=1e-5)
    engine = pdp.DPEngine(accountant, backend)
    result = engine.aggregate(rows, params, EXTRACTORS, public)
    accountant.compute_budgets()
    return dict(result)


class TestShardRows:

    def test_shard_rows_by_pid_colocates_and_pads(self):
        pid = np.arange(100, dtype=np.int32)
        pk = np.zeros(100, dtype=np.int32)
        values = np.ones(100)
        valid = np.ones(100, dtype=bool)
        spid, spk, svalues, svalid = shard_rows_by_pid(
            pid, pk, values, valid, 8)
        assert len(spid) % 8 == 0
        per_shard = len(spid) // 8
        # Every privacy id's rows land on exactly one shard.
        shard_of = {}
        for s in range(8):
            block_pid = spid[s * per_shard:(s + 1) * per_shard]
            block_valid = svalid[s * per_shard:(s + 1) * per_shard]
            for p in block_pid[block_valid]:
                assert shard_of.setdefault(int(p), s) == s
        assert svalid.sum() == 100
        assert svalues[svalid].sum() == 100

    def test_all_rows_one_pid(self):
        pid = np.zeros(10, dtype=np.int32)
        spid, spk, sval, svalid = shard_rows_by_pid(pid, pid, pid.astype(
            float), np.ones(10, bool), 4)
        assert svalid.sum() == 10

    def test_skewed_pids_bounded_padding(self):
        # Zipf-ish skew: a few very hot ids plus a long tail. The two-phase
        # balancing (greedy LPT for heavy ids, serpentine tail) must keep
        # total padded size < 1.2x the ideal equal-split layout (the old
        # pid%n scheme + pow2 rounding could inflate this past 2x).
        rng = np.random.default_rng(0)
        n_ids = 2000
        counts = (rng.zipf(1.5, n_ids) % 500 + 1)
        pid = np.repeat(np.arange(n_ids, dtype=np.int32), counts)
        n = len(pid)
        pk = rng.integers(0, 16, n).astype(np.int32)
        spid, _, _, svalid = shard_rows_by_pid(pid, pk, np.ones(n),
                                               np.ones(n, bool), 8)
        ideal = 8 * (-(-n // 8))
        assert len(spid) < 1.2 * ideal, (len(spid), ideal)
        assert svalid.sum() == n

    def test_one_dominant_pid_padding(self):
        # One id holds half the rows; its shard is irreducibly hot, but the
        # other shards must share the remainder evenly.
        n_tail = 7000
        pid = np.concatenate([
            np.zeros(7000, dtype=np.int32),
            np.arange(1, 1 + n_tail, dtype=np.int32)
        ])
        n = len(pid)
        spid, _, _, svalid = shard_rows_by_pid(pid, pid, np.ones(n),
                                               np.ones(n, bool), 8)
        # Capacity is set by the hot shard (7000 rows) with <=12.5% slack.
        assert len(spid) <= 8 * 7000 * 1.125
        assert svalid.sum() == n


class TestShardedEngineParity:

    @pytest.mark.parametrize("n_devices", [1, 4, 8])
    def test_count_sum_matches_local(self, n_devices):
        mesh = make_mesh(n_devices=n_devices)
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM,
                     pdp.Metrics.PRIVACY_ID_COUNT],
            max_partitions_contributed=7,
            max_contributions_per_partition=30,
            min_value=0.0,
            max_value=5.0)
        public = ["pk%d" % i for i in range(7)]
        expected = _aggregate(pdp.LocalBackend(seed=0), ROWS, params, public)
        actual = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=0), ROWS,
                            params, public)
        assert set(actual) == set(expected)
        for pk in expected:
            assert actual[pk].count == pytest.approx(expected[pk].count,
                                                     abs=0.05)
            assert actual[pk].sum == pytest.approx(expected[pk].sum, abs=0.05)
            assert actual[pk].privacy_id_count == pytest.approx(
                expected[pk].privacy_id_count, abs=0.05)

    def test_private_selection_sharded(self):
        mesh = make_mesh(n_devices=8)
        rows = [(f"u{i}", "big", 1.0) for i in range(2000)]
        rows += [("solo", "tiny", 1.0)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=1,
                                     max_contributions_per_partition=1)
        result = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=1), rows,
                            params)
        assert "big" in result
        assert "tiny" not in result
        assert result["big"].count == pytest.approx(2000, abs=0.1)

    def test_l0_bounding_across_shards(self):
        # One privacy id with rows in many partitions: bounding must treat
        # them globally (all rows co-located on one shard).
        mesh = make_mesh(n_devices=8)
        rows = [("hot_user", f"pk{i}", 1.0) for i in range(16)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=4,
                                     max_contributions_per_partition=1)
        public = [f"pk{i}" for i in range(16)]
        result = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=2), rows,
                            params, public)
        total = sum(result[pk].count for pk in public)
        assert total == pytest.approx(4, abs=0.05)

    def test_mean_sharded(self):
        mesh = make_mesh(n_devices=4)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.MEAN],
                                     max_partitions_contributed=7,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=5.0)
        public = ["pk%d" % i for i in range(7)]
        expected = _aggregate(pdp.LocalBackend(seed=0), ROWS, params, public)
        actual = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=3), ROWS,
                            params, public)
        for pk in expected:
            assert actual[pk].mean == pytest.approx(expected[pk].mean,
                                                    abs=0.01)

    def test_variance_sharded(self):
        mesh = make_mesh(n_devices=4)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VARIANCE,
                                              pdp.Metrics.MEAN],
                                     max_partitions_contributed=7,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=5.0)
        public = ["pk%d" % i for i in range(7)]
        expected = _aggregate(pdp.LocalBackend(seed=0), ROWS, params, public)
        actual = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=3), ROWS,
                            params, public)
        for pk in expected:
            assert actual[pk].variance == pytest.approx(
                expected[pk].variance, abs=0.05)
            assert actual[pk].mean == pytest.approx(expected[pk].mean,
                                                    abs=0.01)

    def test_secure_release_sharded(self):
        # Secure (snapped discrete) release must survive the psum'd
        # multi-chip path with the same huge-eps values as LocalBackend.
        mesh = make_mesh(n_devices=4)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     noise_kind=pdp.NoiseKind.LAPLACE,
                                     max_partitions_contributed=7,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=5.0)
        public = ["pk%d" % i for i in range(7)]
        expected = _aggregate(pdp.LocalBackend(seed=0), ROWS, params, public)
        actual = _aggregate(
            pdp.TPUBackend(mesh=mesh, noise_seed=5, secure_noise=True), ROWS,
            params, public)
        for pk in expected:
            assert actual[pk].count == pytest.approx(expected[pk].count,
                                                     abs=0.05)
            assert actual[pk].sum == pytest.approx(expected[pk].sum,
                                                   abs=0.05)

    def test_percentile_sharded(self):
        # Values spread across shards must merge into one global tree.
        mesh = make_mesh(n_devices=8)
        rows = [("u%d" % i, "A", float(i % 100)) for i in range(800)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(25),
                     pdp.Metrics.PERCENTILE(75)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=100.0)
        result = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=5), rows,
                            params, ["A"])
        assert result["A"].percentile_25 == pytest.approx(25.0, abs=2.0)
        assert result["A"].percentile_75 == pytest.approx(75.0, abs=2.0)

    def test_percentile_sharded_multichunk(self, monkeypatch):
        # Forces quantile_chunk=2 so quantile_outputs dispatches to the
        # LAZY descent (executor._lazy_quantile_outputs) under shard_map —
        # its per-level psum of [P, B] child counts is the collective that
        # would otherwise only be exercised on real meshes.
        import dataclasses
        from pipelinedp_tpu import executor
        orig = executor.make_kernel_config

        def forced_chunk(*a, **kw):
            cfg = orig(*a, **kw)
            return dataclasses.replace(cfg, quantile_chunk=2)

        monkeypatch.setattr(executor, "make_kernel_config", forced_chunk)
        mesh = make_mesh(n_devices=8)
        rows = [("u%d" % i, "pk%d" % (i % 5), float(i % 100))
                for i in range(1000)]
        params = pdp.AggregateParams(
            metrics=[pdp.Metrics.PERCENTILE(50)],
            max_partitions_contributed=1,
            max_contributions_per_partition=1,
            min_value=0.0,
            max_value=100.0)
        public = ["pk%d" % i for i in range(5)]
        result = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=6), rows,
                            params, public)
        assert set(result) == set(public)
        for pk in public:
            assert 30.0 <= result[pk].percentile_50 <= 70.0

    def test_vector_sum_sharded(self):
        mesh = make_mesh(n_devices=8)
        rows = [("u%d" % (i % 50), "pk%d" % (i % 3),
                 np.array([float(i % 5), 1.0])) for i in range(300)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=100,
                                     vector_norm_kind=pdp.NormKind.Linf,
                                     vector_max_norm=1000.0,
                                     vector_size=2)
        public = ["pk0", "pk1", "pk2"]
        expected = _aggregate(pdp.LocalBackend(seed=0), rows, params, public)
        actual = _aggregate(pdp.TPUBackend(mesh=mesh, noise_seed=4), rows,
                            params, public)
        for pk in public:
            np.testing.assert_allclose(actual[pk].vector_sum,
                                       expected[pk].vector_sum, atol=0.1)


class TestMultiProcBackend:

    def test_engine_e2e_on_multiproc(self):
        backend = pdp.MultiProcLocalBackend(n_jobs=2)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=2)
        rows = [("u1", "A", 1.0), ("u2", "A", 1.0), ("u1", "B", 1.0)]
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        result = engine.aggregate(rows, params, EXTRACTORS, ["A", "B"])
        accountant.compute_budgets()
        result = dict(result)
        assert result["A"].count == pytest.approx(2, abs=0.01)
        assert result["B"].count == pytest.approx(1, abs=0.01)


class TestMaxPartitionsKnob:

    def test_max_partitions_pads_and_decodes(self):
        backend = pdp.TPUBackend(max_partitions=64, noise_seed=0)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=2)
        rows = [("u1", "A", 1.0), ("u2", "B", 1.0)]
        result = _aggregate(backend, rows, params, ["A", "B"])
        assert set(result) == {"A", "B"}

    def test_max_partitions_too_small_raises(self):
        backend = pdp.TPUBackend(max_partitions=1, noise_seed=0)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=2)
        rows = [("u1", "A", 1.0), ("u2", "B", 1.0)]
        with pytest.raises(ValueError, match="max_partitions"):
            _aggregate(backend, rows, params, ["A", "B"])


class TestShardedSelectPartitions:

    @staticmethod
    def _select(backend, rows, l0=30):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-5)
        engine = pdp.DPEngine(accountant, backend)
        params = pdp.SelectPartitionsParams(max_partitions_contributed=l0)
        result = engine.select_partitions(rows, params, EXTRACTORS)
        accountant.compute_budgets()
        return set(result)

    def test_select_partitions_mesh_matches_local(self):
        # Every partition has many distinct users and l0 does not bind, so
        # huge-eps selection is deterministic on every path.
        rng = np.random.default_rng(11)
        rows = [(f"u{i % 120}", f"pk{k}", 0.0)
                for i, k in enumerate(rng.integers(0, 20, size=4000))]
        mesh = make_mesh(n_devices=8)
        expected = self._select(pdp.LocalBackend(seed=0), rows)
        assert self._select(pdp.TPUBackend(mesh=mesh, noise_seed=3),
                            rows) == expected
        assert len(expected) == 20

    def test_select_partitions_mesh_drops_small(self):
        mesh = make_mesh(n_devices=4)
        rows = [(f"u{i}", "big", 0.0) for i in range(2000)]
        rows += [("solo", "tiny", 0.0)]
        got = self._select(pdp.TPUBackend(mesh=mesh, noise_seed=5), rows,
                           l0=2)
        assert got == {"big"}

    def test_sharded_counts_match_single_device(self):
        # Count-stage parity: psum of shard-local counts == single-device
        # counts when l0 does not bind (no sampling randomness involved).
        import jax
        from pipelinedp_tpu import executor
        from pipelinedp_tpu.parallel import sharded
        from pipelinedp_tpu.ops import selection_ops

        rng = np.random.default_rng(7)
        n, P = 5000, 40
        pid = rng.integers(0, 200, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        valid = np.ones(n, bool)
        selection = selection_ops.SelectionParams(kind=1, pre_shift=0,
                                                  threshold=10.5,
                                                  scale=1e-12)
        mesh = make_mesh(n_devices=8)
        keep_mesh = np.asarray(
            sharded.sharded_select_partitions(mesh, pid, pk, valid,
                                              jax.random.PRNGKey(0), P, P,
                                              selection))
        keep_single = np.asarray(
            executor.select_partitions_kernel(pid, pk, valid,
                                              jax.random.PRNGKey(0), P, P,
                                              selection))
        # Deterministic threshold selection: both reduce to count >= 10.5.
        expected = np.array([
            len({p for p, k in zip(pid, pk) if k == j}) >= 11
            for j in range(P)
        ])
        assert (keep_mesh == expected).all()
        assert (keep_single == expected).all()


class TestShardedBlockedLargeP:
    """Mesh-sharded blocked large-P path (aggregate_blocked_sharded)."""

    @staticmethod
    def _spec(P, **kw):
        from tests.test_large_p import _spec
        return _spec(P, **kw)

    @staticmethod
    def _data(n, n_ids, P, seed=0):
        rng = np.random.default_rng(seed)
        pid = rng.integers(0, n_ids, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        values = rng.uniform(0, 5, n)
        return pid, pk, values, np.ones(n, bool)

    @pytest.mark.parametrize("n_devices", [1, 8])
    def test_public_noise_free_exact_parity(self, n_devices):
        # Multiple blocks, no selection, zero noise: the sharded blocked
        # result must EXACTLY match the single-device blocked path and the
        # raw numpy aggregate.
        import jax
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=n_devices)
        P = 1000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = self._spec(
            P, private=False, l0=P, linf=64)
        stds = np.zeros_like(np.asarray(stds))
        pid, pk, values, valid = self._data(20_000, 500, P)
        key = jax.random.PRNGKey(0)
        kept, outputs = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=128)
        ref_kept, ref_outputs = large_p.aggregate_blocked(
            pid, pk, values, valid, min_v, max_v, min_s, max_s, mid, stds,
            key, cfg, block_partitions=128)
        assert list(kept) == list(range(P))
        assert list(ref_kept) == list(kept)
        expected_count = np.bincount(pk, minlength=P)
        expected_sum = np.bincount(pk, weights=np.clip(values, 0, 5),
                                   minlength=P)
        np.testing.assert_allclose(outputs["count"], expected_count,
                                   atol=1e-4)
        np.testing.assert_allclose(outputs["sum"], expected_sum, rtol=1e-5)
        np.testing.assert_allclose(outputs["sum"], ref_outputs["sum"],
                                   rtol=1e-5)

    def test_private_selection_across_blocks(self):
        # Dense partitions in first/middle/last block kept, single-id
        # partitions dropped — decisions deterministic at huge eps, so the
        # kept set must equal the single-device blocked path's.
        import jax
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=8)
        P = 300
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = self._spec(
            P, l0=20, linf=4, eps=30)
        stds = np.zeros_like(np.asarray(stds))
        rows = []
        for p in list(range(10)) + [150] + list(range(290, 300)):
            for u in range(200):
                rows.append((u * 100_003 + p, p))
        for i, p in enumerate(range(20, 280, 13)):
            rows.append((50_000_000 + i, p))
        pid = np.array([r[0] for r in rows], np.int64)
        pk = np.array([r[1] for r in rows], np.int32)
        values = np.ones(len(rows))
        valid = np.ones(len(rows), bool)
        key = jax.random.PRNGKey(3)
        kept, outputs = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=64)
        ref_kept, _ = large_p.aggregate_blocked(
            pid, pk, values, valid, min_v, max_v, min_s, max_s, mid, stds,
            key, cfg, block_partitions=64)
        expected = set(list(range(10)) + [150] + list(range(290, 300)))
        assert set(kept.tolist()) == expected
        assert set(ref_kept.tolist()) == expected
        # Noise-free counts: l0=20 does not bind (each id hits one
        # partition), so kept counts equal the raw per-partition bincount
        # (partition 150 also catches one sparse row: 201).
        truth = np.bincount(pk, minlength=P)
        np.testing.assert_allclose(outputs["count"], truth[kept], atol=1e-4)

    @pytest.mark.slow
    def test_percentile_blocked_sharded(self):
        # Per-block lazy quantile descent over the mesh: the [C, B]
        # child-count psum inside quantile_outputs is the collective under
        # test. Noise-free medians must land within leaf width of numpy.
        # `slow`: ~4 min of wall alone on the CPU tier-1 box — the
        # descent's per-level dispatches dominate; the same collective
        # is covered fast by test_percentile_sharded (dense route) and
        # test_percentile_blocked_matches_dense (blocked, single
        # device), so tier-1 keeps both halves of the composition.
        import jax
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=8)
        P = 3000
        metrics = [pdp.Metrics.COUNT, pdp.Metrics.PERCENTILE(50)]
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = self._spec(
            P, private=False, metrics_list=metrics, l0=P, linf=64)
        stds = np.zeros_like(np.asarray(stds))
        rng = np.random.default_rng(5)
        n = 30_000
        pid = rng.integers(0, 400, n).astype(np.int32)
        pk = rng.integers(0, 40, n).astype(np.int32) * 75  # spread blocks
        values = rng.uniform(0, 5, n)
        valid = np.ones(n, bool)
        kept, outputs = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, jax.random.PRNGKey(2), cfg, block_partitions=256)
        leaf = (max_v - min_v) / (cfg.branching**cfg.tree_height)
        kept_list = kept.tolist()
        for p in range(0, 3000, 75):
            j = kept_list.index(p)
            true_median = np.quantile(values[pk == p], 0.5,
                                      method="inverted_cdf")
            assert abs(outputs["percentile_50"][j] -
                       true_median) < 3 * leaf + 0.05

    def test_mean_variance_engine_meshed_blocked(self):
        # MEAN/VARIANCE children (count+sum+sum-of-squares columns) through
        # the meshed blocked route vs LocalBackend at huge eps.
        mesh = make_mesh(n_devices=8)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.MEAN,
                                              pdp.Metrics.VARIANCE],
                                     max_partitions_contributed=7,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=5.0)
        public = ["pk%d" % i for i in range(7)]
        expected = _aggregate(pdp.LocalBackend(seed=0), ROWS, params, public)
        actual = _aggregate(
            pdp.TPUBackend(mesh=mesh, noise_seed=3,
                           large_partition_threshold=4), ROWS, params,
            public)
        for pk in expected:
            assert actual[pk].mean == pytest.approx(expected[pk].mean,
                                                    abs=0.01)
            assert actual[pk].variance == pytest.approx(
                expected[pk].variance, abs=0.05)

    # `slow`: ~30s whole-path sweep. Exact-parity coverage stays in
    # tier-1 via test_public_noise_free_exact_parity[1|8] and the
    # single-device blocked parity tests; this adds the probabilistic-
    # eps L0-not-binding regime on top.
    @pytest.mark.slow
    def test_exact_parity_when_l0_not_binding(self):
        # Whole-path equivalence at probabilistic eps: when L0 sampling
        # never binds (the only per-shard randomness), per-partition
        # counts are identical across paths, so the shared per-block
        # selection keys must give the EXACT same kept set, counts and
        # sums — even where individual keep decisions are coin flips.
        # (Multi-block with skipped empty blocks; the same property was
        # hand-verified at P=10^7 — scale does not change it.)
        import jax
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=8)
        P = 100_000
        cfg, stds, (min_v, max_v, min_s, max_s, mid) = self._spec(
            P, l0=64, linf=8, eps=30)
        stds = np.zeros_like(np.asarray(stds))
        rng = np.random.default_rng(1)
        n = 50_000
        pid = rng.integers(0, 10_000, n).astype(np.int64)
        pk = (np.power(rng.random(n), 6.0) * P).astype(np.int32)
        valid = np.ones(n, bool)
        values = rng.uniform(0, 5, n)
        key = jax.random.PRNGKey(2)
        kept, outputs = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            stds, key, cfg, block_partitions=1 << 14)
        ref_kept, ref_out = large_p.aggregate_blocked(
            pid, pk, values, valid, min_v, max_v, min_s, max_s, mid, stds,
            key, cfg, block_partitions=1 << 14)
        assert len(kept) > 0
        assert np.array_equal(kept, ref_kept)
        np.testing.assert_allclose(outputs["count"], ref_out["count"],
                                   atol=1e-3)
        np.testing.assert_allclose(outputs["sum"], ref_out["sum"],
                                   rtol=1e-4)

    def test_streamed_ingest_through_meshed_blocked(self):
        # Device-resident EncodedData (streamed ingest) through the
        # meshed blocked engine route: columns reshard on device (the
        # collective all_to_all path, tests/test_reshard.py) and the
        # result must match the row-input LocalBackend path.
        from pipelinedp_tpu import ingest
        rows = ROWS
        chunks = [(np.array([r[0] for r in rows[i:i + 300]], object),
                   np.array([r[1] for r in rows[i:i + 300]], object),
                   np.array([r[2] for r in rows[i:i + 300]]))
                  for i in range(0, len(rows), 300)]
        encoded = ingest.stream_encode_columns(iter(chunks))
        mesh = make_mesh(n_devices=8)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=7,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=5.0)
        expected = _aggregate(pdp.LocalBackend(seed=0), rows, params)
        actual = _aggregate(
            pdp.TPUBackend(mesh=mesh, noise_seed=0,
                           large_partition_threshold=4), encoded, params)
        assert set(actual) == set(expected)
        for pk in expected:
            assert actual[pk].count == pytest.approx(expected[pk].count,
                                                     abs=0.05)
            assert actual[pk].sum == pytest.approx(expected[pk].sum,
                                                   abs=0.05)

    def test_vector_sum_engine_meshed_blocked(self):
        # VECTOR_SUM through the meshed blocked route (per-dim scalar
        # columns ride the pass-1 payload sort; the [C]-block reduce keeps
        # vector_size).
        mesh = make_mesh(n_devices=8)
        rows = [("u%d" % (i % 50), "pk%d" % (i % 3),
                 np.array([float(i % 5), 1.0])) for i in range(300)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.VECTOR_SUM],
                                     max_partitions_contributed=3,
                                     max_contributions_per_partition=100,
                                     vector_norm_kind=pdp.NormKind.Linf,
                                     vector_max_norm=1000.0,
                                     vector_size=2)
        public = ["pk0", "pk1", "pk2"]
        expected = _aggregate(pdp.LocalBackend(seed=0), rows, params, public)
        actual = _aggregate(
            pdp.TPUBackend(mesh=mesh, noise_seed=4,
                           large_partition_threshold=1), rows, params,
            public)
        for pk in public:
            np.testing.assert_allclose(actual[pk].vector_sum,
                                       expected[pk].vector_sum, atol=0.1)

    def test_secure_blocked_sharded(self):
        # Secure snapped release through the MESHED blocked path: outputs
        # on the secure grid, equal to the single-device blocked secure
        # outputs' grid, matching the raw aggregate to grid resolution.
        import dataclasses as dc
        import jax
        import jax.numpy as jnp
        from pipelinedp_tpu import executor
        from pipelinedp_tpu.ops import secure_noise
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=4)
        P = 300
        cfg, stds, (min_v, max_v, min_s, max_s,
                    mid), params, compound = self._spec(P, private=False,
                                                        l0=P, linf=64,
                                                        eps=1e6, full=True)
        cfg = dc.replace(cfg, secure=True)
        sens = executor.compute_noise_sensitivities(compound, params)
        thr_hi, thr_lo, gran = secure_noise.build_tables(
            np.asarray(stds), pdp.NoiseKind.LAPLACE, sensitivities=sens)
        tables = (jnp.asarray(thr_hi), jnp.asarray(thr_lo),
                  jnp.asarray(gran))
        rng = np.random.default_rng(6)
        n = 10_000
        pid = rng.integers(0, 300, n).astype(np.int32)
        pk = rng.integers(0, P, n).astype(np.int32)
        values = rng.uniform(0, 5, n)
        valid = np.ones(n, bool)
        kept, outputs = large_p.aggregate_blocked_sharded(
            mesh, pid, pk, values, valid, min_v, max_v, min_s, max_s, mid,
            np.asarray(stds), jax.random.PRNGKey(3), cfg,
            block_partitions=128, secure_tables=tables)
        expected = np.bincount(pk, minlength=P)
        np.testing.assert_allclose(outputs["count"], expected, atol=0.5)
        g = float(gran[0])
        ratios = outputs["count"] / g
        np.testing.assert_allclose(ratios, np.round(ratios), atol=1e-3)

    def test_select_partitions_blocked_sharded_matches_single(self):
        # Mesh + blocked standalone selection: kept set must equal the
        # single-device blocked path's at huge eps (deterministic
        # decisions), across block boundaries.
        import jax
        from pipelinedp_tpu.ops import selection_ops
        from pipelinedp_tpu.parallel import large_p
        mesh = make_mesh(n_devices=8)
        P, l0 = 300, 30
        rows = []
        for p in list(range(10)) + [150] + list(range(290, 300)):
            for u in range(60):
                rows.append((u * 100_003 + p, p))
        for i, p in enumerate(range(21, 280, 13)):
            rows.append((50_000_000 + i, p))
        pid = np.array([r[0] for r in rows], np.int64)
        pk = np.array([r[1] for r in rows], np.int32)
        valid = np.ones(len(rows), bool)
        sel = selection_ops.selection_params_from_host(
            pdp.PartitionSelectionStrategy.TRUNCATED_GEOMETRIC, 1e7, 1e-5,
            l0, None)
        key = jax.random.PRNGKey(5)
        kept = large_p.select_partitions_blocked_sharded(
            mesh, pid, pk, valid, key, l0, P, sel, block_partitions=64)
        ref = large_p.select_partitions_blocked(pid, pk, valid, key, l0, P,
                                                sel, block_partitions=64)
        expected = sorted(list(range(10)) + [150] + list(range(290, 300)))
        assert kept.tolist() == expected
        assert ref.tolist() == expected

    def test_select_partitions_engine_meshed_blocked_route(self):
        # TPUBackend(mesh, threshold below P): standalone selection must
        # route through the sharded blocked path and match LocalBackend.
        rng = np.random.default_rng(11)
        rows = [(f"u{i % 120}", f"pk{k}", 0.0)
                for i, k in enumerate(rng.integers(0, 20, size=4000))]
        mesh = make_mesh(n_devices=8)

        def run(backend):
            accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                                   total_delta=1e-5)
            engine = pdp.DPEngine(accountant, backend)
            params = pdp.SelectPartitionsParams(max_partitions_contributed=30)
            result = engine.select_partitions(rows, params, EXTRACTORS)
            accountant.compute_budgets()
            return set(result)

        expected = run(pdp.LocalBackend(seed=0))
        assert run(
            pdp.TPUBackend(mesh=mesh, noise_seed=3,
                           large_partition_threshold=8)) == expected
        assert len(expected) == 20

    def test_engine_routes_meshed_blocked(self):
        # TPUBackend(mesh, large_partition_threshold below P) must route
        # through the sharded blocked path and agree with LocalBackend.
        mesh = make_mesh(n_devices=8)
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT,
                                              pdp.Metrics.SUM],
                                     max_partitions_contributed=7,
                                     max_contributions_per_partition=30,
                                     min_value=0.0,
                                     max_value=5.0)
        public = ["pk%d" % i for i in range(7)]
        expected = _aggregate(pdp.LocalBackend(seed=0), ROWS, params, public)
        actual = _aggregate(
            pdp.TPUBackend(mesh=mesh, noise_seed=0,
                           large_partition_threshold=4), ROWS, params,
            public)
        assert set(actual) == set(expected)
        for pk in expected:
            assert actual[pk].count == pytest.approx(expected[pk].count,
                                                     abs=0.05)
            assert actual[pk].sum == pytest.approx(expected[pk].sum,
                                                   abs=0.05)
