"""Tests for the legacy utility-analysis (peeker) package."""

import pytest

import pipelinedp_tpu as pdp
from pipelinedp_tpu.utility_analysis import (DataPeeker, PeekerEngine,
                                             SampleParams,
                                             aggregate_sketch_true,
                                             non_private_combiners)

HUGE_EPS = 1e7

# rows: (uid, partition, value)
ROWS = [
    ("u1", "pk0", 1.0),
    ("u1", "pk0", 2.0),
    ("u1", "pk1", 3.0),
    ("u2", "pk0", 4.0),
    ("u2", "pk1", 1.0),
    ("u3", "pk0", 2.0),
]

EXTRACTORS = pdp.DataExtractors(privacy_id_extractor=lambda r: r[0],
                                partition_extractor=lambda r: r[1],
                                value_extractor=lambda r: r[2])


def backend():
    return pdp.LocalBackend(seed=3)


class TestNonPrivateCombiners:

    def test_compound_count_sum(self):
        combiner = non_private_combiners.create_compound_combiner(
            [pdp.Metrics.COUNT, pdp.Metrics.SUM])
        acc1 = combiner.create_accumulator([1.0, 2.0])
        acc2 = combiner.create_accumulator([3.0])
        merged = combiner.merge_accumulators(acc1, acc2)
        assert combiner.compute_metrics(merged) == [3, 6.0]

    def test_mean_variance(self):
        combiner = non_private_combiners.create_compound_combiner(
            [pdp.Metrics.MEAN, pdp.Metrics.VARIANCE])
        acc = combiner.create_accumulator([1.0, 2.0, 3.0])
        mean_t, var_t = combiner.compute_metrics(acc)
        assert mean_t.mean == pytest.approx(2.0)
        assert var_t.variance == pytest.approx(2.0 / 3)

    def test_duplicate_metrics_rejected(self):
        with pytest.raises(ValueError, match="same"):
            non_private_combiners.CompoundCombiner(
                [non_private_combiners.RawCountCombiner(),
                 non_private_combiners.RawCountCombiner()])


class TestDataPeeker:

    def test_sketch_count(self):
        peeker = DataPeeker(backend())
        params = SampleParams(number_of_sampled_partitions=10,
                              metrics=[pdp.Metrics.COUNT])
        sketches = sorted(peeker.sketch(ROWS, params, EXTRACTORS))
        # one sketch per (pk, pid): u1 contributes to pk0(2 rows),pk1(1);
        # u2 to pk0(1),pk1(1); u3 to pk0(1)
        assert sketches == sorted([("pk0", 2, 2), ("pk1", 1, 2),
                                   ("pk0", 1, 2), ("pk1", 1, 2),
                                   ("pk0", 1, 1)])

    def test_sketch_requires_single_count_or_sum(self):
        peeker = DataPeeker(backend())
        with pytest.raises(ValueError, match="COUNT or SUM"):
            list(
                peeker.sketch(
                    ROWS,
                    SampleParams(number_of_sampled_partitions=1,
                                 metrics=[pdp.Metrics.MEAN]), EXTRACTORS))

    def test_sample_restricts_partitions(self):
        peeker = DataPeeker(backend())
        params = SampleParams(number_of_sampled_partitions=1)
        sampled = list(peeker.sample(ROWS, params, EXTRACTORS))
        pks = set(pk for _, pk, _ in sampled)
        assert len(pks) == 1
        # all rows of the sampled partition are present
        want = [r for r in ROWS if r[1] in pks]
        assert sorted(sampled) == sorted(want)

    def test_aggregate_true(self):
        peeker = DataPeeker(backend())
        params = SampleParams(number_of_sampled_partitions=10,
                              metrics=[pdp.Metrics.COUNT, pdp.Metrics.SUM])
        got = dict(peeker.aggregate_true(ROWS, params, EXTRACTORS))
        assert got["pk0"] == [4, 9.0]
        assert got["pk1"] == [2, 4.0]


class TestPeekerEngine:

    def test_aggregate_sketch_true(self):
        sketches = [("pk0", 2, 2), ("pk0", 1, 2), ("pk1", 3, 1)]
        got = dict(
            aggregate_sketch_true(backend(), sketches, pdp.Metrics.SUM))
        assert got == {"pk0": 3, "pk1": 3}
        got_count = dict(
            aggregate_sketch_true(backend(), sketches, pdp.Metrics.COUNT))
        assert got_count == {"pk0": 2, "pk1": 1}

    def test_aggregate_sketches_dp(self):
        accountant = pdp.NaiveBudgetAccountant(total_epsilon=HUGE_EPS,
                                               total_delta=1e-4)
        engine = PeekerEngine(accountant, backend())
        # 3 users in pk0 (values 2,1,2), 2 in pk1
        sketches = [("pk0", 2, 2), ("pk0", 1, 2), ("pk0", 2, 1),
                    ("pk1", 1, 2), ("pk1", 1, 2)]
        params = pdp.AggregateParams(metrics=[pdp.Metrics.COUNT],
                                     noise_kind=pdp.NoiseKind.LAPLACE,
                                     max_partitions_contributed=2,
                                     max_contributions_per_partition=3)
        result = engine.aggregate_sketches(sketches, params)
        accountant.compute_budgets()
        got = dict(result)
        # huge eps → everything kept, counts ≈ clipped per-user counts summed
        assert got["pk0"].count == pytest.approx(5, abs=0.1)
        assert got["pk1"].count == pytest.approx(2, abs=0.1)
