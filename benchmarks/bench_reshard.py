#!/usr/bin/env python
"""Meshed reshard benchmark: host-staged permutation vs on-device
all_to_all collective.

Times the two row-staging paths every meshed aggregation starts with
(parallel/reshard.stage_rows_to_mesh):

  * host-staged — the exact load-balanced host permutation
    (sharded.shard_rows_by_pid: greedy-LPT heavy ids + serpentine tail)
    followed by the sharded upload; timed from host numpy columns.
  * collective — pid-hash bucketize + [D, D] count exchange + one padded
    jax.lax.all_to_all + shard-local compaction
    (reshard.device_reshard_rows_by_pid); timed from device-resident
    columns (the streamed-ingest regime), which never touch the host.

Runs on the 8-device virtual CPU mesh by default (set --devices / run
under real devices for pod numbers). On the CPU mesh the "exchange" is a
memcpy, so the numbers bound the host-side permutation + staging overhead
the collective path deletes — NOT ICI bandwidth; on a pod the gap widens
by the host link / ICI bandwidth ratio. Prints ONE JSON line of
`meshed_reshard_*` keys (merged into bench.py's receipt detail).
"""

import argparse
import json
import os
import sys
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=1 << 20)
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--users", type=int, default=200_000)
    parser.add_argument("--reps", type=int, default=3)
    args = parser.parse_args()

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()
    # A single attached chip cannot exchange with itself; default to the
    # virtual CPU mesh (override by exporting JAX_PLATFORMS before running
    # on a real multi-device platform).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _common  # noqa: E402  (sibling import when run as a script)
    _common.path_setup()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pipelinedp_tpu.parallel import make_mesh, reshard

    devices = jax.devices()
    n_devices = min(args.devices, len(devices))
    mesh = make_mesh(devices=devices[:n_devices])

    n = args.rows
    rng = np.random.default_rng(17)
    pid = rng.integers(0, args.users, n).astype(np.int32)
    pk = rng.integers(0, 4096, n).astype(np.int32)
    values = rng.uniform(0, 5, n).astype(np.float32)
    valid = np.ones(n, bool)

    def sync(cols):
        _common.sync_fetch(list(cols), all_leaves=True)

    # --- Host-staged: permute on host, upload sharded. -------------------
    def run_host():
        out = reshard.stage_rows_to_mesh(mesh, pid, pk, values, valid,
                                         "host")
        sync(out)
        return out

    run_host()  # warm any lazy imports / upload paths
    host_sec = float("inf")
    for _ in range(args.reps):
        t0 = time.perf_counter()
        run_host()
        host_sec = min(host_sec, time.perf_counter() - t0)

    # --- Collective: device-resident columns, all_to_all over the mesh. --
    dev_cols = (jnp.asarray(pid), jnp.asarray(pk), jnp.asarray(values),
                jnp.asarray(valid))
    sync(dev_cols)

    def run_device():
        with reshard.forbid_row_fetches():
            out = reshard.stage_rows_to_mesh(mesh, *dev_cols, "device")
        sync(out)
        return out

    run_device()  # compile (bucketize/count/exchange kernels)
    dev_sec = float("inf")
    for _ in range(args.reps):
        t0 = time.perf_counter()
        run_device()
        dev_sec = min(dev_sec, time.perf_counter() - t0)

    print(
        json.dumps({
            "meshed_reshard_devices": n_devices,
            "meshed_reshard_rows": n,
            "meshed_reshard_host_staged_sec": round(host_sec, 4),
            "meshed_reshard_host_staged_rows_per_sec": round(n / host_sec),
            "meshed_reshard_collective_sec": round(dev_sec, 4),
            "meshed_reshard_collective_rows_per_sec": round(n / dev_sec),
            "meshed_reshard_collective_speedup": round(host_sec / dev_sec,
                                                       2),
            "meshed_reshard_platform": devices[0].platform,
        }))


if __name__ == "__main__":
    sys.exit(main())
