"""Sweep block_partitions for the device-resident blocked path.

Fewer blocks mean fewer per-block n_kept sync round trips (the dominant
residual term of the round-5 profile, ~64 ms each over the tunnel) but a
larger per-block finalize; this measures where the trade lands at
P = 10^7.  The round-5 session attempted this sweep and lost the tunnel
mid-compile — C = 2^20 remains the default until a window lands a
measurement (tpu_watch.sh runs this script automatically on recovery).
"""
import os
import time

import _common

_common.path_setup()

import jax  # noqa: E402

from pipelinedp_tpu.parallel import large_p  # noqa: E402

P = int(os.environ.get("BENCH_P", 10_000_000))
n = int(os.environ.get("BENCH_ROWS", 2**22))

_, cfg, stds, (min_v, max_v, min_s, max_s, mid) = _common.build_spec(P)
pid, pk, values, valid = _common.zipfish_data(n, P)
dev = [jax.device_put(c) for c in (pid, pk, values, valid)]
_common.sync_fetch(dev, all_leaves=True)  # block_until_ready no-ops

for C in (1 << 19, 1 << 20, 1 << 21, 1 << 22):

    def run(seed):
        return large_p.aggregate_blocked(*dev, min_v, max_v, min_s, max_s,
                                         mid, stds, jax.random.PRNGKey(seed),
                                         cfg, block_partitions=C)

    kept, _ = run(8)  # warm this C's block-kernel shapes
    t0 = time.perf_counter()
    kept, _ = run(9)
    t1 = time.perf_counter()
    print(f"C=2^{C.bit_length() - 1} blocks={-(-P // C)} kept={len(kept)} "
          f"{t1 - t0:.3f}s {n / (t1 - t0) / 1e3:.0f}K rows/s", flush=True)
