"""Phase timing of the main fused kernel: bounding sort vs reduce vs
finalize, sort key-count scaling, and payload-carry vs gather variants.

Round-3 findings (TPU v5e, 33.5M rows): the 5-key bounding sort is ~75%
of the bound phase; scans ~2%; the iota+gather variant was no better.
"""
import functools
import os
import time

import _common

_common.path_setup()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pipelinedp_tpu import executor  # noqa: E402

n = int(os.environ.get("BENCH_ROWS", 2**25))
P = int(os.environ.get("BENCH_P", 4096))

_, cfg, stds, (min_v, max_v, min_s, max_s, mid) = _common.build_spec(P)

key = jax.random.PRNGKey(0)


@jax.jit
def make(k):
    kp, ku, kv = jax.random.split(k, 3)
    u = jax.random.uniform(kp, (n,))
    pk = (jnp.power(u, 3.0) * P).astype(jnp.int32)
    pid = jax.random.randint(ku, (n,), 0, 1_000_000, dtype=jnp.int32)
    values = jax.random.uniform(kv, (n,), minval=0.0, maxval=5.0)
    return pid, pk, values, jnp.ones((n,), bool)


@jax.jit
def phase_bound(pid, pk, values, valid, k):
    spk, keep, pair, cols, _ = executor.bounded_row_columns(
        pid, pk, values, valid, min_v, max_v, min_s, max_s, mid, k, cfg)
    return spk, keep, pair, cols


@jax.jit
def phase_reduce(spk, keep, pair, cols):
    return executor.reduce_rows_to_partitions(spk, keep, pair, cols, P, 0)


@jax.jit
def phase_finalize(dense, k):
    return executor.finalize(dense, min_v, mid, jnp.asarray(stds), k, cfg)


@jax.jit
def sort_only(pid, pk, values, valid, k):
    # The 5-key bounding sort in isolation.
    key_total, key_linf, key_l0 = jax.random.split(k, 3)
    pk_sent = jnp.where(valid, pk, P).astype(jnp.int32)
    pid_sent = jnp.where(valid, pid, jnp.iinfo(jnp.int32).max)
    h0, h1 = executor._pair_hash(pid_sent, pk_sent, key_l0)
    rand = jax.random.uniform(key_linf, (n,))
    (spid, _, _, spk, _), pay = executor._sort_rows(
        [pid_sent, h0, h1, pk_sent, rand], [values, valid])
    return spid[0] + spk[-1]


_sync = _common.sync_fetch  # one-element host fetch; see its docstring


def timed(fn, *args, reps=3):
    out = fn(*args)
    _sync(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _sync(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


@functools.partial(jax.jit, static_argnames=("nkeys",))
def sort_scaling(pid, pk, values, valid, nkeys):
    cols = [pid, pk.astype(jnp.uint32),
            (pid * 7919).astype(jnp.uint32), values,
            (pk * 31).astype(jnp.float32)][:nkeys]
    out = jax.lax.sort(tuple(cols) + (values, valid), num_keys=nkeys)
    return out[0][0]


@jax.jit
def sort_gather_variant(pid, pk, values, valid, k):
    # Same 5 keys, but carry a row index and gather the payloads after —
    # narrower sort records vs two extra gather passes.
    key_total, key_linf, key_l0 = jax.random.split(k, 3)
    pk_sent = jnp.where(valid, pk, P).astype(jnp.int32)
    pid_sent = jnp.where(valid, pid, jnp.iinfo(jnp.int32).max)
    h0, h1 = executor._pair_hash(pid_sent, pk_sent, key_l0)
    rand = jax.random.uniform(key_linf, (n,))
    iota = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort((pid_sent, h0, h1, pk_sent, rand, iota), num_keys=5)
    perm = out[5]
    return out[0][0] + values[perm][0] + valid[perm][0]


@jax.jit
def cumsum_cost(values):
    from pipelinedp_tpu.ops import segment_ops
    return segment_ops.chunked_cumsum(values)[-1]


@jax.jit
def scans_cost(values, pk):
    # The scan bundle the bounding phase runs besides the sort.
    from pipelinedp_tpu.ops import segment_ops
    new = segment_ops.boundary_mask(pk)
    seg, rank = segment_ops.segment_starts_and_ids(new)
    nxt = segment_ops.next_segment_start(new)
    c = segment_ops.chunked_cumsum(values)
    return seg[-1] + rank[-1] + nxt[-1] + c[-1]


data = make(key)
_sync(data)

# Null baseline: dispatch + scalar-fetch round trip with no real compute
# (shared helper, min-of-3). Subtract this mentally from every number
# below; over the tunnel it is dominated by RTT and can swamp sub-100 ms
# phases.
print(f"null dispatch+fetch round trip: "
      f"{_common.null_roundtrip() * 1e3:.1f} ms", flush=True)

t_bound, bound = timed(phase_bound, *data, jax.random.fold_in(key, 1))
t_reduce, dense = timed(phase_reduce, *bound)
t_final, _ = timed(phase_finalize, dense, jax.random.fold_in(key, 2))
t_sort, _ = timed(sort_only, *data, jax.random.fold_in(key, 1))
print(f"rows={n}")
print(f"bound (sort5 + scans + clip): {t_bound*1e3:.0f} ms")
print(f"  of which bare 5-key sort:   {t_sort*1e3:.0f} ms")
print(f"reduce (1-key sort + cumsum): {t_reduce*1e3:.0f} ms")
print(f"finalize (select + noise):    {t_final*1e3:.0f} ms")
print(f"sum: {(t_bound+t_reduce+t_final)*1e3:.0f} ms "
      f"-> {n/(t_bound+t_reduce+t_final)/1e6:.1f}M rows/s", flush=True)

pid_, pk_, values_, valid_ = data
for nk in (1, 2, 3, 5):
    t_nk, _ = timed(sort_scaling, pid_, pk_, values_, valid_, nk)
    print(f"sort {nk} keys (+2 payload): {t_nk*1e3:.0f} ms", flush=True)
t_sg, _ = timed(sort_gather_variant, pid_, pk_, values_, valid_,
                jax.random.fold_in(key, 1))
print(f"sort 5 keys + iota, gather payloads after: {t_sg*1e3:.0f} ms",
      flush=True)
t_cs, _ = timed(cumsum_cost, values_)
print(f"chunked_cumsum: {t_cs*1e3:.1f} ms", flush=True)
t_sc, _ = timed(scans_cost, values_, pk_)
print(f"scan bundle (boundary+ranks+next+cumsum): {t_sc*1e3:.1f} ms",
      flush=True)


def time_packed_variants():
    """Key-packing experiment: (pid,h0)->i64 and (h1,pk)->i64 give a
    LOSSLESS 3-key sort with ordering identical to the 5-key original
    (all fields non-negative < 2^32, lexicographic order preserved by
    the shifts). TPU emulates int64 as register pairs, so comparator
    work per element is similar — the question the measurement answers
    is whether fewer lax.sort operands beat the packing overhead.

    Flips jax_enable_x64 globally (int64 is silently downcast without
    it); runs LAST in this script so earlier measurements keep the
    kernel's real f32/i32 dtypes."""
    jax.config.update("jax_enable_x64", True)
    try:

        @jax.jit
        def packed3(pid, pk, values, valid, k):
            _, key_linf, key_l0 = jax.random.split(k, 3)
            pk_sent = jnp.where(valid, pk, P).astype(jnp.int32)
            pid_sent = jnp.where(valid, pid, jnp.iinfo(jnp.int32).max)
            h0, h1 = executor._pair_hash(pid_sent, pk_sent, key_l0)
            rand = jax.random.uniform(key_linf, (n,), dtype=jnp.float32)
            # uint64, not int64: the high field spans the full uint32
            # range, and (h >= 2^31) << 32 would wrap a signed int64
            # negative — inverting the order vs the real sort's unsigned
            # uint32 comparisons.
            k1 = ((pid_sent.astype(jnp.uint32).astype(jnp.uint64) << 32)
                  | h0.astype(jnp.uint32).astype(jnp.uint64))
            k2 = ((h1.astype(jnp.uint32).astype(jnp.uint64) << 32)
                  | pk_sent.astype(jnp.uint32).astype(jnp.uint64))
            out = jax.lax.sort((k1, k2, rand, values, valid), num_keys=3)
            return out[0][0] + out[3][-1]

        @jax.jit
        def packed4(pid, pk, values, valid, k):
            # Half-packed: only (h0,h1) -> one i64 hash key.
            _, key_linf, key_l0 = jax.random.split(k, 3)
            pk_sent = jnp.where(valid, pk, P).astype(jnp.int32)
            pid_sent = jnp.where(valid, pid, jnp.iinfo(jnp.int32).max)
            h0, h1 = executor._pair_hash(pid_sent, pk_sent, key_l0)
            rand = jax.random.uniform(key_linf, (n,), dtype=jnp.float32)
            h64 = ((h0.astype(jnp.uint32).astype(jnp.uint64) << 32)
                   | h1.astype(jnp.uint32).astype(jnp.uint64))
            out = jax.lax.sort((pid_sent, h64, pk_sent, rand, values, valid),
                               num_keys=4)
            return out[0][0] + out[4][-1]

        for name, fn in (("3 keys (pid|h0, h1|pk, rand) i64-packed",
                          packed3),
                         ("4 keys (pid, h0|h1 i64, pk, rand)", packed4)):
            t, _ = timed(fn, pid_, pk_, values_, valid_,
                         jax.random.fold_in(key, 1))
            print(f"sort {name}: {t*1e3:.0f} ms", flush=True)
    finally:
        jax.config.update("jax_enable_x64", False)


time_packed_variants()
