"""Benchmark and profiling scripts (see README.md in this directory).

Importable as a package so bench.py at the repo root can share the spec
and data construction in benchmarks._common with the standalone scripts.
"""
